#!/usr/bin/env python
"""Regenerate ballista_pb2.py without protoc.

The container image carries no protoc / grpc_tools, so the generated
module cannot be rebuilt from ballista.proto the normal way. This script
instead treats the CHECKED-IN generated module as the carrier of the
serialized FileDescriptorProto, mutates that descriptor programmatically
(google.protobuf.descriptor_pb2 is a full in-memory model of a .proto
file), and re-emits the module. ballista.proto remains the human-readable
source of truth: every mutation made here must be mirrored there by hand.

Idempotent: additions are keyed by message/field name and skipped when
already present, so re-running is safe.

Usage: python dev/gen_proto_patch.py
"""

from __future__ import annotations

import ast
import os
import re

from google.protobuf import descriptor_pb2 as dp

HERE = os.path.dirname(os.path.abspath(__file__))
PB2 = os.path.join(HERE, "..", "ballista_tpu", "proto", "ballista_pb2.py")

F = dp.FieldDescriptorProto


def load_serialized_blob(path: str) -> bytes:
    """Pull the AddSerializedFile(b'...') literal out of the generated
    module WITHOUT importing it (importing would register the old file in
    the default descriptor pool and block re-registration)."""
    text = open(path).read()
    m = re.search(r"AddSerializedFile\(\s*(b(?:'|\").*?(?:'|\"))\s*\)", text,
                  re.DOTALL)
    if m is None:
        raise SystemExit(f"no AddSerializedFile literal in {path}")
    return ast.literal_eval(m.group(1))


def get_message(fdp: dp.FileDescriptorProto, name: str) -> dp.DescriptorProto:
    for msg in fdp.message_type:
        if msg.name == name:
            return msg
    raise SystemExit(f"message {name} not found")


def has_field(msg: dp.DescriptorProto, name: str) -> bool:
    return any(f.name == name for f in msg.field)


def has_message(fdp: dp.FileDescriptorProto, name: str) -> bool:
    return any(m.name == name for m in fdp.message_type)


def add_field(msg, name, number, ftype, *, type_name=None, repeated=False,
              oneof=None):
    if has_field(msg, name):
        return
    f = msg.field.add(
        name=name, number=number, type=ftype,
        label=F.LABEL_REPEATED if repeated else F.LABEL_OPTIONAL,
    )
    if type_name is not None:
        f.type_name = type_name
    if oneof is not None:
        f.oneof_index = next(
            i for i, o in enumerate(msg.oneof_decl) if o.name == oneof
        )


def apply_observability(fdp: dp.FileDescriptorProto) -> None:
    # -- EXPLAIN ANALYZE -----------------------------------------------------
    add_field(get_message(fdp, "ExplainNode"), "analyze", 3, F.TYPE_BOOL)

    if not has_message(fdp, "PhysicalExplainAnalyzeNode"):
        m = fdp.message_type.add(name="PhysicalExplainAnalyzeNode")
        add_field(m, "input", 1, F.TYPE_MESSAGE,
                  type_name=".ballista_tpu.PhysicalPlanNode")
        add_field(m, "verbose", 2, F.TYPE_BOOL)
        add_field(m, "logical_text", 3, F.TYPE_STRING)
    add_field(get_message(fdp, "PhysicalPlanNode"), "explain_analyze", 17,
              F.TYPE_MESSAGE,
              type_name=".ballista_tpu.PhysicalExplainAnalyzeNode",
              oneof="plan_type")

    # -- per-task / per-stage metrics ---------------------------------------
    if not has_message(fdp, "MetricValue"):
        m = fdp.message_type.add(name="MetricValue")
        m.oneof_decl.add(name="value")
        add_field(m, "name", 1, F.TYPE_STRING)
        add_field(m, "counter", 2, F.TYPE_INT64, oneof="value")
        add_field(m, "elapsed_secs", 3, F.TYPE_DOUBLE, oneof="value")
        add_field(m, "gauge", 4, F.TYPE_DOUBLE, oneof="value")

    if not has_message(fdp, "OperatorMetrics"):
        m = fdp.message_type.add(name="OperatorMetrics")
        add_field(m, "operator", 1, F.TYPE_STRING)
        add_field(m, "depth", 2, F.TYPE_UINT32)
        add_field(m, "metrics", 3, F.TYPE_MESSAGE,
                  type_name=".ballista_tpu.MetricValue", repeated=True)

    if not has_message(fdp, "TaskMetrics"):
        m = fdp.message_type.add(name="TaskMetrics")
        add_field(m, "operators", 1, F.TYPE_MESSAGE,
                  type_name=".ballista_tpu.OperatorMetrics", repeated=True)
        add_field(m, "elapsed_total_secs", 2, F.TYPE_DOUBLE)

    if not has_message(fdp, "StageMetrics"):
        m = fdp.message_type.add(name="StageMetrics")
        add_field(m, "stage_id", 1, F.TYPE_UINT32)
        add_field(m, "num_tasks", 2, F.TYPE_UINT32)
        add_field(m, "metrics", 3, F.TYPE_MESSAGE,
                  type_name=".ballista_tpu.TaskMetrics")

    add_field(get_message(fdp, "CompletedTask"), "metrics", 4,
              F.TYPE_MESSAGE, type_name=".ballista_tpu.TaskMetrics")
    add_field(get_message(fdp, "CompletedJob"), "stage_metrics", 2,
              F.TYPE_MESSAGE, type_name=".ballista_tpu.StageMetrics",
              repeated=True)


def apply_adaptive(fdp: dp.FileDescriptorProto) -> None:
    """PR 2: adaptive query execution wire fields (mirrored by hand in
    ballista.proto — keep the two in sync; dev/check_proto_sync.py
    guards the drift)."""
    # per-output-partition shuffle byte histogram on task stats
    add_field(get_message(fdp, "PartitionStats"), "shuffle_partition_bytes",
              5, F.TYPE_INT64, repeated=True)

    # adaptive reader layout on ShuffleReaderNode
    if not has_message(fdp, "ShuffleReadRange"):
        m = fdp.message_type.add(name="ShuffleReadRange")
        add_field(m, "output_lo", 1, F.TYPE_UINT32)
        add_field(m, "output_hi", 2, F.TYPE_UINT32)
        add_field(m, "producer_lo", 3, F.TYPE_UINT32)
        add_field(m, "producer_hi", 4, F.TYPE_UINT32)
    if not has_message(fdp, "ShuffleReadPartition"):
        m = fdp.message_type.add(name="ShuffleReadPartition")
        add_field(m, "ranges", 1, F.TYPE_MESSAGE,
                  type_name=".ballista_tpu.ShuffleReadRange", repeated=True)
    reader = get_message(fdp, "ShuffleReaderNode")
    add_field(reader, "read_partitions", 3, F.TYPE_MESSAGE,
              type_name=".ballista_tpu.ShuffleReadPartition", repeated=True)
    add_field(reader, "hash_columns", 4, F.TYPE_STRING, repeated=True)
    add_field(reader, "original_partitions", 5, F.TYPE_UINT32)

    # join demotion annotation
    add_field(get_message(fdp, "PhysicalJoinNode"), "adaptive_note", 7,
              F.TYPE_STRING)

    # stage versioning: definitions carry it, status reports echo it
    add_field(get_message(fdp, "TaskDefinition"), "stage_version", 5,
              F.TYPE_UINT32)
    add_field(get_message(fdp, "TaskStatus"), "stage_version", 5,
              F.TYPE_UINT32)


def apply_health(fdp: dp.FileDescriptorProto) -> None:
    """PR 5: executor heartbeats carry resource gauges for the
    scheduler's health plane (mirrored by hand in ballista.proto;
    dev/check_proto_sync.py guards the drift)."""
    if not has_message(fdp, "ExecutorResources"):
        m = fdp.message_type.add(name="ExecutorResources")
        add_field(m, "rss_bytes", 1, F.TYPE_UINT64)
        add_field(m, "device_bytes", 2, F.TYPE_UINT64)
        add_field(m, "inflight_tasks", 3, F.TYPE_UINT32)
        add_field(m, "ingest_pool_depth", 4, F.TYPE_UINT32)
        add_field(m, "peak_host_bytes", 5, F.TYPE_UINT64)
    add_field(get_message(fdp, "ExecutorMetadata"), "resources", 5,
              F.TYPE_MESSAGE, type_name=".ballista_tpu.ExecutorResources")


def apply_profiler(fdp: dp.FileDescriptorProto) -> None:
    """PR 7: distributed profiler wire fields (mirrored by hand in
    ballista.proto; dev/check_proto_sync.py guards the drift) — the
    per-task profile window riding CompletedTask, and the GetJobProfile
    RPC messages serving merged per-job artifacts to clients."""
    if not has_message(fdp, "TaskProfile"):
        m = fdp.message_type.add(name="TaskProfile")
        add_field(m, "t0", 1, F.TYPE_DOUBLE)
        add_field(m, "wall_seconds", 2, F.TYPE_DOUBLE)
        add_field(m, "pid", 3, F.TYPE_UINT32)
        add_field(m, "role", 4, F.TYPE_STRING)
        add_field(m, "executor_id", 5, F.TYPE_STRING)
        add_field(m, "records_json", 6, F.TYPE_BYTES)
        add_field(m, "phases_json", 7, F.TYPE_BYTES)
        add_field(m, "compile_json", 8, F.TYPE_BYTES)
        add_field(m, "memory_json", 9, F.TYPE_BYTES)
    add_field(get_message(fdp, "CompletedTask"), "profile", 5,
              F.TYPE_MESSAGE, type_name=".ballista_tpu.TaskProfile")

    if not has_message(fdp, "GetJobProfileParams"):
        m = fdp.message_type.add(name="GetJobProfileParams")
        add_field(m, "job_id", 1, F.TYPE_STRING)
    if not has_message(fdp, "GetJobProfileResult"):
        m = fdp.message_type.add(name="GetJobProfileResult")
        add_field(m, "artifact_json", 1, F.TYPE_BYTES)
        add_field(m, "error", 2, F.TYPE_STRING)


def apply_systables(fdp: dp.FileDescriptorProto) -> None:
    """PR 8: SQL-queryable system.* tables (mirrored by hand in
    ballista.proto; dev/check_proto_sync.py guards the drift) — the
    serialized-snapshot payload on TableSourceDesc and the
    GetSystemTable RPC serving scheduler snapshots to remote scans."""
    add_field(get_message(fdp, "TableSourceDesc"), "payload", 8,
              F.TYPE_BYTES)

    if not has_message(fdp, "GetSystemTableParams"):
        m = fdp.message_type.add(name="GetSystemTableParams")
        add_field(m, "table", 1, F.TYPE_STRING)
    if not has_message(fdp, "GetSystemTableResult"):
        m = fdp.message_type.add(name="GetSystemTableResult")
        add_field(m, "rows_json", 1, F.TYPE_BYTES)
        add_field(m, "error", 2, F.TYPE_STRING)


def apply_lifecycle(fdp: dp.FileDescriptorProto) -> None:
    """PR 9: query lifecycle control plane (mirrored by hand in
    ballista.proto; dev/check_proto_sync.py guards the drift) — the
    CancelJob RPC messages, the terminal CancelledJob status, the
    server-side deadline on ExecuteQueryParams, and the cancelled-job
    piggyback on PollWorkResult."""
    if not has_message(fdp, "CancelledJob"):
        m = fdp.message_type.add(name="CancelledJob")
        add_field(m, "reason", 1, F.TYPE_STRING)
    add_field(get_message(fdp, "JobStatus"), "cancelled", 5,
              F.TYPE_MESSAGE, type_name=".ballista_tpu.CancelledJob",
              oneof="status")

    add_field(get_message(fdp, "PollWorkResult"), "cancelled_jobs", 2,
              F.TYPE_STRING, repeated=True)
    add_field(get_message(fdp, "ExecuteQueryParams"), "deadline_secs", 5,
              F.TYPE_DOUBLE)

    if not has_message(fdp, "CancelJobParams"):
        m = fdp.message_type.add(name="CancelJobParams")
        add_field(m, "job_id", 1, F.TYPE_STRING)
        add_field(m, "reason", 2, F.TYPE_STRING)
    if not has_message(fdp, "CancelJobResult"):
        m = fdp.message_type.add(name="CancelJobResult")
        add_field(m, "cancelled", 1, F.TYPE_BOOL)
        add_field(m, "state", 2, F.TYPE_STRING)


def apply_progress(fdp: dp.FileDescriptorProto) -> None:
    """PR 10: live query progress plane (mirrored by hand in
    ballista.proto; dev/check_proto_sync.py guards the drift) — compact
    per-task progress samples piggybacked on the PollWork heartbeat,
    and the live job progress model served through GetJobStatus."""
    if not has_message(fdp, "TaskProgress"):
        m = fdp.message_type.add(name="TaskProgress")
        add_field(m, "partition_id", 1, F.TYPE_MESSAGE,
                  type_name=".ballista_tpu.PartitionId")
        add_field(m, "stage_version", 2, F.TYPE_UINT32)
        add_field(m, "operator", 3, F.TYPE_STRING)
        add_field(m, "rows_so_far", 4, F.TYPE_UINT64)
        add_field(m, "input_rows_total", 5, F.TYPE_UINT64)
        add_field(m, "bytes_so_far", 6, F.TYPE_UINT64)
        add_field(m, "elapsed_seconds", 7, F.TYPE_DOUBLE)
    add_field(get_message(fdp, "PollWorkParams"), "task_progress", 4,
              F.TYPE_MESSAGE, type_name=".ballista_tpu.TaskProgress",
              repeated=True)

    if not has_message(fdp, "StageProgress"):
        m = fdp.message_type.add(name="StageProgress")
        add_field(m, "stage_id", 1, F.TYPE_UINT32)
        add_field(m, "tasks_total", 2, F.TYPE_UINT32)
        add_field(m, "tasks_running", 3, F.TYPE_UINT32)
        add_field(m, "tasks_completed", 4, F.TYPE_UINT32)
        add_field(m, "fraction", 5, F.TYPE_DOUBLE)
        add_field(m, "eta_seconds", 6, F.TYPE_DOUBLE)
        add_field(m, "rows_so_far", 7, F.TYPE_UINT64)
        add_field(m, "bytes_so_far", 8, F.TYPE_UINT64)
    if not has_message(fdp, "JobProgress"):
        m = fdp.message_type.add(name="JobProgress")
        add_field(m, "fraction", 1, F.TYPE_DOUBLE)
        add_field(m, "eta_seconds", 2, F.TYPE_DOUBLE)
        add_field(m, "wall_seconds", 3, F.TYPE_DOUBLE)
        add_field(m, "tasks_total", 4, F.TYPE_UINT32)
        add_field(m, "tasks_running", 5, F.TYPE_UINT32)
        add_field(m, "tasks_queued", 6, F.TYPE_UINT32)
        add_field(m, "tasks_completed", 7, F.TYPE_UINT32)
        add_field(m, "stages", 8, F.TYPE_MESSAGE,
                  type_name=".ballista_tpu.StageProgress", repeated=True)
    add_field(get_message(fdp, "GetJobStatusResult"), "progress", 2,
              F.TYPE_MESSAGE, type_name=".ballista_tpu.JobProgress")


def apply_spill(fdp: dp.FileDescriptorProto) -> None:
    """PR 12: memory-governed streaming shuffle (mirrored by hand in
    ballista.proto; dev/check_proto_sync.py guards the drift) — the
    data-plane chunk-stream negotiation field on Action and the shuffle
    governor gauges riding the executor heartbeat."""
    add_field(get_message(fdp, "Action"), "stream_window", 11,
              F.TYPE_UINT64)
    add_field(get_message(fdp, "Action"), "stream_chunk", 12,
              F.TYPE_UINT64)
    res = get_message(fdp, "ExecutorResources")
    add_field(res, "shuffle_inflight_bytes", 6, F.TYPE_UINT64)
    add_field(res, "spill_bytes_total", 7, F.TYPE_UINT64)


def apply_admission(fdp: dp.FileDescriptorProto) -> None:
    """PR 15: multi-tenant admission plane (mirrored by hand in
    ballista.proto; dev/check_proto_sync.py guards the drift) — the
    structured shed on ExecuteQueryResult, queue position/reason on the
    queued JobStatus, and the retryable retry-after on FailedJob
    (queue-timeout sheds travel as a terminal failed status)."""
    res = get_message(fdp, "ExecuteQueryResult")
    add_field(res, "error", 2, F.TYPE_STRING)
    add_field(res, "retry_after_secs", 3, F.TYPE_DOUBLE)

    q = get_message(fdp, "QueuedJob")
    add_field(q, "queue_position", 1, F.TYPE_UINT32)
    add_field(q, "reason", 2, F.TYPE_STRING)
    add_field(q, "queued_seconds", 3, F.TYPE_DOUBLE)

    add_field(get_message(fdp, "FailedJob"), "retry_after_secs", 2,
              F.TYPE_DOUBLE)


def apply_controlplane(fdp: dp.FileDescriptorProto) -> None:
    """PR 17: durable elastic control plane (mirrored by hand in
    ballista.proto; dev/check_proto_sync.py guards the drift) — the
    recovered marker on the queued JobStatus (the entry was rebuilt
    from the journal by a restarted scheduler) and the autoscaler's
    graceful-drain piggyback on PollWorkResult (the executor stops
    accepting tasks and exits once its in-flight work completes)."""
    add_field(get_message(fdp, "QueuedJob"), "recovered", 4,
              F.TYPE_BOOL)
    add_field(get_message(fdp, "PollWorkResult"), "drain", 3,
              F.TYPE_BOOL)


TEMPLATE = '''# -*- coding: utf-8 -*-
# Generated by dev/gen_proto_patch.py (no protoc in this image). DO NOT EDIT!
# source: ballista.proto
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()




DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, \'ballista_pb2\', globals())
# @@protoc_insertion_point(module_scope)
'''


def main() -> None:
    blob = load_serialized_blob(PB2)
    fdp = dp.FileDescriptorProto.FromString(blob)
    apply_observability(fdp)
    apply_adaptive(fdp)
    apply_health(fdp)
    apply_profiler(fdp)
    apply_systables(fdp)
    apply_lifecycle(fdp)
    apply_progress(fdp)
    apply_spill(fdp)
    apply_admission(fdp)
    apply_controlplane(fdp)
    out = TEMPLATE.format(blob=fdp.SerializeToString())
    with open(PB2, "w") as f:
        f.write(out)
    print(f"wrote {os.path.normpath(PB2)} "
          f"({len(fdp.message_type)} messages)")


if __name__ == "__main__":
    main()
