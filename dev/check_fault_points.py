#!/usr/bin/env python
"""Fault-point drift lint (tier-1) — thin shim over the unified
analysis engine (``ballista_tpu/analysis/``, rule id ``fault-points``;
run everything at once with ``dev/analyze.py``).

CLI and exit semantics are unchanged from the standalone version:
exit 0 = in sync, per-problem ``error:`` lines otherwise. The check
stays symmetric — unknown call-site names AND registered points with
no call site both fail. Dynamic sites still annotate with
``# fault-points: a b c``.

Usage: python dev/check_fault_points.py   (exit 0 = clean)
"""

from __future__ import annotations

import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.normpath(os.path.join(HERE, ".."))
sys.path.insert(0, HERE)

import analyze  # noqa: E402 - sibling loader for the analysis engine


def main() -> int:
    analysis = analyze.load_analysis(REPO)
    pkg = analysis.Package.load(REPO)
    rule = analysis.RULE_FACTORIES["fault-points"]()
    result = analysis.analyze(pkg, [rule])
    problems = result.parse_errors + result.findings
    if problems:
        for f in problems:
            print(f"error: {f.file}:{f.line}: {f.message}")
        print(f"{len(problems)} fault-point drift error(s)")
        return 1
    from ballista_tpu.testing.faults import FAULT_POINTS

    print(f"fault points in sync ({len(FAULT_POINTS)} registered)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
