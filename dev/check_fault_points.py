#!/usr/bin/env python
"""Fault-point drift lint (tier-1).

Every ``fault_point("x", ...)`` literal in ``ballista_tpu/**`` must
name a point registered in
``ballista_tpu/testing/faults.py::FAULT_POINTS`` — the same table
``BALLISTA_FAULTS`` validates specs against and docs/robustness.md
catalogs. A call site that builds its name dynamically must carry a
``# fault-points: a b c`` annotation on the same line naming every
point it can hit; those names are checked against the registry too.

The check is symmetric: a registered point with NO call site fails as
well — a fault the chaos sweep can arm but that can never fire is a
test bug waiting to no-op.

Wired into tier-1 (tests/test_lifecycle.py) next to
check_metric_names.py / check_knob_docs.py / check_proto_sync.py.

Usage: python dev/check_fault_points.py   (exit 0 = clean)
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Set, Tuple

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.abspath(os.path.join(HERE, ".."))
PKG = os.path.join(ROOT, "ballista_tpu")

sys.path.insert(0, ROOT)

from ballista_tpu.testing.faults import FAULT_POINTS  # noqa: E402

_CALL = re.compile(r"\bfault_point\s*\(")
# a literal first argument ends at , or ) — "prefix." + name is DYNAMIC
_LITERAL_ARG = re.compile(r"^\s*(['\"])([^'\"]+)\1\s*[,)]")
_ANNOTATION = re.compile(r"#\s*fault-points:\s*([\w\s.,-]+)")

# the machinery itself (definitions, re-dispatch) — not call sites
SKIP_FILES = {
    "ballista_tpu/testing/faults.py",
}
SKIP_DIRS = ("ballista_tpu/proto/",)


def scan() -> Tuple[List[Tuple[str, int, str, str]], Dict[str, int]]:
    """Returns (problems, {point: call-site count})."""
    problems: List[Tuple[str, int, str, str]] = []
    used: Dict[str, int] = {p: 0 for p in FAULT_POINTS}
    for root, dirs, files in os.walk(PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, ROOT).replace(os.sep, "/")
            if rel in SKIP_FILES or rel.startswith(SKIP_DIRS):
                continue
            for i, line in enumerate(open(path, encoding="utf-8"), 1):
                dynamic = False
                for m in _CALL.finditer(line):
                    lit = _LITERAL_ARG.match(line[m.end():])
                    if lit is None:
                        dynamic = True
                        continue
                    name = lit.group(2)
                    if name in FAULT_POINTS:
                        used[name] += 1
                    else:
                        problems.append(
                            (rel, i, name,
                             "literal fault-point name not in "
                             "FAULT_POINTS registry"))
                if dynamic:
                    ann = _ANNOTATION.search(line)
                    if ann is None:
                        problems.append(
                            (rel, i, "<dynamic>",
                             "dynamic fault-point name without a "
                             "'# fault-points: ...' annotation"))
                        continue
                    names: Set[str] = {
                        t for t in re.split(r"[\s,]+", ann.group(1))
                        if t
                    }
                    for name in sorted(names):
                        if name in FAULT_POINTS:
                            used[name] += 1
                        else:
                            problems.append(
                                (rel, i, name,
                                 "annotated fault-point name not in "
                                 "FAULT_POINTS registry"))
    return problems, used


def main() -> int:
    problems, used = scan()
    for rel, line, name, why in problems:
        print(f"error: {rel}:{line}: {name!r}: {why}")
    unused = sorted(p for p, n in used.items() if n == 0)
    for p in unused:
        print(f"error: registered fault point {p!r} has no call site "
              "(an armable fault that can never fire)")
    n = len(problems) + len(unused)
    if n:
        print(f"{n} fault-point drift error(s)")
        return 1
    total = sum(used.values())
    print(f"fault points in sync ({len(used)} registered, "
          f"{total} call site(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
