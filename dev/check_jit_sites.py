#!/usr/bin/env python
"""Guard against raw ``jax.jit`` call sites regrowing outside the
compile governor.

PR 3 folded ~10 scattered ad-hoc jit caches (per-instance
``self._jit_cache`` dicts, module-level ``*_JITS`` maps) into
``ballista_tpu/compile/`` so compilation is a managed, observable
resource: adaptive re-plans reuse traces, compile counts/seconds flow
into operator metrics, and shape bucketing bounds the signature count.
A stray ``jax.jit(`` anywhere else silently re-creates the
uncounted-per-instance-cache problem — this lint (run from tier-1,
tests/test_compile_governor.py) fails the build instead.

Scans ``ballista_tpu/**/*.py`` for ``jax.jit`` / ``pjit`` uses. The
allowlist names the legitimate remainder (the governor itself).

Usage: python dev/check_jit_sites.py   (exit 0 = clean)
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

HERE = os.path.dirname(os.path.abspath(__file__))
PKG = os.path.join(HERE, "..", "ballista_tpu")

# repo-relative files allowed to call jax.jit directly
ALLOWLIST = {
    "ballista_tpu/compile/governor.py",  # THE jit site: the governor
}

# individual call sites elsewhere opt out with a trailing
# ``# jit-ok: <reason>`` comment on the offending line — file-level
# allowlisting would silently exempt future sites in the same module
MARKER = "jit-ok:"

# jax.jit(...), jax.pjit(...), bare pjit( after a from-import
_PAT = re.compile(r"\bjax\s*\.\s*(?:jit|pjit)\s*\(|\bpjit\s*\(")
_COMMENT = re.compile(r"(^|\s)#.*$")


def scan() -> List[Tuple[str, int, str]]:
    hits: List[Tuple[str, int, str]] = []
    for root, _dirs, files in os.walk(os.path.abspath(PKG)):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(
                path, os.path.abspath(os.path.join(HERE, ".."))
            ).replace(os.sep, "/")
            if rel in ALLOWLIST:
                continue
            in_doc = False
            for i, line in enumerate(open(path, encoding="utf-8"), 1):
                # crude but sufficient: strip comments; skip docstring
                # bodies (module docs MENTION jax.jit legitimately)
                if line.count('"""') % 2 == 1:
                    in_doc = not in_doc
                    continue
                if in_doc or MARKER in line:
                    continue
                code = _COMMENT.sub("", line)
                if _PAT.search(code):
                    hits.append((rel, i, line.rstrip()))
    return hits


def main() -> int:
    hits = scan()
    if hits:
        for rel, i, line in hits:
            print(f"JIT-SITE: {rel}:{i}: {line.strip()}", file=sys.stderr)
        print(
            f"{len(hits)} raw jax.jit call site(s) outside "
            "ballista_tpu/compile/ — route them through "
            "ballista_tpu.compile.governed() (or extend the allowlist "
            "with a justification)",
            file=sys.stderr,
        )
        return 1
    print("no raw jax.jit sites outside ballista_tpu/compile/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
