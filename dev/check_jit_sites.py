#!/usr/bin/env python
"""Guard against raw ``jax.jit`` call sites regrowing outside the
compile governor — thin shim over the unified analysis engine
(``ballista_tpu/analysis/``, rule id ``jit-sites``; run everything at
once with ``dev/analyze.py``).

CLI and exit semantics are unchanged from the standalone version:
exit 0 = clean, per-site ``JIT-SITE:`` lines on stderr otherwise, and
``--budget`` still runs the program-count regression gate. Per-line
opt-out stays ``# jit-ok: <reason>``; the allowlist lives on the rule
(``analysis/passes/shape.py::JitSitesRule``).

Usage: python dev/check_jit_sites.py   (exit 0 = clean)
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.normpath(os.path.join(HERE, ".."))
sys.path.insert(0, HERE)

import analyze  # noqa: E402 - sibling loader for the analysis engine


def scan() -> List[Tuple[str, int, str]]:
    """[(repo-relative file, line, source line)] of violations —
    signature preserved for tests importing this module directly."""
    analysis = analyze.load_analysis(REPO)
    pkg = analysis.Package.load(REPO)
    rule = analysis.RULE_FACTORIES["jit-sites"]()
    result = analysis.analyze(pkg, [rule])
    # unparseable files fail too: the regex original scanned raw text,
    # so a violation in a broken file could never pass silently
    return [(f.file, f.line, f.message) for f in result.parse_errors] + \
        [(f.file, f.line, pkg.by_rel[f.file].line(f.line).rstrip())
         for f in result.findings]


# ---------------------------------------------------------------------------
# program-count regression gate (--budget): whole-stage fusion exists to
# keep the governed program count down; silent de-fusion (a matcher that
# stops firing, a planner change that breaks the chain shape) would leak
# programs back without failing any correctness test. The gate runs
# q1+q5 on a tiny generated dataset with fusion ON and pins (a) that
# fused operators are actually in the plans and (b) the number of
# governed entries minted. Budget pinned from a measured 22 entries
# (pre-fusion: 27 at the same scale) with small headroom for planner
# drift — a de-fused q1 alone would add 3+ entries and trip it.
# ---------------------------------------------------------------------------

DEFAULT_ENTRY_BUDGET = 24


def check_budget(budget: int = DEFAULT_ENTRY_BUDGET) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["BALLISTA_FUSION"] = "on"
    import tempfile

    sys.path.insert(0, REPO)
    from benchmarks.tpch import datagen
    from benchmarks.tpch.schema_def import register_tpch
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.compile import compile_stats
    from ballista_tpu.physical.fusion import FusedStageExec
    from ballista_tpu.physical.join import JoinExec

    import shutil

    d = tempfile.mkdtemp(prefix="jit_budget_")
    try:
        datagen.generate(d, scale=0.002, num_parts=2)
        ctx = BallistaContext.standalone()
        register_tpch(ctx, d, "tbl")
        qdir = os.path.join(HERE, "..", "benchmarks", "tpch", "queries")
        fused_seen = 0
        for q in ("q1", "q5"):
            df = ctx.sql(open(os.path.join(qdir, f"{q}.sql")).read())
            df.collect()
            phys = df._phys

            def count_fused(node):
                n = int(isinstance(node, FusedStageExec))
                n += int(isinstance(node, JoinExec)
                         and bool(node.probe_chain))
                return n + sum(count_fused(c) for c in node.children())

            fused_seen += count_fused(phys)
    finally:
        shutil.rmtree(d, ignore_errors=True)
    if fused_seen == 0:
        print("BUDGET: no FusedStageExec in the q1+q5 plans — "
              "silent de-fusion", file=sys.stderr)
        return 1
    built = int(compile_stats()["entries_built"])
    if built > budget:
        print(f"BUDGET: q1+q5 minted {built} governed entries "
              f"(budget {budget}) — fusion regressed", file=sys.stderr)
        return 1
    print(f"program budget ok: {built} governed entries <= {budget} "
          f"({fused_seen} fused stages)")
    return 0


def main() -> int:
    if "--budget" in sys.argv:
        i = sys.argv.index("--budget")
        n = (int(sys.argv[i + 1]) if len(sys.argv) > i + 1
             else DEFAULT_ENTRY_BUDGET)
        return check_budget(n)
    hits = scan()
    if hits:
        for rel, i, line in hits:
            print(f"JIT-SITE: {rel}:{i}: {line.strip()}", file=sys.stderr)
        print(
            f"{len(hits)} raw jax.jit call site(s) outside "
            "ballista_tpu/compile/ — route them through "
            "ballista_tpu.compile.governed() (or extend the allowlist "
            "with a justification)",
            file=sys.stderr,
        )
        return 1
    print("no raw jax.jit sites outside ballista_tpu/compile/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
