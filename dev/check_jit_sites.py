#!/usr/bin/env python
"""Guard against raw ``jax.jit`` call sites regrowing outside the
compile governor.

PR 3 folded ~10 scattered ad-hoc jit caches (per-instance
``self._jit_cache`` dicts, module-level ``*_JITS`` maps) into
``ballista_tpu/compile/`` so compilation is a managed, observable
resource: adaptive re-plans reuse traces, compile counts/seconds flow
into operator metrics, and shape bucketing bounds the signature count.
A stray ``jax.jit(`` anywhere else silently re-creates the
uncounted-per-instance-cache problem — this lint (run from tier-1,
tests/test_compile_governor.py) fails the build instead.

Scans ``ballista_tpu/**/*.py`` for ``jax.jit`` / ``pjit`` uses. The
allowlist names the legitimate remainder (the governor itself).

Usage: python dev/check_jit_sites.py   (exit 0 = clean)
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

HERE = os.path.dirname(os.path.abspath(__file__))
PKG = os.path.join(HERE, "..", "ballista_tpu")

# repo-relative files allowed to call jax.jit directly
ALLOWLIST = {
    "ballista_tpu/compile/governor.py",  # THE jit site: the governor
    # fused-stage AOT export wraps a governed entry's own (already
    # governed) python function for jax.export serialization — it never
    # creates an uncounted cache
    "ballista_tpu/compile/aot.py",
}

# individual call sites elsewhere opt out with a trailing
# ``# jit-ok: <reason>`` comment on the offending line — file-level
# allowlisting would silently exempt future sites in the same module
MARKER = "jit-ok:"

# jax.jit(...), jax.pjit(...), bare pjit( after a from-import
_PAT = re.compile(r"\bjax\s*\.\s*(?:jit|pjit)\s*\(|\bpjit\s*\(")
_COMMENT = re.compile(r"(^|\s)#.*$")


def scan() -> List[Tuple[str, int, str]]:
    hits: List[Tuple[str, int, str]] = []
    for root, _dirs, files in os.walk(os.path.abspath(PKG)):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(
                path, os.path.abspath(os.path.join(HERE, ".."))
            ).replace(os.sep, "/")
            if rel in ALLOWLIST:
                continue
            in_doc = False
            for i, line in enumerate(open(path, encoding="utf-8"), 1):
                # crude but sufficient: strip comments; skip docstring
                # bodies (module docs MENTION jax.jit legitimately)
                if line.count('"""') % 2 == 1:
                    in_doc = not in_doc
                    continue
                if in_doc or MARKER in line:
                    continue
                code = _COMMENT.sub("", line)
                if _PAT.search(code):
                    hits.append((rel, i, line.rstrip()))
    return hits


# ---------------------------------------------------------------------------
# program-count regression gate (--budget): whole-stage fusion exists to
# keep the governed program count down; silent de-fusion (a matcher that
# stops firing, a planner change that breaks the chain shape) would leak
# programs back without failing any correctness test. The gate runs
# q1+q5 on a tiny generated dataset with fusion ON and pins (a) that
# fused operators are actually in the plans and (b) the number of
# governed entries minted. Budget pinned from a measured 22 entries
# (pre-fusion: 27 at the same scale) with small headroom for planner
# drift — a de-fused q1 alone would add 3+ entries and trip it.
# ---------------------------------------------------------------------------

DEFAULT_ENTRY_BUDGET = 24


def check_budget(budget: int = DEFAULT_ENTRY_BUDGET) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["BALLISTA_FUSION"] = "on"
    import tempfile

    sys.path.insert(0, os.path.abspath(os.path.join(HERE, "..")))
    from benchmarks.tpch import datagen
    from benchmarks.tpch.schema_def import register_tpch
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.compile import compile_stats
    from ballista_tpu.physical.fusion import FusedStageExec
    from ballista_tpu.physical.join import JoinExec

    import shutil

    d = tempfile.mkdtemp(prefix="jit_budget_")
    try:
        datagen.generate(d, scale=0.002, num_parts=2)
        ctx = BallistaContext.standalone()
        register_tpch(ctx, d, "tbl")
        qdir = os.path.join(HERE, "..", "benchmarks", "tpch", "queries")
        fused_seen = 0
        for q in ("q1", "q5"):
            df = ctx.sql(open(os.path.join(qdir, f"{q}.sql")).read())
            df.collect()
            phys = df._phys

            def count_fused(node):
                n = int(isinstance(node, FusedStageExec))
                n += int(isinstance(node, JoinExec)
                         and bool(node.probe_chain))
                return n + sum(count_fused(c) for c in node.children())

            fused_seen += count_fused(phys)
    finally:
        shutil.rmtree(d, ignore_errors=True)
    if fused_seen == 0:
        print("BUDGET: no FusedStageExec in the q1+q5 plans — "
              "silent de-fusion", file=sys.stderr)
        return 1
    built = int(compile_stats()["entries_built"])
    if built > budget:
        print(f"BUDGET: q1+q5 minted {built} governed entries "
              f"(budget {budget}) — fusion regressed", file=sys.stderr)
        return 1
    print(f"program budget ok: {built} governed entries <= {budget} "
          f"({fused_seen} fused stages)")
    return 0


def main() -> int:
    if "--budget" in sys.argv:
        i = sys.argv.index("--budget")
        n = (int(sys.argv[i + 1]) if len(sys.argv) > i + 1
             else DEFAULT_ENTRY_BUDGET)
        return check_budget(n)
    hits = scan()
    if hits:
        for rel, i, line in hits:
            print(f"JIT-SITE: {rel}:{i}: {line.strip()}", file=sys.stderr)
        print(
            f"{len(hits)} raw jax.jit call site(s) outside "
            "ballista_tpu/compile/ — route them through "
            "ballista_tpu.compile.governed() (or extend the allowlist "
            "with a justification)",
            file=sys.stderr,
        )
        return 1
    print("no raw jax.jit sites outside ballista_tpu/compile/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
