#!/usr/bin/env python
"""Guard against host dictionary unify/remap paths regrowing outside
the dictionary registry — thin shim over the unified analysis engine
(``ballista_tpu/analysis/``, rule id ``dict-sites``; run everything at
once with ``dev/analyze.py``).

CLI and exit semantics are unchanged from the standalone version:
exit 0 = clean, per-site ``DICT-SITE:`` lines on stderr otherwise.
Per-line opt-out stays ``# dict-ok: <reason>``; the allowlist lives on
the rule (``analysis/passes/shape.py::DictSitesRule``).

Usage: python dev/check_dict_sites.py   (exit 0 = clean)
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.normpath(os.path.join(HERE, ".."))
sys.path.insert(0, HERE)

import analyze  # noqa: E402 - sibling loader for the analysis engine


def scan() -> List[Tuple[str, int, str]]:
    analysis = analyze.load_analysis(REPO)
    pkg = analysis.Package.load(REPO)
    rule = analysis.RULE_FACTORIES["dict-sites"]()
    result = analysis.analyze(pkg, [rule])
    # unparseable files fail too: the regex original scanned raw text,
    # so a violation in a broken file could never pass silently
    return [(f.file, f.line, f.message) for f in result.parse_errors] + \
        [(f.file, f.line, pkg.by_rel[f.file].line(f.line).rstrip())
         for f in result.findings]


def main() -> int:
    hits = scan()
    if hits:
        for rel, i, line in hits:
            print(f"DICT-SITE: {rel}:{i}: {line.strip()}", file=sys.stderr)
        print(
            f"{len(hits)} host np.unique/np.searchsorted call site(s) "
            "outside the dictionary registry — route dictionary "
            "unify/remap through ballista_tpu.columnar_registry (or mark "
            "a legitimate non-dictionary use with '# dict-ok: <reason>')",
            file=sys.stderr,
        )
        return 1
    print("no host dictionary unify/remap sites outside the registry")
    return 0


if __name__ == "__main__":
    sys.exit(main())
