#!/usr/bin/env python
"""Guard against host dictionary unify/remap paths regrowing outside
the dictionary registry.

ISSUE 11 moved every sorted-union / searchsorted-remap over dictionary
value arrays into ``ballista_tpu/columnar_registry.py`` (versioned
entries, cached integer remaps) and ``ballista_tpu/columnar.py``
(the Dictionary's own encode primitives). A stray ``np.unique(`` /
``np.searchsorted(`` anywhere else silently reintroduces the
GIL-bound object-array work the ``host.dictionary`` profiler lane
exists to keep visible — this lint (run from tier-1,
tests/test_dict_registry.py) fails the build instead, mirroring
``dev/check_jit_sites.py``.

Device-side ``jnp.searchsorted`` is fine (that's the point); only host
``np.`` calls are flagged. Legitimate non-dictionary uses elsewhere
(building a NEW dictionary from raw scan values, numeric arrays) opt
out per line with a trailing ``# dict-ok: <reason>`` marker.

Usage: python dev/check_dict_sites.py   (exit 0 = clean)
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

HERE = os.path.dirname(os.path.abspath(__file__))
PKG = os.path.join(HERE, "..", "ballista_tpu")

# repo-relative files allowed to run host unique/searchsorted directly
ALLOWLIST = {
    # THE unify/remap site: versioned unions, cached remap tables
    "ballista_tpu/columnar_registry.py",
    # the Dictionary's own encode/canonicalize/search primitives —
    # building a dictionary from raw values is not unifying two
    "ballista_tpu/columnar.py",
}

MARKER = "dict-ok:"

_PAT = re.compile(r"\bnp\s*\.\s*(?:unique|searchsorted)\s*\(")
_COMMENT = re.compile(r"(^|\s)#.*$")


def scan() -> List[Tuple[str, int, str]]:
    hits: List[Tuple[str, int, str]] = []
    for root, _dirs, files in os.walk(os.path.abspath(PKG)):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(
                path, os.path.abspath(os.path.join(HERE, ".."))
            ).replace(os.sep, "/")
            if rel in ALLOWLIST:
                continue
            in_doc = False
            for i, line in enumerate(open(path, encoding="utf-8"), 1):
                if line.count('"""') % 2 == 1:
                    in_doc = not in_doc
                    continue
                if in_doc or MARKER in line:
                    continue
                code = _COMMENT.sub("", line)
                if _PAT.search(code):
                    hits.append((rel, i, line.rstrip()))
    return hits


def main() -> int:
    hits = scan()
    if hits:
        for rel, i, line in hits:
            print(f"DICT-SITE: {rel}:{i}: {line.strip()}", file=sys.stderr)
        print(
            f"{len(hits)} host np.unique/np.searchsorted call site(s) "
            "outside the dictionary registry — route dictionary "
            "unify/remap through ballista_tpu.columnar_registry (or mark "
            "a legitimate non-dictionary use with '# dict-ok: <reason>')",
            file=sys.stderr,
        )
        return 1
    print("no host dictionary unify/remap sites outside the registry")
    return 0


if __name__ == "__main__":
    sys.exit(main())
