#!/usr/bin/env python
"""Knob/documentation drift lint (tier-1).

Three surfaces must agree on the set of ``BALLISTA_*`` environment
knobs:

- the SOURCE: every exact ``"BALLISTA_X"`` string literal in
  ``ballista_tpu/**/*.py`` (AST string constants, so prose mentioning a
  knob inside a docstring only counts when it IS the bare name);
- the REGISTRY: ``observability/systables.py`` ``KNOBS`` /
  ``KNOB_PREFIXES`` — what ``system.settings`` serves;
- the DOCS: the README knob tables (any ``BALLISTA_X`` token).

Failures are symmetric: a knob read in the source but missing from the
registry or README fails, and so does a registry/README entry no code
reads (stale docs). Dynamic env-name families (``BALLISTA_ADAPTIVE_*``,
binary config prefixes) are declared as prefixes in ``KNOB_PREFIXES``;
a literal ending in ``_`` must be one of them, and a README token is
accepted when a declared prefix covers it.

Usage: python dev/check_knob_docs.py   (exit 0 = in sync)
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, Set

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.normpath(os.path.join(HERE, ".."))
PKG = os.path.join(REPO, "ballista_tpu")
README = os.path.join(REPO, "README.md")

_EXACT = re.compile(r"^BALLISTA_[A-Z0-9]+(?:_[A-Z0-9]+)*$")
_PREFIX = re.compile(r"^BALLISTA_[A-Z0-9]+(?:_[A-Z0-9]+)*_$")
_README_TOKEN = re.compile(r"\bBALLISTA_[A-Z0-9_]+\b")

# literals that are not knobs: "BALLISTA_" alone is the base of a
# dynamically-composed env name (adaptive/config.py, distributed/
# config.py) — the composed families are declared as prefixes
_IGNORED_LITERALS = {"BALLISTA_"}


def source_literals() -> Dict[str, Set[str]]:
    """{exact | prefix: {file:line, ...}} for every BALLISTA_* string
    constant in the package."""
    found: Dict[str, Set[str]] = {}
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, REPO)
            try:
                tree = ast.parse(open(path).read(), filename=path)
            except SyntaxError as e:
                print(f"error: cannot parse {rel}: {e}", file=sys.stderr)
                sys.exit(2)
            for node in ast.walk(tree):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str):
                    v = node.value
                    if v in _IGNORED_LITERALS:
                        continue
                    if _EXACT.match(v) or _PREFIX.match(v):
                        found.setdefault(v, set()).add(
                            f"{rel}:{node.lineno}")
    return found


def readme_tokens() -> Set[str]:
    return set(_README_TOKEN.findall(open(README).read()))


def main() -> int:
    sys.path.insert(0, REPO)
    from ballista_tpu.observability.systables import KNOB_PREFIXES, KNOBS

    prefixes = set(KNOB_PREFIXES)
    registry = set(KNOBS)
    errors = []

    def covered_by_prefix(name: str) -> bool:
        return any(name.startswith(p) for p in prefixes)

    literals = source_literals()
    exact_in_source = {n for n in literals if not n.endswith("_")}
    prefix_in_source = {n for n in literals if n.endswith("_")}

    # 1. source -> registry
    for name in sorted(exact_in_source):
        if name not in registry and not covered_by_prefix(name):
            where = ", ".join(sorted(literals[name])[:3])
            errors.append(
                f"knob {name} is read in the source ({where}) but "
                "missing from the system.settings registry "
                "(observability/systables.py KNOBS)")
    for name in sorted(prefix_in_source):
        if name not in prefixes:
            where = ", ".join(sorted(literals[name])[:3])
            errors.append(
                f"dynamic knob prefix {name} is used in the source "
                f"({where}) but not declared in KNOB_PREFIXES")

    # 2. registry -> source (stale entries) and registry -> README
    tokens = readme_tokens()
    for name in sorted(registry):
        if name not in exact_in_source:
            errors.append(
                f"registry knob {name} is not read anywhere in "
                "ballista_tpu/ (stale KNOBS entry?)")
        if name not in tokens:
            errors.append(
                f"registry knob {name} is missing from the README "
                "knob tables")
    for name in sorted(prefixes):
        if name not in prefix_in_source:
            errors.append(
                f"declared prefix {name} is not used anywhere in "
                "ballista_tpu/ (stale KNOB_PREFIXES entry?)")

    # 3. README -> registry
    for tok in sorted(tokens):
        if tok in registry or covered_by_prefix(tok):
            continue
        errors.append(
            f"README mentions {tok}, which is neither a registered "
            "knob nor covered by a declared prefix")

    if errors:
        for e in errors:
            print(f"error: {e}", file=sys.stderr)
        print(f"{len(errors)} knob/doc drift error(s)", file=sys.stderr)
        return 1
    print(f"knob docs in sync ({len(registry)} knobs, "
          f"{len(prefixes)} prefixes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
