#!/usr/bin/env python
"""Knob/documentation drift lint (tier-1) — thin shim over the unified
analysis engine (``ballista_tpu/analysis/``, rule id ``knob-docs``;
run everything at once with ``dev/analyze.py``).

Three surfaces must agree on the set of ``BALLISTA_*`` environment
knobs — the source literals, the ``system.settings`` registry
(``observability/systables.py`` KNOBS/KNOB_PREFIXES) and the README
knob tables — with symmetric failures in every direction. CLI and exit
semantics are unchanged from the standalone version: exit 0 = in sync,
per-problem ``error:`` lines on stderr otherwise.

Usage: python dev/check_knob_docs.py   (exit 0 = in sync)
"""

from __future__ import annotations

import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.normpath(os.path.join(HERE, ".."))
sys.path.insert(0, HERE)

import analyze  # noqa: E402 - sibling loader for the analysis engine


def main() -> int:
    analysis = analyze.load_analysis(REPO)
    pkg = analysis.Package.load(REPO)
    rule = analysis.RULE_FACTORIES["knob-docs"]()
    result = analysis.analyze(pkg, [rule])
    problems = result.parse_errors + result.findings
    if problems:
        for f in problems:
            print(f"error: {f.message}", file=sys.stderr)
        print(f"{len(problems)} knob/doc drift error(s)",
              file=sys.stderr)
        return 1
    from ballista_tpu.observability.systables import KNOB_PREFIXES, KNOBS

    print(f"knob docs in sync ({len(KNOBS)} knobs, "
          f"{len(KNOB_PREFIXES)} prefixes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
