#!/usr/bin/env bash
# Integration gate (reference parity: dev/integration-tests.sh builds
# images, generates data, runs the compose cluster + query subset; here:
# native build, the full suite INCLUDING the SF0.2 scale tier (all 22
# TPC-H queries through standalone AND the cluster — the scale-dependent
# paths: overflow, compaction, partitioned joins, recovery), then the
# benchmark smoke. Budget: ~6min on a 1-core box (~2min fast tier +
# ~160s SF0.2 + bench). Skip the scale tier for quick iteration with
#   FAST_ONLY=1 dev/integration_test.sh
set -euo pipefail
cd "$(dirname "$0")/.."

make -C ballista_tpu/native

# Real-etcd tier: when an etcd binary (or BALLISTA_ETCD_URL) is present —
# e.g. inside deploy/docker-compose.etcd.yaml — tests/test_real_etcd.py
# runs the etcd v3 wire implementation against the real server instead of
# only the in-repo fake (protocol-skew guard). It self-skips otherwise.
if command -v etcd >/dev/null 2>&1 || [[ -n "${BALLISTA_ETCD_URL:-}" ]]; then
  echo "real etcd detected: running protocol-skew tier"
  python -m pytest tests/test_real_etcd.py -q
fi

if [[ "${FAST_ONLY:-0}" == "1" ]]; then
  python -m pytest tests/ -q -m "not sf02"
else
  python -m pytest tests/ -q
fi
python bench.py --cpu --scale 0.2 --runs 2
