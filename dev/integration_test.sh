#!/usr/bin/env bash
# Integration gate (reference parity: dev/integration-tests.sh builds
# images, generates data, runs the compose cluster + query subset; here:
# native build, fast suite incl. the process-level binary cluster test,
# then the benchmark smoke). Opt into the SF0.2 scale suite with
#   RUN_SF02=1 dev/integration_test.sh
set -euo pipefail
cd "$(dirname "$0")/.."

make -C ballista_tpu/native
python -m pytest tests/ -q
if [[ "${RUN_SF02:-0}" == "1" ]]; then
  python -m pytest tests/test_tpch_sf02.py -m sf02 -q
fi
python bench.py --cpu --scale 0.2 --runs 2
