#!/usr/bin/env python
"""Guard against metric names drifting out of the registry — thin shim
over the unified analysis engine (``ballista_tpu/analysis/``, rule id
``metric-names``; run everything at once with ``dev/analyze.py``).

CLI and exit semantics are unchanged from the standalone version:
exit 0 = clean, per-problem ``METRIC-NAME:`` lines on stderr otherwise.
Dynamic call sites still annotate with ``# metric-names: a b c``; the
machinery skip list lives on the rule
(``analysis/passes/shape.py::MetricNamesRule``).

Usage: python dev/check_metric_names.py   (exit 0 = clean)
"""

from __future__ import annotations

import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.normpath(os.path.join(HERE, ".."))
sys.path.insert(0, HERE)

import analyze  # noqa: E402 - sibling loader for the analysis engine


def main() -> int:
    analysis = analyze.load_analysis(REPO)
    pkg = analysis.Package.load(REPO)
    rule = analysis.RULE_FACTORIES["metric-names"]()
    result = analysis.analyze(pkg, [rule])
    problems = result.parse_errors + result.findings
    if problems:
        for f in problems:
            print(f"METRIC-NAME: {f.file}:{f.line}: {f.message}",
                  file=sys.stderr)
        print(
            f"{len(problems)} unregistered metric name(s) — "
            "register them in ballista_tpu/observability/registry.py "
            "(they feed /metrics export and docs/observability.md)",
            file=sys.stderr,
        )
        return 1
    from ballista_tpu.observability.registry import (
        OPERATOR_METRICS,
        PROCESS_METRICS,
    )

    print(f"all metric names registered "
          f"({len(OPERATOR_METRICS)} operator, "
          f"{len(PROCESS_METRICS)} process families)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
