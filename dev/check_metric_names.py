#!/usr/bin/env python
"""Guard against metric names drifting out of the registry.

Every ``add_counter("x")`` / ``add_time("x")`` / ``set_gauge("x")``
literal in ``ballista_tpu/**`` must name a metric registered in
``ballista_tpu/observability/registry.py::OPERATOR_METRICS`` — the same
table that gives the health plane its ``/metrics`` HELP/TYPE lines and
documents every name in docs/observability.md. A call site that builds
its name dynamically (e.g. ``add_time("elapsed_" + name, ...)``) must
carry a ``# metric-names: a b c`` annotation on the same line naming
every metric it can emit; those names are checked against the registry
too. Prometheus family literals passed to health-plane samples
(``("ballista_...", ...)``) are checked against ``PROCESS_METRICS``.

Wired into tier-1 (tests/test_profiler_health.py) next to
check_jit_sites.py / check_proto_sync.py.

Usage: python dev/check_metric_names.py   (exit 0 = clean)
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.abspath(os.path.join(HERE, ".."))
PKG = os.path.join(ROOT, "ballista_tpu")

sys.path.insert(0, ROOT)

from ballista_tpu.observability.registry import (  # noqa: E402
    OPERATOR_METRICS,
    PROCESS_METRICS,
)

_CALL = re.compile(r"\b(?:add_counter|add_time|set_gauge)\s*\(")
# a literal first argument ends at , or ) — "elapsed_" + name is DYNAMIC
_LITERAL_ARG = re.compile(r"^\s*(['\"])([^'\"]+)\1\s*[,)]")
_ANNOTATION = re.compile(r"#\s*metric-names:\s*([\w\s,-]+)")

# files whose add_*/set_gauge are the RECORDING MACHINERY itself (they
# re-emit caller-supplied names, checked at the caller)
SKIP_FILES = {
    "ballista_tpu/observability/metrics.py",
}
# generated code (the pb2 module's symbol strings trip the prometheus
# family pattern)
SKIP_DIRS = ("ballista_tpu/proto/",)


def scan() -> List[Tuple[str, int, str, str]]:
    problems: List[Tuple[str, int, str, str]] = []
    for root, _dirs, files in os.walk(PKG):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, ROOT).replace(os.sep, "/")
            if rel in SKIP_FILES or rel.startswith(SKIP_DIRS):
                continue
            for i, line in enumerate(open(path, encoding="utf-8"), 1):
                dynamic = False
                for m in _CALL.finditer(line):
                    rest = line[m.end():]
                    lit = _LITERAL_ARG.match(rest)
                    if lit is None:
                        dynamic = True
                        continue
                    name = lit.group(2)
                    if name not in OPERATOR_METRICS:
                        problems.append(
                            (rel, i, name,
                             "literal metric name not in "
                             "OPERATOR_METRICS registry"))
                # dynamic names need an annotation listing the space
                if dynamic:
                    ann = _ANNOTATION.search(line)
                    if ann is None:
                        problems.append(
                            (rel, i, line.strip()[:80],
                             "dynamic metric name without a "
                             "'# metric-names: ...' annotation"))
                    else:
                        for name in re.split(r"[\s,]+",
                                             ann.group(1).strip()):
                            if name and name not in OPERATOR_METRICS:
                                problems.append(
                                    (rel, i, name,
                                     "annotated metric name not in "
                                     "OPERATOR_METRICS registry"))
                # prometheus family literals in sample tuples
                for fam in re.findall(r"(['\"])(ballista_\w+)\1\s*,",
                                      line):
                    if fam[1] not in PROCESS_METRICS:
                        problems.append(
                            (rel, i, fam[1],
                             "prometheus family not in PROCESS_METRICS "
                             "registry"))
    return problems


def main() -> int:
    problems = scan()
    if problems:
        for rel, i, name, why in problems:
            print(f"METRIC-NAME: {rel}:{i}: {name!r}: {why}",
                  file=sys.stderr)
        print(
            f"{len(problems)} unregistered metric name(s) — register "
            "them in ballista_tpu/observability/registry.py (they feed "
            "/metrics export and docs/observability.md)",
            file=sys.stderr,
        )
        return 1
    print(f"all metric names registered "
          f"({len(OPERATOR_METRICS)} operator, "
          f"{len(PROCESS_METRICS)} process families)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
