"""Per-operator wall-time profiler for a standalone query.

Wraps every physical operator's execute() so each yielded batch
attributes the time spent producing it (enqueue + any host sync) to the
yielding operator. Device work is async, so time shows up wherever a
host sync blocks — exactly what we want to find over a high-latency
tunnel.

Usage: python dev/profile_query.py [--query q5] [--data benchmarks/bench_data/sf1]
"""

from __future__ import annotations

import argparse
import collections
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--query", default="q5")
    ap.add_argument("--data", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench_data", "sf1"))
    ap.add_argument("--runs", type=int, default=2)
    ap.add_argument("--sql", default=None,
                    help="profile this SQL string instead of --query")
    args = ap.parse_args()

    import jax

    from benchmarks.tpch.schema_def import register_tpch
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.physical.base import PhysicalPlan

    print(f"# platform: {jax.devices()[0].platform}", file=sys.stderr)

    ctx = BallistaContext.standalone()
    register_tpch(ctx, args.data, "tbl", cached=True)
    sql = args.sql or open(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "tpch", "queries", f"{args.query}.sql")).read()
    df = ctx.sql(sql)

    # one cold run to compile + warm caches
    t0 = time.perf_counter()
    df.collect()
    print(f"# cold: {time.perf_counter()-t0:.3f}s", file=sys.stderr)

    # instrument: wrap execute on the cached physical plan's nodes
    stats = collections.defaultdict(lambda: [0.0, 0])  # label -> [sec, batches]

    def wrap(node, seen):
        if id(node) in seen:
            return
        seen.add(id(node))
        label = node.display().split("\n")[0][:72]
        orig = node.execute

        def timed_execute(partition, _orig=orig, _label=label):
            it = _orig(partition)
            while True:
                t0 = time.perf_counter()
                try:
                    b = next(it)
                except StopIteration:
                    stats[_label][0] += time.perf_counter() - t0
                    return
                stats[_label][0] += time.perf_counter() - t0
                stats[_label][1] += 1
                yield b

        node.execute = timed_execute
        for c in node.children():
            wrap(c, seen)

    phys = getattr(df, "_phys", None)
    if phys is None:
        print("no cached physical plan (_phys); aborting", file=sys.stderr)
        sys.exit(1)
    wrap(phys, set())

    best = None
    for i in range(args.runs):
        for v in stats.values():
            v[0], v[1] = 0.0, 0
        t0 = time.perf_counter()
        df.collect()
        dt = time.perf_counter() - t0
        print(f"# run {i}: {dt:.3f}s", file=sys.stderr)
        if best is None or dt < best[0]:
            best = (dt, {k: tuple(v) for k, v in stats.items()})

    total, snap = best
    print(f"\n=== warm {args.query}: {total:.3f}s ===")
    acc = 0.0
    for label, (sec, nb) in sorted(snap.items(), key=lambda kv: -kv[1][0]):
        print(f"{sec:8.3f}s  {nb:5d} batches  {label}")
        acc += sec
    # note: parents include children's time (nested iteration), so the
    # sum exceeds wall; read top-down and compare levels
    print(f"# (nested totals; wall={total:.3f}s)")


if __name__ == "__main__":
    main()
