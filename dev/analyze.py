#!/usr/bin/env python
"""Unified static analysis driver — ALL passes, one process, one exit
code (tier-1 entry point; the old per-lint ``dev/check_*.py`` scripts
are thin shims over the same engine).

Usage::

    python dev/analyze.py [--baseline dev/analysis_baseline.json]
                          [--rules id1,id2] [--list-rules] [--json]
                          [--changed-only] [--write-baseline] [--root D]

- exit 0 when every finding is suppressed or baselined; 1 otherwise.
- ``--baseline`` defaults to ``dev/analysis_baseline.json`` when that
  file exists. Stale entries (triaged findings whose site was fixed)
  are reported as warnings; ``--write-baseline`` rewrites the file
  from the current findings (new entries carry a ``TRIAGE ME`` note —
  replace it with a justification before committing).
- ``--changed-only`` scopes reported findings to files touched per
  ``git diff --name-only HEAD`` (+ staged + untracked) — the fast
  pre-commit mode; package-scoped rules still analyze the whole tree.
- ``--json`` emits a machine-readable report on stdout.

The analysis package is loaded STANDALONE (no ``ballista_tpu/__init__``
execution, hence no jax import) so pure-AST runs are fast; the three
registry-backed rules import the live registries lazily and only then
pay the package import.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.normpath(os.path.join(HERE, ".."))

DEFAULT_BASELINE = os.path.join("dev", "analysis_baseline.json")


def load_analysis(repo_root: str = REPO):
    """Import ``<repo>/ballista_tpu/analysis`` as a standalone package
    (registered as ``_ballista_analysis``) without executing the parent
    package's ``__init__``. Registry-backed rules that do
    ``from ballista_tpu... import`` at run time still resolve the real
    package via ``repo_root`` on sys.path."""
    name = "_ballista_analysis"
    if name in sys.modules:
        return sys.modules[name]
    pkg_dir = os.path.join(repo_root, "ballista_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    spec.loader.exec_module(mod)
    return mod


def changed_files(repo_root: str):
    """Repo-relative paths touched vs HEAD (unstaged + staged +
    untracked) for --changed-only."""
    out = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            r = subprocess.run(args, cwd=repo_root, capture_output=True,
                               text=True, timeout=30)
        except Exception:  # noqa: BLE001 - no git: fall back to full run
            return None
        if r.returncode != 0:
            return None
        out.update(p.strip() for p in r.stdout.splitlines() if p.strip())
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=REPO)
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON path (default: "
                         f"{DEFAULT_BASELINE} when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--changed-only", action="store_true")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings")
    args = ap.parse_args(argv)

    analysis = load_analysis(args.root)

    if args.list_rules:
        for rid, factory in analysis.RULE_FACTORIES.items():
            print(f"{rid:18s} {factory.description}")
        return 0

    try:
        rules = (analysis.rules_for(args.rules.split(","))
                 if args.rules else analysis.all_rules())
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2

    baseline = None
    baseline_path = args.baseline
    if baseline_path is None:
        cand = os.path.join(args.root, DEFAULT_BASELINE)
        baseline_path = cand if os.path.exists(cand) else None
    elif not os.path.isabs(baseline_path):
        baseline_path = os.path.join(args.root, baseline_path)
    if baseline_path and not args.no_baseline and not args.write_baseline:
        baseline = analysis.Baseline.load(baseline_path)

    package = analysis.Package.load(args.root)
    only = None
    if args.changed_only and not args.write_baseline:
        # a baseline rewrite must always see the whole package — a
        # diff-scoped one would silently drop unchanged files' entries
        only = changed_files(args.root)
    result = analysis.analyze(package, rules, baseline, only_files=only)

    if args.write_baseline:
        if baseline_path is None:
            baseline_path = os.path.join(args.root, DEFAULT_BASELINE)
        previous = (analysis.Baseline.load(baseline_path)
                    if os.path.exists(baseline_path) else None)
        bl = analysis.Baseline.from_findings(result.findings,
                                             previous=previous)
        if previous is not None:
            # a --rules-scoped rewrite must not erase other rules'
            # triaged entries — carry them over untouched
            run_ids = {r.id for r in rules}
            bl.entries = sorted(
                [e for e in previous.entries
                 if e.get("rule") not in run_ids] + bl.entries,
                key=lambda e: (e.get("rule", ""), e.get("file", ""),
                               e.get("anchor", "")))
        bl.save(baseline_path)
        fresh = sum(1 for e in bl.entries if e.get("note") == "TRIAGE ME")
        print(f"wrote {len(bl.entries)} baseline entr"
              f"{'y' if len(bl.entries) == 1 else 'ies'} to "
              f"{os.path.relpath(baseline_path, args.root)} "
              f"({fresh} new) — replace every 'TRIAGE ME' note with a "
              "justification")
        return 0

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in result.findings],
            "parse_errors": [f.to_dict() for f in result.parse_errors],
            "baselined": len(result.baselined),
            "suppressed": result.suppressed,
            "stale_baseline": result.stale,
        }, indent=2))
        return 0 if result.ok else 1

    for f in result.parse_errors + result.findings:
        print(f.render(), file=sys.stderr)
    for e in result.stale:
        print(f"warning: stale baseline entry {e.get('rule')}: "
              f"{e.get('file')}: {e.get('anchor')!r} (fixed? prune with "
              "--write-baseline)", file=sys.stderr)
    n = len(result.findings) + len(result.parse_errors)
    if n:
        print(f"{n} finding(s) ({len(result.baselined)} baselined, "
              f"{result.suppressed} suppressed) — fix, suppress with "
              "'# ballista: ignore[rule]' + reason, or triage into the "
              "baseline", file=sys.stderr)
        return 1
    print(f"analysis clean: {len(rules)} rule(s), "
          f"{len(package.files)} files, {len(result.baselined)} "
          f"baselined, {result.suppressed} suppressed"
          + (f", {len(result.stale)} stale baseline entr"
             f"{'y' if len(result.stale) == 1 else 'ies'}"
             if result.stale else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
