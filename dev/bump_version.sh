#!/usr/bin/env bash
# Bump BALLISTA_TPU_VERSION (reference parity: dev/bump-version.sh seds
# across manifests).
set -euo pipefail
[[ $# == 1 ]] || { echo "usage: $0 <new-version>" >&2; exit 2; }
cd "$(dirname "$0")/.."
sed -i "s/^BALLISTA_TPU_VERSION = \".*\"/BALLISTA_TPU_VERSION = \"$1\"/" \
    ballista_tpu/__init__.py
grep -n "BALLISTA_TPU_VERSION" ballista_tpu/__init__.py
