#!/usr/bin/env python
"""Compare two bench.py JSON lines and fail on regression.

Intended invocation — OLD is the accepted baseline round, NEW is the
candidate (each file holds one or more JSON lines as bench.py prints
them; the LAST well-formed line wins, matching the parent watchdog's
salvage rule):

    python dev/check_bench_regress.py BENCH_r05.json BENCH_r06.json

Exit codes: 0 = no regression, 1 = at least one metric regressed past
its tolerance, 2 = usage / unreadable input. Each checked metric prints
one line (`ok` / `REGRESSED` / `skipped` when either side lacks it), so
a red run says exactly which lane or latency moved.

Per-metric tolerances are deliberately loose: bench runs on a noisy
shared box (the repo's measured run-to-run jitter on cold phases is
tens of percent), so only moves beyond the listed relative slack fail.
Scale them all at once with ``--tolerance-scale`` (e.g. 2.0 on a
particularly noisy box). Metrics the profiler added in PR 7
(``device_blocked_seconds`` / ``host_dictionary_seconds`` /
``compile_trace_lower_seconds``) make ROADMAP's lane-cited targets
(e.g. item 2's host_dictionary < 0.5s) regression-checkable from bench
output alone.

``--self-test`` runs the built-in check of the comparison logic
(tier-1 invokes it from tests/test_distributed_profiler.py).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Optional, Tuple

# metric -> (direction, relative tolerance). "lower" = lower is better.
METRICS: Dict[str, Tuple[str, float]] = {
    # headline throughput (rows/s, higher is better)
    "value": ("higher", 0.25),
    # latencies (seconds, lower is better)
    "warm_seconds": ("lower", 0.25),
    "cold_seconds": ("lower", 0.35),
    "first_run_seconds": ("lower", 0.35),
    "q5_first_seconds": ("lower", 0.35),
    "q5_warm_seconds": ("lower", 0.30),
    "q3_first_seconds": ("lower", 0.35),
    "q3_warm_seconds": ("lower", 0.30),
    "q18_first_seconds": ("lower", 0.35),
    "q18_warm_seconds": ("lower", 0.30),
    "q16_first_seconds": ("lower", 0.35),
    "q16_warm_seconds": ("lower", 0.30),
    # profiler lanes (PR 7; unprefixed = q5, PR 8 added q3/q18): the
    # ROADMAP's lane-cited targets
    "device_blocked_seconds": ("lower", 0.45),
    "host_dictionary_seconds": ("lower", 0.45),
    "compile_trace_lower_seconds": ("lower", 0.45),
    "q3_device_blocked_seconds": ("lower", 0.45),
    "q3_host_dictionary_seconds": ("lower", 0.45),
    "q3_compile_trace_lower_seconds": ("lower", 0.45),
    "q18_device_blocked_seconds": ("lower", 0.45),
    "q18_host_dictionary_seconds": ("lower", 0.45),
    "q18_compile_trace_lower_seconds": ("lower", 0.45),
    # PR 11 (dictionary registry): q16 is the string-heavy join query
    # pinning the host_dictionary lane — it may never silently regrow
    "q16_device_blocked_seconds": ("lower", 0.45),
    "q16_host_dictionary_seconds": ("lower", 0.45),
    "q16_compile_trace_lower_seconds": ("lower", 0.45),
    # resource envelope
    "peak_rss_mb": ("lower", 0.30),
    # live progress plane (PR 10): on_progress callbacks delivered
    # during the cold q5 run — a sampler that silently dies would read
    # 0. "nonzero": only 0 regresses. The raw count scales with cold-run
    # wall time, so a ratio gate would punish legitimate cold-time
    # speedups. Absent from pre-PR-10 baselines (compare() skips
    # missing keys).
    "progress_samples": ("nonzero", 0.0),
    # PR 12 (memory-governed streaming shuffle): the fixed-budget q5
    # cluster run. spill_bytes reads 0 if the spill lane silently dies;
    # the in-flight peak and the run's RSS must not regrow round-over-
    # round (the ABSOLUTE peak<=budget gate is budget_check below).
    "spill_bytes": ("nonzero", 0.0),
    "shuffle_peak_inflight_mb": ("lower", 0.50),
    "spill_q5_seconds": ("lower", 0.50),
    "spill_q5_peak_rss_mb": ("lower", 0.35),
    # PR 15 (admission plane): bench_serving.py — K concurrent mixed
    # TPC-H sessions against one warm LocalCluster. Throughput rides
    # "value" (higher) in that file; the latency percentiles must not
    # silently regrow round-over-round, and an engine error during the
    # storm (sheds are counted separately and are policy, not errors)
    # shows up as serving_completed dropping to 0.
    "serving_p50_seconds": ("lower", 0.40),
    "serving_p99_seconds": ("lower", 0.50),
    "serving_completed": ("nonzero", 0.0),
    # engine errors during the storm must stay ZERO (sheds are counted
    # separately — they are policy, not errors)
    "serving_errors": ("zero", 0.0),
    # PR 20 (latency ledger, docs/observability.md): the serving line
    # carries per-lane p50/p99 from the always-on per-query ledger. The
    # dominant lanes must not silently regrow (generous tolerance —
    # single-lane seconds are noisier than the end-to-end percentile),
    # and a storm that records no ledgers means the always-on
    # attribution plane is dead. Zero-baseline lanes (a workload that
    # never queued, say) are skipped by the o<=0 ratio-gate rule.
    "serving_ledgers": ("nonzero", 0.0),
    "serving_device_execute_p99_seconds": ("lower", 0.60),
    "serving_compile_p99_seconds": ("lower", 0.60),
    "serving_planning_p99_seconds": ("lower", 0.60),
    "serving_queue_wait_p99_seconds": ("lower", 0.60),
    "serving_shuffle_fetch_p99_seconds": ("lower", 0.60),
    # PR 17 (durable control plane): bench_serving.py --phase restart
    # times the rehydrate+recover gap of a scheduler restart over
    # sqlite; recovered_jobs reads 0 if the journal or the recovery
    # pass silently dies, and recovery errors are never acceptable.
    "recovery_seconds": ("lower", 0.50),
    "recovered_jobs": ("nonzero", 0.0),
    "recovery_errors": ("zero", 0.0),
    # --phase autoscale storms a min-sized fleet at 2x sessions: a
    # burst that triggers no scaling decision means the loop is dead,
    # and the burst's tail latency must not silently regrow.
    "autoscale_events": ("nonzero", 0.0),
    "autoscale_p99_seconds": ("lower", 0.50),
    "autoscale_errors": ("zero", 0.0),
    # PR 19 (warm-path serving caches, docs/caching.md): the cache
    # phase repeats q1 on a fresh residency tier. Warm/hit latencies
    # and speedups must not silently regrow; the per-line counters and
    # the byte-identity / budget-respect flags are aliveness gates (a
    # cache that silently stops hitting, donating or evicting reads 0).
    "cache_warm_q1_seconds": ("lower", 0.40),
    "cache_q1_speedup": ("higher", 0.40),
    "result_cache_hit_seconds": ("lower", 0.50),
    "result_cache_speedup": ("higher", 0.50),
    "table_cache_hits": ("nonzero", 0.0),
    "result_cache_hits": ("nonzero", 0.0),
    "donated_buffers": ("nonzero", 0.0),
    "cache_q1_identical": ("nonzero", 0.0),
    "result_cache_identical": ("nonzero", 0.0),
    "cache_budget_identical": ("nonzero", 0.0),
    "cache_budget_ok": ("nonzero", 0.0),
    "cache_budget_evictions": ("nonzero", 0.0),
}


def budget_check(new: dict) -> int:
    """Absolute gate for the fixed-budget q5 run: the governed in-flight
    peak must respect the configured shuffle memory budget (plus one
    chunk of slack — a charge is refused only once it would CROSS the
    watermark). Returns the number of violations."""
    peak = new.get("shuffle_peak_inflight_mb")
    budget = new.get("spill_budget_mb")
    if peak is None or budget is None:
        return 0
    slack = float(new.get("spill_chunk_mb", 4.0))
    if float(peak) > float(budget) + slack:
        print(f"regressed  shuffle_peak_inflight_mb: {peak} MB exceeds "
              f"the configured budget {budget} MB (+{slack} MB chunk "
              "slack)")
        return 1
    print(f"ok         shuffle_peak_inflight_mb: {peak} MB within "
          f"budget {budget} MB")
    return 0


def last_json_line(path: str) -> Optional[dict]:
    """The bench line in the file. Accepts both raw bench.py output
    (JSON lines; the LAST well-formed one wins — bench prints partial
    snapshots first, and the watchdog salvages the same way) and the
    driver's archived wrapper format (BENCH_rNN.json: one pretty-printed
    object with the bench line under ``parsed``)."""
    try:
        text = open(path).read()
    except OSError as e:
        print(f"error: cannot read {path}: {e}", file=sys.stderr)
        return None
    try:
        whole = json.loads(text)
        if isinstance(whole, dict):
            if isinstance(whole.get("parsed"), dict):
                return whole["parsed"]
            return whole
    except ValueError:
        pass
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            return json.loads(line)
        except ValueError:
            continue
    print(f"error: no JSON line in {path}", file=sys.stderr)
    return None


def compare(old: dict, new: dict, tolerance_scale: float = 1.0) -> list:
    """Returns [(metric, old, new, rel_change, regressed, checked)].
    ``rel_change`` is signed so the report reads naturally: positive =
    the metric moved in the WORSE direction."""
    rows = []
    for metric, (direction, tol) in METRICS.items():
        if metric not in old or metric not in new:
            rows.append((metric, old.get(metric), new.get(metric),
                         None, False, False))
            continue
        o, n = float(old[metric]), float(new[metric])
        if direction == "nonzero":
            # aliveness gate: regress only when a previously-reporting
            # metric reads 0 now (magnitude is wall-time-coupled noise)
            regressed = o > 0 and n <= 0
            rows.append((metric, o, n, 1.0 if regressed else 0.0,
                         regressed, True))
            continue
        if direction == "zero":
            # hard-zero gate: any nonzero NEW value regresses (the old
            # value is irrelevant — errors are never acceptable)
            regressed = n > 0
            rows.append((metric, o, n, 1.0 if regressed else 0.0,
                         regressed, True))
            continue
        if o <= 0:
            rows.append((metric, o, n, None, False, False))
            continue
        if direction == "lower":
            rel = (n - o) / o  # got slower/bigger = worse
        else:
            rel = (o - n) / o  # got smaller = worse
        regressed = rel > tol * tolerance_scale
        rows.append((metric, o, n, rel, regressed, True))
    return rows


def report(rows, tolerance_scale: float) -> int:
    bad = 0
    for metric, o, n, rel, regressed, checked in rows:
        if not checked:
            print(f"skipped    {metric}: missing on one side "
                  f"(old={o!r} new={n!r})")
            continue
        direction, tol = METRICS[metric]
        tol *= tolerance_scale
        tag = "REGRESSED" if regressed else "ok"
        if regressed:
            bad += 1
        print(f"{tag:<10} {metric}: {o:g} -> {n:g} "
              f"({rel:+.1%} worse-direction, tol {tol:.0%}, "
              f"{direction} is better)")
    if bad:
        print(f"{bad} metric(s) regressed past tolerance",
              file=sys.stderr)
    return 1 if bad else 0


def self_test() -> int:
    """Pin the comparison semantics this script promises."""
    old = {"value": 1000.0, "warm_seconds": 1.0,
           "host_dictionary_seconds": 2.0, "peak_rss_mb": 1000.0}
    # within tolerance: slightly slower warm, slightly lower throughput
    ok_new = {"value": 900.0, "warm_seconds": 1.1,
              "host_dictionary_seconds": 1.0, "peak_rss_mb": 1100.0}
    rows = compare(old, ok_new)
    assert not any(r[4] for r in rows), rows
    # a big warm slowdown regresses; an IMPROVEMENT never does
    bad_new = {"value": 5000.0, "warm_seconds": 2.0}
    rows = {r[0]: r for r in compare(old, bad_new)}
    assert rows["warm_seconds"][4] is True
    assert rows["value"][4] is False
    # higher-is-better: a big throughput drop regresses
    rows = {r[0]: r for r in compare(old, {"value": 500.0})}
    assert rows["value"][4] is True
    # missing metrics are skipped, never failed
    assert all(not r[4] for r in compare(old, {}))
    # tolerance scaling loosens the gate
    rows = {r[0]: r for r in compare(old, {"warm_seconds": 1.4},
                                     tolerance_scale=2.0)}
    assert rows["warm_seconds"][4] is False
    # zero/absent baselines are skipped (cannot compute a ratio)
    assert not any(r[4] for r in compare({"value": 0.0},
                                         {"value": 10.0}))
    # nonzero metrics: only a drop to 0 regresses — a faster cold run
    # delivering FEWER samples must never fail the gate
    rows = {r[0]: r for r in compare({"progress_samples": 8},
                                     {"progress_samples": 2})}
    assert rows["progress_samples"][4] is False
    rows = {r[0]: r for r in compare({"progress_samples": 8},
                                     {"progress_samples": 0})}
    assert rows["progress_samples"][4] is True
    # absolute budget gate: in-flight peak past budget+chunk fails,
    # within it passes, absent fields are a no-op
    assert budget_check({"shuffle_peak_inflight_mb": 7.5,
                         "spill_budget_mb": 8.0,
                         "spill_chunk_mb": 1.0}) == 0
    assert budget_check({"shuffle_peak_inflight_mb": 20.0,
                         "spill_budget_mb": 8.0,
                         "spill_chunk_mb": 1.0}) == 1
    assert budget_check({}) == 0
    # zero metrics: ANY nonzero new value regresses, improvement to 0
    # never does
    rows = {r[0]: r for r in compare({"serving_errors": 0},
                                     {"serving_errors": 2})}
    assert rows["serving_errors"][4] is True
    rows = {r[0]: r for r in compare({"serving_errors": 3},
                                     {"serving_errors": 0})}
    assert rows["serving_errors"][4] is False
    # restart phase: recovery_seconds is lower-is-better — a FASTER
    # recovery must never regress, a 2x slower one must
    rows = {r[0]: r for r in compare({"recovery_seconds": 2.0},
                                     {"recovery_seconds": 0.5})}
    assert rows["recovery_seconds"][4] is False
    rows = {r[0]: r for r in compare({"recovery_seconds": 1.0},
                                     {"recovery_seconds": 2.0})}
    assert rows["recovery_seconds"][4] is True
    # recovered_jobs / autoscale_events are aliveness gates: only a
    # drop to 0 regresses (fewer jobs in the batch is configuration)
    rows = {r[0]: r for r in compare({"recovered_jobs": 6},
                                     {"recovered_jobs": 0})}
    assert rows["recovered_jobs"][4] is True
    rows = {r[0]: r for r in compare({"autoscale_events": 4},
                                     {"autoscale_events": 1})}
    assert rows["autoscale_events"][4] is False
    # recovery/autoscale errors: hard zero
    rows = {r[0]: r for r in compare({"recovery_errors": 0},
                                     {"recovery_errors": 1})}
    assert rows["recovery_errors"][4] is True
    # cache phase (PR 19): warm latency is lower-is-better, speedup is
    # higher-is-better — a faster warm run / bigger speedup never fails
    rows = {r[0]: r for r in compare(
        {"cache_warm_q1_seconds": 0.10, "cache_q1_speedup": 10.0},
        {"cache_warm_q1_seconds": 0.30, "cache_q1_speedup": 2.0})}
    assert rows["cache_warm_q1_seconds"][4] is True
    assert rows["cache_q1_speedup"][4] is True
    rows = {r[0]: r for r in compare(
        {"result_cache_hit_seconds": 0.05, "result_cache_speedup": 5.0},
        {"result_cache_hit_seconds": 0.01, "result_cache_speedup": 50.0})}
    assert not any(r[4] for r in rows.values())
    # identity / budget-respect flags and the live counters are
    # aliveness gates: a drop to 0 regresses, a smaller count does not
    rows = {r[0]: r for r in compare(
        {"cache_q1_identical": 1, "cache_budget_ok": 1,
         "donated_buffers": 18, "table_cache_hits": 4},
        {"cache_q1_identical": 0, "cache_budget_ok": 1,
         "donated_buffers": 0, "table_cache_hits": 1})}
    assert rows["cache_q1_identical"][4] is True
    assert rows["cache_budget_ok"][4] is False
    assert rows["donated_buffers"][4] is True
    assert rows["table_cache_hits"][4] is False
    # ledger lanes (PR 20): lower-is-better with a generous tolerance —
    # a lane p99 that more than doubles regresses, one that shrinks
    # never does, and a zero-baseline lane (never exercised) is skipped
    # rather than tripping a divide-by-zero ratio
    rows = {r[0]: r for r in compare(
        {"serving_device_execute_p99_seconds": 1.0,
         "serving_compile_p99_seconds": 0.5,
         "serving_queue_wait_p99_seconds": 0.0},
        {"serving_device_execute_p99_seconds": 2.5,
         "serving_compile_p99_seconds": 0.2,
         "serving_queue_wait_p99_seconds": 0.4})}
    assert rows["serving_device_execute_p99_seconds"][4] is True
    assert rows["serving_compile_p99_seconds"][4] is False
    assert rows["serving_queue_wait_p99_seconds"][5] is False  # skipped
    # serving_ledgers is an aliveness gate: the always-on plane going
    # silent regresses; recording fewer ledgers does not
    rows = {r[0]: r for r in compare({"serving_ledgers": 24},
                                     {"serving_ledgers": 0})}
    assert rows["serving_ledgers"][4] is True
    rows = {r[0]: r for r in compare({"serving_ledgers": 24},
                                     {"serving_ledgers": 6})}
    assert rows["serving_ledgers"][4] is False
    print("self-test ok")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="compare two bench.py JSON files; non-zero exit on "
                    "regression")
    ap.add_argument("old", nargs="?", help="baseline bench JSON file")
    ap.add_argument("new", nargs="?", help="candidate bench JSON file")
    ap.add_argument("--tolerance-scale", type=float, default=1.0,
                    help="multiply every per-metric tolerance "
                         "(noisy boxes: try 2.0)")
    ap.add_argument("--self-test", action="store_true",
                    help="run the built-in comparison-logic checks")
    args = ap.parse_args()
    if args.self_test:
        return self_test()
    if not args.old or not args.new:
        ap.print_usage(sys.stderr)
        return 2
    old = last_json_line(args.old)
    new = last_json_line(args.new)
    if old is None or new is None:
        return 2
    rc = report(compare(old, new, args.tolerance_scale),
                args.tolerance_scale)
    return rc or (1 if budget_check(new) else 0)


if __name__ == "__main__":
    sys.exit(main())
