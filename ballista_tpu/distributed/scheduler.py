"""Scheduler: control-plane gRPC service + planning pipeline.

Re-implements the reference scheduler (reference: rust/scheduler/src/
lib.rs — the 5 SchedulerGrpc RPCs; execute_query background planning at
:224-407, poll_work assignment at :105-182). Differences by design:

- task assignment pops an event-driven ready-queue (see state.py) instead
  of scanning all tasks under a global lock;
- executors run tasks in-process (no self-RPC hop; the reference itself
  flags its own as convoluted, execution_loop.rs:90-91).
"""

from __future__ import annotations

import functools
import logging
import os
import random
import string
import threading
import time
from concurrent import futures
from typing import Dict, Optional

import grpc

from ..errors import ClusterError
from ..execution import plan_logical
from ..observability import trace_span
from ..proto import ballista_pb2 as pb
from ..testing.faults import fault_point
from .. import serde
from .planner import (
    DistributedPlanner,
    find_unresolved_shuffles,
    remove_unresolved_shuffles,
)
from .state import SchedulerState
from .types import ExecutorMeta, JobStatus, PartitionId, TaskStatus

log = logging.getLogger("ballista.scheduler")

SERVICE = "ballista_tpu.SchedulerGrpc"

# Control-plane messages are small EXCEPT the distributed-profiler
# payloads: a PollWork carrying several completed tasks' profile
# windows (512 KiB each), and a GetJobProfile response serializing a
# whole merged artifact. gRPC's 4 MB default receive limit would fail
# exactly the jobs worth profiling — and a failed PollWork LOSES the
# completion reports it carried (the executor clears its pending list
# before the RPC). Applied to the server and every channel.
_GRPC_MSG_OPTS = [
    ("grpc.max_send_message_length", 64 << 20),
    ("grpc.max_receive_message_length", 64 << 20),
]


def _fuse_mesh_stages(stages, n_mesh: int):
    """ICI fast path: collapse a hash-shuffle stage + its final-aggregate
    consumer into ONE MeshAggExec stage that runs the shuffle as an
    in-SPMD ``lax.all_to_all`` over the executor's device mesh instead of
    writing N^2 shuffle files through the data plane (the model being
    replaced: location-resolved file fetches, reference
    rust/scheduler/src/planner.rs:236-269 + shuffle_reader.rs:77-99).

    ``n_mesh`` is the CLUSTER-resolved mesh width (executor-reported
    device counts, see ``_cluster_mesh_devices``), not a client hint;
    < 2 disables fusion. Pattern matched exactly: consumer stage whose
    plan is HashAggregateExec(final) over UnresolvedShuffleExec([S])
    where S is a hash-shuffle stage."""
    from ..physical import operators as ops
    from ..physical.aggregate import HashAggregateExec
    from ..physical.join import JoinExec
    from ..physical.mesh_agg import MeshAggExec, MeshJoinExec
    from ..physical.shuffle import QueryStageExec, UnresolvedShuffleExec

    if n_mesh < 2:
        return stages
    from collections import Counter

    # by_id is kept UP TO DATE with rewritten stages, so a consumer
    # fusing later absorbs the fused producer subtree (chained joins),
    # never a stale child with dangling references to dropped stages
    by_id = {s.stage_id: s for s in stages}
    refcount = Counter(
        sid
        for s in stages
        for u in find_unresolved_shuffles(s.child)
        for sid in u.query_stage_ids
    )
    fused = []
    dropped = set()
    for stage in stages:
        # walk through single-child vertical wrappers (output projection,
        # HAVING filter) to the final aggregate
        wrappers = []
        plan = stage.child
        while isinstance(plan, (ops.ProjectionExec, ops.FilterExec)):
            wrappers.append(plan)
            plan = plan.children()[0]
        def _shuffle_producer(node):
            """The single hash-shuffle producer stage behind an
            UnresolvedShuffleExec (referenced nowhere else, so dropping
            it is safe), or None."""
            if not (isinstance(node, UnresolvedShuffleExec)
                    and len(node.query_stage_ids) == 1):
                return None
            sid = node.query_stage_ids[0]
            prod = by_id.get(sid)
            if prod is None or sid in dropped or refcount[sid] != 1 \
                    or not prod.shuffle_output_partitions \
                    or not prod.shuffle_hash_exprs:
                return None
            return prod

        new_plan = None
        if isinstance(plan, HashAggregateExec) and plan.mode == "final":
            producer = _shuffle_producer(plan.child)
            if producer is not None:
                dropped.add(producer.stage_id)
                new_plan = MeshAggExec(
                    producer.child, plan.group_exprs, plan.agg_exprs,
                    list(producer.shuffle_hash_exprs), n_mesh,
                    plan.group_capacity,
                )
                log.info("fused stages %d+%d into a %d-device mesh "
                         "shuffle-agg", producer.stage_id, stage.stage_id,
                         n_mesh)
        else:
            # partitioned-join fusion: the JoinExec may sit anywhere in
            # the stage plan (e.g. under a partial aggregate) — replace
            # the subtree; everything above it runs on host over the
            # fused single-partition output
            def replace_join(node):
                if isinstance(node, JoinExec) and node.partitioned:
                    bprod = _shuffle_producer(node.build)
                    pprod = _shuffle_producer(node.probe)
                    if bprod is not None and pprod is not None:
                        dropped.update({bprod.stage_id, pprod.stage_id})
                        log.info(
                            "fused stages %d+%d+%d into a %d-device mesh "
                            "shuffle-join (how=%s)", bprod.stage_id,
                            pprod.stage_id, stage.stage_id, n_mesh,
                            node.how)
                        return MeshJoinExec(bprod.child, pprod.child,
                                            node.on, node.how, n_mesh,
                                            null_aware=node.null_aware)
                kids = node.children()
                if not kids:
                    return node
                new_kids = [replace_join(c) for c in kids]
                if all(a is b for a, b in zip(kids, new_kids)):
                    return node
                return node.with_new_children(new_kids)

            replaced = replace_join(plan)
            if replaced is not plan:
                new_plan = replaced
        if new_plan is None:
            fused.append(stage)
            continue
        for w in reversed(wrappers):
            new_plan = w.with_new_children([new_plan])
        # PRESERVE the stage's own shuffle spec: a fused stage may itself
        # feed a downstream shuffle (e.g. one partitioned join in a chain
        # of them) — its single task then hash-splits its output as usual
        rebuilt = QueryStageExec(
            stage.job_id, stage.stage_id, new_plan,
            shuffle_hash_exprs=stage.shuffle_hash_exprs,
            shuffle_output_partitions=stage.shuffle_output_partitions,
        )
        by_id[stage.stage_id] = rebuilt
        fused.append(rebuilt)
    return [s for s in fused if s.stage_id not in dropped]


def _cluster_mesh_devices(state: SchedulerState, settings,
                          wait_secs: float = 3.0) -> int:
    """Mesh width for fusion, resolved from EXECUTOR-REPORTED device
    counts (each PollWork carries ``metadata.num_devices``) — the cluster
    truth — rather than the client's ``mesh.devices`` hint. Rules:

    - fleet uniformly reports n >= 2  -> fuse over n devices;
    - fleet reports mixed counts      -> no fusion (warned), unless the
      client claimed a width — then fail the job loudly;
    - a client claim that contradicts the uniform fleet is an ERROR: a
      lying (or stale) client must not change plan shape silently;
    - no executors registered yet: wait briefly only if the client
      claimed a mesh (cluster startup), else plan unfused.
    """
    try:
        claimed = int((settings or {}).get("mesh.devices", "0"))
    except ValueError:
        claimed = 0
    metas = state.get_executors_metadata()
    if not metas and claimed >= 2:
        deadline = time.time() + wait_secs
        while not metas and time.time() < deadline:
            time.sleep(0.1)
            metas = state.get_executors_metadata()
    if not metas:
        return 0
    reported = sorted({m.num_devices or 1 for m in metas})
    if len(reported) > 1:
        if claimed >= 2:
            raise ClusterError(
                f"mesh.devices={claimed} requested but executors report "
                f"mixed device counts {reported}; mesh fusion needs a "
                "uniform fleet"
            )
        log.warning("executors report mixed device counts %s: mesh "
                    "fusion disabled", reported)
        return 0
    n = reported[0]
    if claimed >= 2 and claimed != n:
        raise ClusterError(
            f"client requested mesh.devices={claimed} but executors "
            f"uniformly report {n} device(s); refusing to plan against "
            "the claimed mesh"
        )
    return n if n >= 2 else 0


def _mesh_requirement(plan) -> int:
    """Devices a task of this stage needs (max over mesh-fused nodes;
    0 = any executor). Drives device-aware task assignment."""
    from ..physical.mesh_agg import MeshAggExec, MeshJoinExec

    need = (plan.n_devices
            if isinstance(plan, (MeshAggExec, MeshJoinExec)) else 0)
    for c in plan.children():
        need = max(need, _mesh_requirement(c))
    return need


def _job_id() -> str:
    # 7-char alphanumeric starting with a letter (reference: lib.rs:262-270)
    first = random.choice(string.ascii_lowercase)
    rest = "".join(random.choices(string.ascii_lowercase + string.digits, k=6))
    return first + rest


class SchedulerService:
    def __init__(self, state: SchedulerState,
                 speculation_age_secs: float = 60.0,
                 metrics_port: "int | None" = None):
        self.state = state
        # duplicate straggler tasks older than this when executors idle;
        # 0 disables
        self.speculation_age_secs = speculation_age_secs
        # adaptive query execution: re-plan not-yet-started stages from
        # observed stage metrics on every stage completion (per-job
        # knobs ride the query settings; see adaptive/replanner.py)
        from ..adaptive.replanner import replan_on_stage_complete

        state.replan_hook = replan_on_stage_complete
        # distributed profiler: the scheduler's own spans carry its
        # identity; executor task-profile payloads (riding CompletedTask
        # through PollWork) collect per job and merge — with the
        # scheduler's flight-recorder window — into ONE Chrome-trace
        # artifact per job (ambient BALLISTA_PROFILE, slow-query
        # retroactive dump, GetJobProfile RPC, /debug/profile/<job_id>)
        from ..observability.distributed import JobProfileCollector
        from ..observability.tracing import set_process_identity

        set_process_identity("scheduler")
        self.profiles = JobProfileCollector()
        # live progress plane (observability/progress.py): executor
        # TaskProgress piggybacks fold into per-stage completion
        # fractions + ETAs, served through GetJobStatus, /debug/jobs,
        # Prometheus gauges and the system.tasks/system.stages tables
        from ..observability.progress import JobProgressTracker

        self.progress = JobProgressTracker(state=state)
        # admission plane (distributed/admission.py): every
        # ExecuteQuery passes the gate; queued submissions hold their
        # planning args here until the pump admits (or sheds) them
        from .admission import AdmissionController

        self.admission = AdmissionController(
            state=state, launch_fn=self._launch_job,
            shed_fn=self._shed_queued_job)
        # durable control plane (distributed/controlplane/): accepted
        # submissions journal through the state's KvBackend at decision
        # time so a restarted scheduler rebuilds its admission queue and
        # replays planning lost mid-flight; observed stage costs persist
        # per plan digest and steer the NEXT initial plan. Both degrade
        # to in-memory (loudly) on backend errors — never refuse work.
        from .controlplane import ControlPlaneJournal, CostFeedbackStore

        self.journal = ControlPlaneJournal(state)
        self.costs = CostFeedbackStore(state)
        # elasticity: attach_autoscaler() installs the decision loop;
        # drain_requests carries scale-down targets to their executors
        # on the next PollWork (PollWorkResult.drain piggyback)
        self.autoscaler = None
        self.drain_requests: set = set()
        # merge/render/write of terminal-job artifacts runs here, OFF
        # the RPC handler threads (thread created lazily on first use:
        # unprofiled schedulers never spawn it)
        self._profile_pool = futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="profile-build")
        state.profile_hook = self._on_job_terminal
        # health plane: /healthz + /metrics + /debug/queries. The
        # scheduler's /metrics additionally aggregates the resource
        # gauges executors ship with every heartbeat.
        from ..observability.health import (maybe_start_health_server,
                                            metrics_port_from_env)

        # system.* tables (observability/systables.py): the scheduler
        # owns the cluster-wide snapshot — query ring + durable
        # history, per-job operator metrics, executor heartbeats — and
        # serves it to remote scans over GetSystemTable
        from ..observability.systables import OperatorStore, SystemSnapshot

        self.system_ops = OperatorStore()
        self.systables = SystemSnapshot(
            query_log=state.query_log, operators=self.system_ops,
            executors_fn=self._executor_rows,
            tasks_fn=self.progress.task_rows,
            stages_fn=self.progress.stage_rows,
            admission_fn=self.admission.decision_rows,
            autoscaler_fn=self._autoscaler_rows,
        )
        # system.queries / /debug/queries: queued rows carry their live
        # admission-queue position
        state.queue_info_fn = self.admission.queue_info
        # latency ledger (observability/ledger.py): scheduler-side
        # phase stamps (admission_wait/queue_wait/planning) accumulate
        # here per job until the terminal hook assembles the full
        # ledger; the admission pump stamps queue waits on admit
        self._ledger_stamps = {}
        self._ledger_lock = threading.Lock()
        self.admission.queue_wait_fn = (
            lambda job_id, wait: self._ledger_stamp(
                job_id, "queue_wait", wait))
        self.tasks_dispatched = 0
        if metrics_port is None:
            metrics_port = metrics_port_from_env(-1)
        self.health = maybe_start_health_server(
            "scheduler", metrics_port, samples_fn=self._metric_samples,
            query_log=state.query_log,
            profile_fn=self._profile_artifact,
            jobs_fn=self._debug_jobs,
        )

    def _metric_samples(self):
        st = self.state
        metas = st.get_executors_metadata()
        out = [
            ("ballista_executors_live", {}, len(metas)),
            ("ballista_jobs_submitted_total", {}, st.jobs_submitted),
            ("ballista_jobs_completed_total", {}, st.jobs_completed),
            ("ballista_jobs_failed_total", {}, st.jobs_failed),
            ("ballista_jobs_cancelled_total", {}, st.jobs_cancelled),
            ("ballista_tasks_dispatched_total", {}, self.tasks_dispatched),
            ("ballista_ready_queue_depth", {}, st.ready_queue_depth()),
            ("ballista_slow_queries_total", {}, st.query_log.slow_total),
            # admission plane: queue depth + the decision counters
            ("ballista_admission_queue_depth", {},
             self.admission.queue_depth()),
            ("ballista_admission_admitted_total", {},
             self.admission.admitted_total),
            ("ballista_admission_queued_total", {},
             self.admission.queued_total),
            ("ballista_admission_sheds_total", {},
             self.admission.sheds_total),
        ]
        if self.autoscaler is not None:
            out.extend([
                ("ballista_autoscale_target_executors", {},
                 self.autoscaler.target),
                ("ballista_autoscale_ups_total", {},
                 self.autoscaler.scale_ups_total),
                ("ballista_autoscale_downs_total", {},
                 self.autoscaler.scale_downs_total),
            ])
        # live progress gauges: per-job completion fraction + the
        # cluster-wide running-task count (gated through the registry
        # like every family; live jobs are bounded by the tracker cap)
        try:
            live = self.progress.live_snapshots()
        except Exception:  # noqa: BLE001 - diagnosis plane
            live = []
        out.append(("ballista_tasks_running", {},
                    sum(s["tasks_running"] for s in live)))
        for s in live:
            out.append(("ballista_job_progress_fraction",
                        {"job": s["job_id"]}, s["fraction"]))
        for m in metas:
            # getattr: a durable backend may still hold ExecutorMeta
            # pickles written by pre-resources code (unpickling skips
            # dataclass defaults), and one AttributeError here would
            # blank EVERY scheduler sample until the lease expires
            res = getattr(m, "resources", None) or {}
            labels = {"executor": m.id[:8]}
            out.append(("ballista_executor_rss_bytes", labels,
                        res.get("rss_bytes", 0)))
            out.append(("ballista_executor_device_bytes", labels,
                        res.get("device_bytes", 0)))
            out.append(("ballista_executor_inflight_tasks", labels,
                        res.get("inflight_tasks", 0)))
            out.append(("ballista_executor_ingest_pool_depth", labels,
                        res.get("ingest_pool_depth", 0)))
            out.append(("ballista_executor_peak_host_bytes", labels,
                        res.get("peak_host_bytes", 0)))
        return out

    def _executor_rows(self):
        """system.executors rows from the executor heartbeat metadata
        (same source as the /metrics per-executor gauges). Built from
        the DURABLE address records so a dead executor stays visible
        from SQL: ``heartbeat_age_seconds`` is the scheduler-side clock
        minus the last PollWork, and rows past
        ``BALLISTA_EXECUTOR_STALE_SECS`` (or with no heartbeat this
        scheduler lifetime) carry ``stale=true``."""
        from ..observability.progress import executor_stale_secs

        beats = self.state.executor_heartbeats()
        thr = executor_stale_secs()
        now = time.time()
        rows = []
        for m in self.state.all_executor_metadata():
            res = getattr(m, "resources", None) or {}
            hb = beats.get(m.id)
            age = (now - hb) if hb is not None else None
            rows.append({
                "executor_id": m.id,
                "host": m.host,
                "port": m.port,
                "num_devices": m.num_devices or 1,
                "rss_bytes": res.get("rss_bytes"),
                "device_bytes": res.get("device_bytes"),
                "inflight_tasks": res.get("inflight_tasks"),
                "ingest_pool_depth": res.get("ingest_pool_depth"),
                "peak_host_bytes": res.get("peak_host_bytes"),
                "shuffle_inflight_bytes": res.get("shuffle_inflight_bytes"),
                "spill_bytes_total": res.get("spill_bytes_total"),
                "heartbeat_age_seconds": round(age, 3)
                if age is not None else None,
                "stale": int(age is None or age > thr),
            })
        return rows

    def _autoscaler_rows(self):
        """system.autoscaler rows (empty until attach_autoscaler)."""
        if self.autoscaler is None:
            return []
        return self.autoscaler.decision_rows()

    def _debug_jobs(self, job_id: "str | None"):
        """``/debug/jobs`` (job_id None: every live job) and
        ``/debug/jobs/<job_id>`` (live or recently terminal). Queued
        jobs carry their admission-queue position/reason."""
        def enrich(snap):
            if snap and snap.get("status") == "queued":
                info = self.admission.queue_info(snap["job_id"])
                if info:
                    snap = {**snap, **info}
            return snap

        if job_id:
            return enrich(self.progress.snapshot(job_id))
        return [enrich(s) for s in self.progress.live_snapshots()]

    def begin_drain(self):
        """Degrade to rejecting NEW work while admitted work finishes
        (the admission ladder's terminal rung; scheduler_main flips it
        on SIGTERM before waiting out live jobs)."""
        self.admission.begin_drain()

    def close_health(self):
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if self.health is not None:
            self.health.close()
        self._profile_pool.shutdown(wait=False)

    # -- durable control plane ----------------------------------------------

    def recover(self):
        """One explicit restart-recovery pass over the durable backend
        (controlplane/recovery.py): re-queue journaled submissions,
        replay planning lost mid-flight, distrust unroutable shuffle
        outputs, fail orphans loudly. Call once, BEFORE executors poll.
        Returns the :class:`RecoveryReport`."""
        from .controlplane import recover as _recover

        return _recover(self)

    def attach_autoscaler(self, config, spawn_fn, drain_fn=None,
                          start=True):
        """Install the demand-driven autoscaler over this scheduler's
        own signals (ready+admission backlog, in-flight task gauges,
        live executor count, max live-job ETA). ``drain_fn`` defaults
        to flagging the least-loaded live executor for a graceful
        drain via the PollWorkResult piggyback."""
        from .controlplane import Autoscaler

        def signal_fn():
            eta = 0.0
            try:
                for s in self.progress.live_snapshots():
                    eta = max(eta, float(s.get("eta_seconds") or 0.0))
            except Exception:  # noqa: BLE001 - advisory signal
                pass
            metas = self.state.get_executors_metadata()
            inflight = 0
            for m in metas:
                res = getattr(m, "resources", None) or {}
                inflight += int(res.get("inflight_tasks") or 0)
            return {
                # admission backlog counts only ADMITTABLE queued jobs:
                # work held by its own session quota must not trigger
                # scale-up (admission.admittable_queue_depth)
                "backlog": self.state.ready_queue_depth()
                + self.admission.admittable_queue_depth(),
                "inflight": inflight,
                "executors": len(metas),
                "eta_seconds": eta,
            }

        if drain_fn is None:
            drain_fn = self._drain_one_executor
        self.autoscaler = Autoscaler(config, signal_fn, spawn_fn,
                                     drain_fn)
        if start:
            self.autoscaler.start()
        return self.autoscaler

    def _drain_one_executor(self):
        """Default scale-down hook: flag the least-loaded live executor
        not already draining; its next PollWork carries ``drain=True``
        and the executor stops accepting tasks, exiting via its own
        drain path once idle."""
        metas = self.state.get_executors_metadata()
        candidates = [m for m in metas if m.id not in self.drain_requests]
        if not candidates:
            return None

        def load(m):
            res = getattr(m, "resources", None) or {}
            return int(res.get("inflight_tasks") or 0)

        target = min(candidates, key=load)
        self.drain_requests.add(target.id)
        return target.id

    # -- distributed profiler ------------------------------------------------

    def _ledger_stamp(self, job_id: str, phase: str, secs: float) -> None:
        """Accumulate one scheduler-side latency-ledger phase for
        assembly at the job's terminal transition (best-effort)."""
        with self._ledger_lock:
            st = self._ledger_stamps.setdefault(job_id, {})
            st[phase] = st.get(phase, 0.0) + float(secs)

    def _on_job_terminal(self, job_id: str, summary: dict, status) -> None:
        """state.profile_hook: runs once per job at its terminal
        transition, BEFORE the summary enters the query log. Observes
        the per-stage duration histograms, and — under ambient
        ``BALLISTA_PROFILE`` or for a slow query — builds the merged
        artifact, writes it, and links it from the summary so
        ``/debug/queries`` points straight at the evidence. Only the
        ring snapshot happens here: the hook runs on the PollWork
        handler thread (inside ``save_job_status``), so the expensive
        merge/render/write is handed to a single background worker —
        a multi-megabyte artifact must not stall task handout."""
        from ..observability import profiler as obs_profiler
        from ..observability import systables, tracing
        from ..observability.distributed import slow_query_dir
        from ..observability.health import slow_query_secs
        from ..observability.registry import observe_histogram

        self.profiles.finalize(job_id, summary)
        # latency ledger: scheduler stamps + the summed per-task
        # ``ledger.*`` deltas that rode CompletedTask profiles — cheap
        # (no ring scan, no artifact work), so it runs inline and the
        # job's rows are queryable the moment its status is terminal
        try:
            from ..observability import ledger as obs_ledger

            with self._ledger_lock:
                stamps = self._ledger_stamps.pop(job_id, {})
            obs_ledger.record_ledger(obs_ledger.assemble_job_ledger(
                job_id, float(summary.get("wall_seconds", 0.0)),
                status.state, stamps,
                self.profiles.task_payloads(job_id)))
        except Exception:  # noqa: BLE001 - observability only
            log.exception("ledger assembly failed for job %s", job_id)
        # admission plane: release the session's concurrency slot (and
        # any queue entry — a cancelled/reaped queued job leaves the
        # queue here), then pump so a freed slot admits waiting work
        # immediately instead of on the next heartbeat
        try:
            self.admission.on_terminal(job_id)
            self.admission.pump(force=True)
        except Exception:  # noqa: BLE001 - must not take the job down
            log.exception("admission terminal hook failed for job %s",
                          job_id)
        # durable control plane: the submission record is spent — a
        # restart must not resurrect a terminal job (internally guarded)
        self.journal.drop_submission(job_id)
        # live progress: freeze the final snapshot (fraction exactly
        # 1.0 for completed jobs) and drop the job's sample store
        try:
            self.progress.finish(job_id, status.state)
        except Exception:  # noqa: BLE001 - observability only
            log.exception("progress finish failed for job %s", job_id)
        # per-session metering: fold this job into its session's
        # cumulative record (system.sessions); the session id traveled
        # with the query settings. Only the id lookup happens here —
        # SessionMeter.record rewrites its durable file, and file I/O
        # does not belong on the PollWork handler thread, so the fold
        # runs first on the background worker below (before annotate,
        # which needs the record to exist)
        session_id = ""
        try:
            from ..observability.progress import SESSION_SETTING

            session_id = self.state.get_job_settings(job_id).get(
                SESSION_SETTING, "")
        except Exception:  # noqa: BLE001 - observability only
            log.exception("session lookup failed for job %s", job_id)
        sm = getattr(status, "stage_metrics", None) or {}
        for sid, stage in sm.items():
            observe_histogram("ballista_stage_seconds",
                              {"stage": str(sid)},
                              float(stage.get("elapsed_total", 0.0)))
        if sm:
            # system.operators: the job's per-stage operator metrics
            # (already aggregated host data — a cheap materialization)
            self.system_ops.record(job_id,
                                   summary.get("plan_digest") or "",
                                   systables.stage_metrics_provider(sm))
        thr = slow_query_secs()
        slow = thr is not None and \
            float(summary.get("wall_seconds", 0.0)) >= thr
        out_dir = obs_profiler.profile_dir()
        # snapshot the scheduler's ring window NOW: by the time the
        # worker runs, later queries may have evicted this job's spans
        sched_records = tracing.ring_records(job=job_id)
        wall = float(summary.get("wall_seconds", 0.0))
        dest = out_dir if out_dir is not None else slow_query_dir()
        want_artifact = out_dir is not None or slow

        def build_and_write():
            # EVERY job gets its lane decomposition (system.query_lanes
            # + the lane histograms); the merged ARTIFACT is still only
            # rendered/written when profiled or slow. Runs here, off
            # the PollWork handler thread — the merge walks every
            # collected task window.
            try:
                self._meter_session(session_id, summary, status)
            except Exception:  # noqa: BLE001 - observability only
                log.exception("session metering failed for job %s",
                              job_id)
            # cost feedback: fold the completed job's observed stage
            # costs into its plan digest's record (the next submission
            # of this shape plans from them). Off the PollWork thread
            # like the session meter — it rewrites a durable row.
            if status.state == "completed" and sm:
                try:
                    self.costs.observe(
                        summary.get("plan_digest") or "", sm,
                        wall_seconds=wall)
                except Exception:  # noqa: BLE001 - advisory
                    log.exception("cost observe failed for job %s",
                                  job_id)
            try:
                art = path = None
                if want_artifact:
                    art = self.profiles.build(job_id, wall_seconds=wall,
                                              sched_records=sched_records)
                if art is not None:
                    lanes = dict(art.get("lanes") or {})
                else:
                    from ..observability.distributed import merged_session
                    from ..observability.export import compute_lanes

                    session = merged_session(
                        job_id, sched_records,
                        self.profiles.task_payloads(job_id), wall)
                    lanes = compute_lanes(session)["lanes"]
                for lane, secs in lanes.items():
                    observe_histogram("ballista_query_lane_seconds",
                                      {"lane": lane}, float(secs))
                # session metering, late fact: device-blocked seconds
                # only exist once the lane decomposition lands here
                if lanes.get("device_blocked"):
                    from ..observability.progress import \
                        process_session_meter

                    process_session_meter().annotate(
                        session_id,
                        device_blocked_seconds=lanes["device_blocked"])
                if art is not None:
                    from ..observability.export import write_artifact_file

                    try:
                        path = write_artifact_file(art, out_dir=dest)
                    except OSError:
                        log.exception("profile artifact write failed "
                                      "for job %s", job_id)
                        path = None
                    else:
                        self.profiles.set_artifact(job_id, art, path)
                        log.info("merged profile artifact for job %s: "
                                 "%s", job_id, path)
                        if out_dir is None:
                            # retroactive slow-query dump: keep the
                            # directory bounded (hygiene knob)
                            from ..observability.distributed import \
                                prune_slow_query_artifacts

                            prune_slow_query_artifacts(dest)
                # the ring records the summary BY COPY at the terminal
                # transition, usually before this build finishes: set
                # the source dict (covers a build outrunning record)
                # AND annotate the recorded entries + history log (the
                # common case)
                fields = {"lanes": lanes}
                summary["lanes"] = lanes
                if path is not None:
                    summary["profile_artifact"] = path
                    fields["profile_artifact"] = path
                systables.annotate_query(job_id,
                                         query_log=self.state.query_log,
                                         **fields)
            except Exception:  # noqa: BLE001 - observability only
                log.exception("profile build failed for job %s", job_id)

        self._profile_pool.submit(build_and_write)

    def _meter_session(self, session_id: str, summary: dict,
                       status) -> None:
        """Fold one terminal job into its session's cumulative record:
        wall seconds always; task seconds and shuffle bytes from the
        completed-task stage metrics. ``bytes_shuffled`` counts the
        write-side bytes every NON-FINAL stage materialized into the
        data plane (ShuffleWrite rows for hash exchanges, PartitionWrite
        rows for merge-type exchanges) — the observed wire bytes, the
        honest metering unit for a shuffle data plane."""
        from ..observability.progress import process_session_meter

        sm = getattr(status, "stage_metrics", None) or {}
        task_seconds = sum(float(st.get("elapsed_total", 0.0))
                           for st in sm.values())
        bytes_shuffled = 0
        final_sid = max(sm) if sm else None
        for sid, st in sm.items():
            if sid == final_sid:
                continue  # the result stage's write is not shuffle
            for op in st.get("operators") or []:
                if op.get("operator") in ("ShuffleWrite",
                                          "PartitionWrite"):
                    bytes_shuffled += int(
                        (op.get("metrics") or {}).get("bytes_written", 0))
        process_session_meter().record(
            session_id,
            wall_seconds=float(summary.get("wall_seconds", 0.0)),
            task_seconds=task_seconds,
            bytes_shuffled=bytes_shuffled,
            peak_host_bytes=summary.get("peak_host_bytes") or 0,
            peak_device_bytes=summary.get("peak_device_bytes") or 0,
        )

    def _profile_artifact(self, job_id: str):
        """/debug/profile/<job_id>: the job's merged artifact (built on
        demand from the collector + flight recorder)."""
        return self.profiles.build(job_id)

    # -- RPC: ExecuteQuery --------------------------------------------------

    def ExecuteQuery(self, request: pb.ExecuteQueryParams, context=None):
        job_id = _job_id()
        settings = dict(request.settings)
        # admission gate FIRST (needs only the settings): a shed must
        # not pay plan deserialization or persist any job state — the
        # submission never existed
        t_gate = time.perf_counter()
        decision = self.admission.gate(job_id, settings,
                                       request.deadline_secs)
        if decision.action == "shed":
            err = decision.error()
            return pb.ExecuteQueryResult(
                job_id=job_id, error=str(err),
                retry_after_secs=err.retry_after_secs)
        # latency ledger: gate time for accepted jobs (shed jobs never
        # reach the terminal hook, so they carry no stamps)
        self._ledger_stamp(job_id, "admission_wait",
                           time.perf_counter() - t_gate)
        deadline_ts = None
        if request.deadline_secs > 0:
            # server-side deadline: armed BEFORE planning (a stuck plan
            # counts — and an admission-QUEUED job's wait counts too)
            # and enforced by the PollWork reap pass, so the job dies
            # on time even when the submitting client is gone
            deadline_ts = time.time() + request.deadline_secs
            self.state.save_job_deadline(job_id, deadline_ts)
        try:
            if request.WhichOneof("query") == "logical_plan":
                plan = serde.plan_from_proto(request.logical_plan)
                args = (job_id, plan, settings, None, None)
                plan_bytes = request.logical_plan.SerializeToString()
                sql_text, catalog_bytes = None, None
            else:
                # raw SQL: planned server-side in the background thread
                # (like plan failures, SQL errors land in
                # JobStatus('failed') rather than an opaque transport
                # error; reference accepts sql-or-plan, lib.rs:236-247)
                args = (job_id, None, settings, request.sql,
                        list(request.catalog))
                plan_bytes = None
                sql_text = request.sql
                catalog_bytes = [ct.SerializeToString()
                                 for ct in request.catalog]
            self.state.save_job_status(job_id, JobStatus("queued"))
            # live progress: track from submission so /debug/jobs
            # answers during planning too (fraction 0, no stages yet)
            self.progress.register_job(job_id)
            # durable control plane: journal the accepted submission at
            # decision time — a restarted scheduler re-queues (queued)
            # or replays planning (admitted, crashed mid-plan) from
            # exactly this record. Advisory: degrades loudly in-memory.
            self.journal.record_submission(
                job_id, decision.session_id, settings,
                sql=sql_text, catalog=catalog_bytes,
                plan_bytes=plan_bytes,
                action=decision.action, reason=decision.reason,
                priority=decision.config.priority,
                deadline_ts=deadline_ts)
        except BaseException:
            # the submission dies before it exists (bad plan proto):
            # release the gate's reservation or the session leaks a
            # concurrency slot forever (and drop its ledger stamps —
            # no terminal hook will ever pop them)
            self.admission.on_terminal(job_id)
            with self._ledger_lock:
                self._ledger_stamps.pop(job_id, None)
            raise
        if decision.action == "queue":
            # planning deferred: the pump launches (or sheds) it later;
            # status stays "queued" with a visible queue position
            self.admission.enqueue(decision, args)
            if self.state.is_job_cancelled(job_id):
                # a cancel raced the enqueue (its terminal hook ran
                # before the entry existed): drop the stale entry now —
                # the pump's pre-launch terminal re-check is the
                # backstop for the window that remains
                self.admission.on_terminal(job_id)
        else:
            try:
                self._launch_job(args)
            except BaseException as e:
                # thread spawn failed (fd/thread pressure — exactly the
                # overload regime): the job must not sit status=queued
                # forever holding its admitted slot. The terminal save
                # fires the hook, which releases the slot.
                self.state.save_job_status(job_id, JobStatus(
                    "failed", error=f"planning launch failed: {e}"))
                raise
        return pb.ExecuteQueryResult(job_id=job_id)

    def _launch_job(self, args):
        """Start the background planning thread for an ADMITTED job
        (straight from the gate, or later from the admission pump)."""
        t = threading.Thread(
            target=self._plan_job, args=args, daemon=True,
            name=f"plan-{args[0]}",
        )
        t.start()

    def _shed_queued_job(self, decision):
        """Admission queue timeout: the job was accepted (status queued,
        visible, cancellable) but never admitted — move it to a
        terminal FAILED state whose error is the structured retryable
        shed, so the waiting client's poll raises AdmissionRejected."""
        if self.state.is_job_cancelled(decision.job_id):
            return  # a racing cancel already made it terminal
        self.state.save_job_status(
            decision.job_id,
            JobStatus("failed", error=str(decision.error())))

    def _plan_sql(self, sql: str, catalog_entries):
        from ..sql.parser import CreateExternalTable, parse_sql
        from ..sql.planner import CatalogTable, SqlPlanner

        catalog = {}
        for ct in catalog_entries:
            src = serde.source_from_proto(ct.source)
            catalog[ct.name] = CatalogTable(
                ct.name, src, ct.source.primary_key or None
            )
        stmt = parse_sql(sql)
        if isinstance(stmt, CreateExternalTable):
            raise ClusterError(
                "CREATE EXTERNAL TABLE is a client-side statement; the "
                "scheduler keeps no durable catalog"
            )

        def system_source(name):
            # server-planned SQL over system.* tables: materialize the
            # SCHEDULER's snapshot at plan time (executors scan the
            # shipped rows)
            from ..observability.systables import SystemTableSource

            return SystemTableSource(
                name, rows=self.systables.table_rows(name))

        return SqlPlanner(catalog,
                          system_provider=system_source).plan(stmt)

    def _plan_job(self, job_id: str, logical_plan, settings=None,
                  sql=None, catalog_entries=None):
        try:
            with trace_span("scheduler.plan_job", job=job_id):
                self._plan_job_inner(job_id, logical_plan, settings, sql,
                                     catalog_entries)
        except Exception as e:  # noqa: BLE001 - job-level failure
            log.exception("planning failed for job %s", job_id)
            if not self.state.is_job_cancelled(job_id):
                # a cancel that raced planning stays terminal-cancelled
                self.state.save_job_status(
                    job_id, JobStatus("failed", error=str(e)))

    def _plan_job_inner(self, job_id: str, logical_plan, settings=None,
                        sql=None, catalog_entries=None):
        from ..physical.planner import PlannerOptions

        t0 = time.time()
        # persist the query settings: stage-completion re-planning reads
        # its adaptive.* knobs from here for the job's whole lifetime
        self.state.save_job_settings(job_id, settings or {})
        if logical_plan is None:
            logical_plan = self._plan_sql(sql, catalog_entries or [])
        digest = None
        try:
            # plan digest: identifies the query in slow-query summaries
            # and profile artifacts without re-planning it — and keys
            # the cost-feedback store below
            from ..observability.profiler import plan_digest

            digest = plan_digest(logical_plan)
            self.state.save_job_digest(job_id, digest)
        except Exception:  # noqa: BLE001 - digest is advisory
            pass
        opts = PlannerOptions.from_settings(settings)
        try:
            # cost feedback: observed costs from prior runs of this
            # plan shape refine the INITIAL partition counts and join
            # strategy (AQE still corrects mid-flight; explicit client
            # settings always win inside advise)
            opts, cost_notes = self.costs.advise(digest, opts, settings)
            if cost_notes:
                log.info("cost feedback for job %s: %s", job_id,
                         "; ".join(cost_notes))
        except Exception:  # noqa: BLE001 - advisory
            log.exception("cost advise failed for job %s", job_id)
        phys = plan_logical(logical_plan, opts)
        stages = DistributedPlanner().plan_query_stages(job_id, phys)
        stages = _fuse_mesh_stages(
            stages, _cluster_mesh_devices(self.state, settings)
        )
        for stage in stages:
            deps = [
                sid
                for u in find_unresolved_shuffles(stage.child)
                for sid in u.query_stage_ids
            ]
            nparts = stage.output_partitioning().num_partitions
            plan_bytes = serde.physical_to_proto(stage.child).SerializeToString()
            shuffle_spec = None
            if stage.shuffle_output_partitions:
                hx = [
                    serde.expr_to_proto(e).SerializeToString()
                    for e in (stage.shuffle_hash_exprs or [])
                ]
                shuffle_spec = (hx, stage.shuffle_output_partitions)
            self.state.save_stage_plan(
                job_id, stage.stage_id, plan_bytes, nparts, deps,
                shuffle_spec,
                mesh_devices=_mesh_requirement(stage.child),
            )
            for p in range(nparts):
                self.state.save_task_status(
                    TaskStatus(PartitionId(job_id, stage.stage_id, p))
                )
        if self.state.is_job_cancelled(job_id):
            # cancelled while planning (client cancel or an expired
            # deadline): nothing may reach the ready queue
            log.info("job %s cancelled during planning; not enqueued",
                     job_id)
            return
        # ledger stamp BEFORE the job becomes runnable: once enqueued,
        # the terminal hook may pop the job's stamps at any moment
        self._ledger_stamp(job_id, "planning", time.time() - t0)
        self.state.enqueue_job(job_id)
        # durable control plane: the full stage set + task rows are
        # persisted and the ready stages enqueued — restart recovery
        # may now trust them (absent marker ⇒ planning replays)
        self.journal.mark_planned(job_id)
        log.info(
            "planned job %s into %d stages in %.0fms",
            job_id, len(stages), 1000 * (time.time() - t0),
        )

    # -- RPC: PollWork ------------------------------------------------------

    def PollWork(self, request: pb.PollWorkParams, context=None):
        fault_point("scheduler.poll_work",
                    executor=request.metadata.id[:8])
        res = None
        if request.metadata.HasField("resources"):
            r = request.metadata.resources
            res = {
                "rss_bytes": int(r.rss_bytes),
                "device_bytes": int(r.device_bytes),
                "inflight_tasks": int(r.inflight_tasks),
                "ingest_pool_depth": int(r.ingest_pool_depth),
                "peak_host_bytes": int(r.peak_host_bytes),
                "shuffle_inflight_bytes": int(r.shuffle_inflight_bytes),
                "spill_bytes_total": int(r.spill_bytes_total),
            }
        meta = ExecutorMeta(
            id=request.metadata.id,
            host=request.metadata.host,
            port=request.metadata.port,
            num_devices=request.metadata.num_devices or 1,
            resources=res,
        )
        self.state.save_executor_metadata(meta)
        # live progress plane: fold the heartbeat's piggybacked task
        # samples into the tracker. Advisory by contract — any failure
        # here must not touch the scheduling work below.
        if request.task_progress:
            try:
                for tp in request.task_progress:
                    self.progress.record_report(
                        tp.partition_id.job_id,
                        tp.partition_id.stage_id,
                        tp.partition_id.partition_id,
                        {
                            "rows_so_far": int(tp.rows_so_far),
                            "input_rows_total": int(tp.input_rows_total),
                            "bytes_so_far": int(tp.bytes_so_far),
                            "elapsed_seconds": tp.elapsed_seconds,
                            "operator": tp.operator,
                            "stage_version": int(tp.stage_version),
                        })
            except Exception:  # noqa: BLE001 - best-effort
                log.debug("progress fold failed", exc_info=True)
        jobs_touched = set(self.state.reap_lost_tasks())
        # lifecycle reap: expired server-side deadlines + the slow-query
        # killer (already-terminal, so not re-synchronized below)
        self.state.reap_expired_jobs()
        # admission queue: heartbeats drive timeout sheds + freed-slot
        # admissions (throttled internally, like the reap pass)
        self.admission.pump()
        # late reports from tasks of a cancelled job: the terminal state
        # stands — no recovery, no re-queue, and a completion must not
        # resurrect dependents. Memoized per request: is_job_cancelled
        # falls back to a KV read, and a poll's reports almost always
        # share one job — don't pay one read per report on the hottest
        # handler
        _cancel_memo: dict = {}
        for ts in request.task_status:
            jid = ts.partition_id.job_id
            cancelled = _cancel_memo.get(jid)
            if cancelled is None:
                cancelled = _cancel_memo[jid] = \
                    self.state.is_job_cancelled(jid)
            if cancelled:
                continue
            if ts.WhichOneof("status") == "completed" and \
                    ts.completed.HasField("profile"):
                # distributed profiler: the task's profile window is
                # observability payload, not scheduling state — route it
                # to the bounded collector before the status conversion
                # (stale-version reports still ran; their spans count)
                prof = serde.task_profile_from_proto(ts.completed.profile)
                if prof is not None:
                    self.profiles.add_task_profile(
                        ts.partition_id.job_id, prof,
                        nbytes=len(ts.completed.profile.records_json))
            st = _task_status_from_proto(ts)
            jobs_touched.add(st.partition.job_id)
            if not self.state.accept_report_version(st):
                # the task was cut from a stage version an adaptive
                # re-plan superseded: its output layout no longer
                # matches the plan — drop the report (the state reset
                # any stranded current-version twin)
                continue
            if st.state == "completed":
                self.state.task_completed(st)
            elif st.state == "failed" and self.state.is_completed(st.partition):
                # the losing speculative duplicate failed AFTER the
                # original completed: the recorded result stands — a
                # failure report must not clobber it or trigger recovery
                log.info("dropping failure report for already-completed "
                         "task %s", st.partition.key())
            elif st.state == "failed" and \
                    self.state.absorb_speculative_failure(st.partition):
                # one of two in-flight copies (original + speculative
                # duplicate) failed while its twin may still succeed:
                # don't fail the job or burn recovery budget yet
                log.warning("absorbing first failure of speculated task "
                            "%s; twin copy still in flight (%s)",
                            st.partition.key(), st.error)
            elif st.state == "failed" and (
                self.state.recover_fetch_failure(st)
                or self.state.recover_transient_failure(st)
            ):
                log.warning(
                    "recovering job %s: task %s failed transiently — "
                    "re-queued (%s)",
                    st.partition.job_id, st.partition.key(), st.error,
                )
            else:
                self.state.save_task_status(st)
        result = pb.PollWorkResult()
        # autoscaler scale-down: tell a flagged executor to stop
        # accepting work (it drains its in-flight tasks and exits via
        # its own graceful path) — and don't hand it a task this poll
        draining = meta.id in self.drain_requests
        if draining:
            result.drain = True
        if request.can_accept_task and not draining:
            task = self.state.next_task(meta.num_devices)
            if task is None and self.speculation_age_secs > 0:
                task = self.state.speculative_task(
                    meta.num_devices, self.speculation_age_secs, meta.id,
                    # rate-based trigger off the live progress samples
                    # (age stays the fallback when no samples exist)
                    lag_fn=self.progress.speculation_lag_fn(),
                )
                if task is not None:
                    log.warning("speculating straggler task %s on executor "
                                "%s", task.key(), meta.id)
            if task is not None:
                try:
                    # a SPAN (not an instant): its duration is the real
                    # per-task plan resolution cost, and the merged
                    # artifact draws the flow arrow from this slice into
                    # the matching executor.task slice
                    with trace_span("scheduler.task_dispatch",
                                    task=task.key(), job=task.job_id,
                                    executor=meta.id[:8]):
                        result.task.CopyFrom(
                            self._task_definition(task, meta))
                    self.tasks_dispatched += 1
                except Exception as e:  # noqa: BLE001
                    log.exception("task resolution failed for %s", task)
                    st = TaskStatus(task, "failed", error=str(e))
                    # a tagged ShuffleFetchError here means a completed
                    # producer's data became unreachable (stage_locations
                    # refused to emit an unroutable address) — re-queue the
                    # producer instead of failing the consumer
                    if not self.state.recover_fetch_failure(st):
                        self.state.save_task_status(st)
                    jobs_touched.add(task.job_id)
        # piggyback recently-cancelled job ids: executors abort matching
        # running tasks at batch boundaries and clean partial outputs
        result.cancelled_jobs.extend(self.state.cancelled_job_ids())
        for job_id in jobs_touched:
            self.state.synchronize_job_status(job_id)
        return result

    def _task_definition(self, task: PartitionId, meta: ExecutorMeta
                         ) -> pb.TaskDefinition:
        row = self.state.get_stage_plan(task.job_id, task.stage_id)
        node = pb.PhysicalPlanNode()
        node.ParseFromString(row.plan_bytes)
        plan = serde.physical_from_proto(node)
        if row.deps:
            locations = self.state.stage_locations(task.job_id,
                                                   stages=set(row.deps))
            # expand hash-shuffled producer locations into per-consumer
            # files, and collect per-dep reader info: adaptive read
            # layouts plus the producer's hash columns (so the resolved
            # reader reports trustworthy co-partitioning)
            reader_info = {}
            for dep in row.deps:
                dep_row = self.state.get_stage_plan(task.job_id, dep)
                info = {}
                if dep_row.shuffle_spec is not None:
                    hx_bytes, n_out = dep_row.shuffle_spec
                    info["hash_columns"] = _hash_column_names(hx_bytes)
                    info["original_partitions"] = n_out
                    if locations.get(dep):
                        # (missing/empty deps stay absent so shuffle
                        # resolution fails loudly with PlanError, not a
                        # zero-group reader)
                        locations[dep] = _expand_shuffle_locations(
                            locations[dep], n_out
                        )
                    # adaptive layouts only apply to still-shuffled deps
                    # (a demoted probe keeps a fallback layout that is
                    # meaningless once its shuffle spec was stripped)
                    if row.reader_layouts and dep in row.reader_layouts:
                        info["read_partitions"] = row.reader_layouts[dep]
                reader_info[dep] = info
            plan = remove_unresolved_shuffles(plan, locations, reader_info)
        self.state.save_task_status(
            TaskStatus(task, "running", executor_id=meta.id,
                       started_at=time.time(), stage_version=row.version)
        )
        td = pb.TaskDefinition()
        td.task_id.job_id = task.job_id
        td.task_id.stage_id = task.stage_id
        td.task_id.partition_id = task.partition_id
        td.stage_version = row.version
        td.plan.CopyFrom(serde.physical_to_proto(plan))
        if row.shuffle_spec is not None:
            hx_bytes, n_out = row.shuffle_spec
            for hb in hx_bytes:
                e = pb.LogicalExprNode()
                e.ParseFromString(hb)
                td.shuffle_hash_exprs.append(e)
            td.shuffle_output_partitions = n_out
        return td

    # -- RPC: CancelJob -----------------------------------------------------

    def CancelJob(self, request: pb.CancelJobParams, context=None):
        """Cooperative cancellation entry point: move the job to its
        terminal Cancelled state and drop its queued tasks. Running
        tasks abort at batch boundaries once their executor's next poll
        carries the id (PollWorkResult.cancelled_jobs)."""
        cancelled = self.state.cancel_job(request.job_id,
                                          request.reason or "client")
        st = self.state.get_job_status(request.job_id)
        return pb.CancelJobResult(
            cancelled=cancelled,
            state=st.state if st is not None else "unknown",
        )

    # -- RPC: GetJobStatus --------------------------------------------------

    def GetJobStatus(self, request: pb.GetJobStatusParams, context=None):
        # lifecycle reap rides status polls too: with every executor
        # down there are no PollWork calls, but a waiting client still
        # drives deadline/slow-query-kill enforcement for its job —
        # and the admission pump, so a queue drains (or times out)
        # even with zero executors registered
        self.state.reap_expired_jobs()
        self.admission.pump()
        st = self.state.get_job_status(request.job_id)
        result = pb.GetJobStatusResult()
        if st is None:
            result.status.failed.error = f"unknown job {request.job_id}"
        elif st.state == "queued":
            result.status.queued.SetInParent()
            info = self.admission.queue_info(request.job_id)
            if info:
                result.status.queued.queue_position = \
                    info["queue_position"]
                result.status.queued.reason = info["reason"] or ""
                result.status.queued.queued_seconds = \
                    info["queued_seconds"]
                result.status.queued.recovered = \
                    bool(info.get("recovered"))
        elif st.state == "running":
            result.status.running.SetInParent()
        elif st.state == "cancelled":
            result.status.cancelled.reason = \
                getattr(st, "cancel_reason", None) or "unknown"
        elif st.state == "failed":
            result.status.failed.error = st.error or "unknown error"
            from ..errors import AdmissionRejected

            parsed = AdmissionRejected.parse(st.error or "")
            if parsed is not None:
                # a queue-timeout shed: structured AND machine-readable
                result.status.failed.retry_after_secs = parsed[1]
        else:
            for loc in st.partition_locations or []:
                result.status.completed.partition_location.append(
                    serde.location_to_proto(loc)
                )
            if getattr(st, "stage_metrics", None):
                serde.stage_metrics_to_proto(
                    st.stage_metrics, result.status.completed.stage_metrics
                )
        # live progress snapshot (extended GetJobStatus): present while
        # the tracker knows the job — the client's on_progress callback
        # and ctx.job_progress() read it from here. Skipped entirely
        # when the plane is disabled: status polls are a hot path and
        # the off knob must actually take the work off it
        from ..observability.progress import progress_interval_secs

        if progress_interval_secs() is not None:
            try:
                snap = self.progress.snapshot(request.job_id)
                if snap is not None:
                    serde.job_progress_to_proto(snap, result.progress)
            except Exception:  # noqa: BLE001 - advisory
                log.debug("progress snapshot failed", exc_info=True)
        return result

    # -- RPC: GetJobProfile --------------------------------------------------

    def GetJobProfile(self, request: pb.GetJobProfileParams, context=None):
        """Serve the job's merged profile artifact (distributed
        profiler): the remote ``df.profile()`` path. Built on demand
        from the collected task payloads + the scheduler's
        flight-recorder window when no ambient/slow build cached one."""
        import json as _json

        result = pb.GetJobProfileResult()
        art = self.profiles.build(request.job_id)
        if art is None:
            result.error = (f"no profile data for job {request.job_id} "
                            "(unknown job, or its window aged out of "
                            "the bounded collector)")
        else:
            result.artifact_json = _json.dumps(art, default=str).encode()
        return result

    # -- RPC: GetSystemTable -------------------------------------------------

    def GetSystemTable(self, request: pb.GetSystemTableParams,
                       context=None):
        """Serve one system.* table's rows from the SCHEDULER's
        snapshot: remote contexts route their system-table scans here
        so ``system.executors`` / ``system.queries`` reflect cluster
        state, not the client process."""
        import json as _json

        result = pb.GetSystemTableResult()
        try:
            rows = self.systables.table_rows(request.table)
        except KeyError as e:
            result.error = str(e)
        except Exception as e:  # noqa: BLE001 - diagnosis plane
            log.exception("system table build failed: %s", request.table)
            result.error = f"{type(e).__name__}: {e}"
        else:
            result.rows_json = _json.dumps(rows, default=str).encode()
        return result

    # -- RPC: GetExecutorsMetadata ------------------------------------------

    def GetExecutorsMetadata(self, request, context=None):
        result = pb.GetExecutorsMetadataResult()
        for e in self.state.get_executors_metadata():
            result.metadata.append(
                pb.ExecutorMetadata(id=e.id, host=e.host, port=e.port,
                                    num_devices=e.num_devices)
            )
        return result

    # -- RPC: GetFileMetadata -----------------------------------------------

    def GetFileMetadata(self, request: pb.GetFileMetadataParams, context=None):
        from ..io import ParquetSource

        if request.file_type.lower() not in ("parquet", ""):
            raise ClusterError("only Parquet metadata is supported "
                               "(reference parity: lib.rs:184-222)")
        src = ParquetSource(request.path)
        return pb.GetFileMetadataResult(
            schema=serde.schema_to_proto(src.table_schema()),
            num_partitions=src.num_partitions(),
        )


def _hash_column_names(hx_bytes) -> list:
    """Column names a shuffle stage hash-partitioned on, or [] when any
    hash expr is not a plain column (then co-partitioning cannot be
    keyed by name and the reader stays Partitioning("unknown")).
    Memoized — the exprs are immutable per stage but this runs on every
    task dispatch of every consumer."""
    return list(_hash_column_names_cached(tuple(hx_bytes or ())))


@functools.lru_cache(maxsize=512)
def _hash_column_names_cached(hx_bytes: tuple) -> tuple:
    from .. import expr as ex

    names = []
    for hb in hx_bytes:
        e = pb.LogicalExprNode()
        e.ParseFromString(hb)
        parsed = serde.expr_from_proto(e)
        if not isinstance(parsed, ex.ColumnRef):
            return ()
        names.append(parsed.column)
    return tuple(names)


def _expand_shuffle_locations(producer_locs, n_out: int):
    """Per-producer completed-task locations -> one location per
    (producer, consumer-partition) shuffle file."""
    from .dataplane import shuffle_file_name
    from .types import PartitionLocation

    out = []
    for loc in producer_locs:
        base = os.path.dirname(loc.path) if loc.path else ""
        for q in range(n_out):
            out.append(
                PartitionLocation(
                    job_id=loc.job_id, stage_id=loc.stage_id,
                    partition_id=loc.partition_id,
                    executor_id=loc.executor_id, host=loc.host,
                    port=loc.port,
                    path=os.path.join(base, shuffle_file_name(q)) if base else "",
                    stats=loc.stats, shuffle_output=q,
                )
            )
    return out


def _task_status_from_proto(ts: pb.TaskStatus) -> TaskStatus:
    pid = PartitionId(ts.partition_id.job_id, ts.partition_id.stage_id,
                      ts.partition_id.partition_id)
    ver = ts.stage_version
    which = ts.WhichOneof("status")
    if which == "running":
        return TaskStatus(pid, "running", executor_id=ts.running.executor_id,
                          stage_version=ver)
    if which == "failed":
        return TaskStatus(pid, "failed", error=ts.failed.error,
                          stage_version=ver)
    if which == "completed":
        return TaskStatus(
            pid, "completed", executor_id=ts.completed.executor_id,
            path=ts.completed.path,
            stats=serde.stats_from_proto(ts.completed.stats),
            metrics=serde.task_metrics_from_proto(ts.completed.metrics),
            stage_version=ver,
        )
    return TaskStatus(pid, stage_version=ver)


# ---------------------------------------------------------------------------
# gRPC wiring (hand-rolled handlers; no grpc_tools codegen available)
# ---------------------------------------------------------------------------

_RPCS = {
    "ExecuteQuery": (pb.ExecuteQueryParams, pb.ExecuteQueryResult),
    "PollWork": (pb.PollWorkParams, pb.PollWorkResult),
    "CancelJob": (pb.CancelJobParams, pb.CancelJobResult),
    "GetJobStatus": (pb.GetJobStatusParams, pb.GetJobStatusResult),
    "GetJobProfile": (pb.GetJobProfileParams, pb.GetJobProfileResult),
    "GetSystemTable": (pb.GetSystemTableParams, pb.GetSystemTableResult),
    "GetExecutorsMetadata": (
        pb.GetExecutorsMetadataParams, pb.GetExecutorsMetadataResult,
    ),
    "GetFileMetadata": (pb.GetFileMetadataParams, pb.GetFileMetadataResult),
}


def serve_scheduler(state: SchedulerState, host: str = "0.0.0.0",
                    port: int = 50050, max_workers: int = 16,
                    speculation_age_secs: float = 60.0,
                    metrics_port: "int | None" = None):
    """Start the scheduler gRPC server; returns (grpc_server, service).
    ``metrics_port`` starts the health plane (None = resolve
    ``BALLISTA_METRICS_PORT``, default off; 0 = ephemeral)."""
    svc = SchedulerService(state, speculation_age_secs=speculation_age_secs,
                           metrics_port=metrics_port)
    handlers = {}
    for name, (req_t, _resp_t) in _RPCS.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            getattr(svc, name),
            request_deserializer=req_t.FromString,
            response_serializer=lambda m: m.SerializeToString(),
        )
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers),
                         options=_GRPC_MSG_OPTS)
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, svc, bound


class SchedulerClient:
    """Thin typed client over the generic gRPC channel."""

    def __init__(self, host: str, port: int):
        self.channel = grpc.insecure_channel(f"{host}:{port}",
                                             options=_GRPC_MSG_OPTS)
        self._stubs = {}
        for name, (req_t, resp_t) in _RPCS.items():
            self._stubs[name] = self.channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_t.FromString,
            )

    def __getattr__(self, name):
        if name in _RPCS:
            stub = self._stubs[name]

            def call(request, _stub=stub, _name=name):
                # client-side fault point: a triggered failure surfaces
                # as an RPC error exactly where a flaky network would
                fault_point("client.rpc", method=_name)
                return _stub(request)

            return call
        raise AttributeError(name)

    def close(self):
        self.channel.close()
