"""Scheduler binary: ``python -m ballista_tpu.distributed.scheduler_main``.

(reference: rust/scheduler/src/main.rs:43-115 + scheduler_config_spec.toml
— layered config: defaults < env BALLISTA_SCHEDULER_* < CLI flags.)
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys


def env_default(name: str, fallback):
    v = os.environ.get(f"BALLISTA_SCHEDULER_{name.upper()}")
    if v is None:
        return fallback
    return type(fallback)(v) if fallback is not None else v


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="ballista-tpu scheduler")
    ap.add_argument("--namespace", default=env_default("namespace", "default"))
    ap.add_argument("--bind-host", default=env_default("bind_host", "0.0.0.0"))
    ap.add_argument("--port", type=int, default=env_default("port", 50050))
    ap.add_argument("--config-backend", default=env_default("config_backend", "memory"),
                    choices=["memory", "sqlite"])
    ap.add_argument("--sqlite-path", default=env_default("sqlite_path", "ballista-state.db"))
    ap.add_argument("--log-level", default=env_default("log_level", "INFO"))
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=args.log_level.upper(),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    from .scheduler import serve_scheduler
    from .state import MemoryBackend, SchedulerState, SqliteBackend

    backend = (
        SqliteBackend(args.sqlite_path)
        if args.config_backend == "sqlite"
        else MemoryBackend()
    )
    state = SchedulerState(backend, args.namespace)
    server, _svc, port = serve_scheduler(state, args.bind_host, args.port)
    print(f"ballista-tpu scheduler listening on {args.bind_host}:{port} "
          f"(backend={args.config_backend}, ns={args.namespace})", flush=True)
    stop = signal.sigwait([signal.SIGINT, signal.SIGTERM])
    print(f"signal {stop}; shutting down", flush=True)
    server.stop(grace=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
