"""Scheduler binary: ``python -m ballista_tpu.distributed.scheduler_main``.

(reference: rust/scheduler/src/main.rs:43-115 + scheduler_config_spec.toml
— layered config: defaults < /etc/ballista-tpu/scheduler.toml <
--config-file < env BALLISTA_SCHEDULER_* < CLI flags.)
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys

from .config import layered_config

DEFAULTS = {
    "namespace": "default",
    "bind_host": "0.0.0.0",
    "port": 50050,
    "config_backend": "memory",  # memory | sqlite | etcd
    "sqlite_path": "ballista-state.db",
    "etcd_urls": "localhost:2379",
    "speculation_secs": 60,  # duplicate stragglers after this; 0 = off
    "log_level": "INFO",
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="ballista-tpu scheduler")
    ap.add_argument("--config-file", default=None)
    for key in DEFAULTS:
        ap.add_argument("--" + key.replace("_", "-"), default=None)
    args = ap.parse_args(argv)

    cfg = layered_config(
        "scheduler", DEFAULTS, args.config_file,
        cli={k: getattr(args, k) for k in DEFAULTS},
    )
    backends = ("memory", "sqlite", "etcd")
    if cfg["config_backend"] not in backends:
        # validate post-layering so env/TOML typos fail loudly instead of
        # silently falling back to the in-memory backend
        ap.error(f"config_backend must be one of {backends}, "
                 f"got {cfg['config_backend']!r}")

    logging.basicConfig(
        level=cfg["log_level"].upper(),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    from .scheduler import serve_scheduler
    from .state import MemoryBackend, SchedulerState, SqliteBackend

    if cfg["config_backend"] == "sqlite":
        backend = SqliteBackend(cfg["sqlite_path"])
    elif cfg["config_backend"] == "etcd":
        from .etcd import EtcdBackend

        backend = EtcdBackend(cfg["etcd_urls"])
    else:
        backend = MemoryBackend()
    state = SchedulerState(backend, cfg["namespace"])
    server, _svc, port = serve_scheduler(
        state, cfg["bind_host"], cfg["port"],
        speculation_age_secs=float(cfg["speculation_secs"]),
    )
    print(f"ballista-tpu scheduler listening on {cfg['bind_host']}:{port} "
          f"(backend={cfg['config_backend']}, ns={cfg['namespace']})",
          flush=True)
    stop = signal.sigwait([signal.SIGINT, signal.SIGTERM])
    print(f"signal {stop}; shutting down", flush=True)
    server.stop(grace=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
