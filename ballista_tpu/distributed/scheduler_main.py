"""Scheduler binary: ``python -m ballista_tpu.distributed.scheduler_main``.

(reference: rust/scheduler/src/main.rs:43-115 + scheduler_config_spec.toml
— layered config: defaults < /etc/ballista-tpu/scheduler.toml <
--config-file < env BALLISTA_SCHEDULER_* < CLI flags.)
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys

from .config import layered_config

DEFAULTS = {
    "namespace": "default",
    "bind_host": "0.0.0.0",
    "port": 50050,
    "config_backend": "memory",  # memory | sqlite | etcd
    "sqlite_path": "ballista-state.db",
    "etcd_urls": "localhost:2379",
    "speculation_secs": 60,  # duplicate stragglers after this; 0 = off
    "flight_port": -1,  # Arrow Flight SQL front-end; -1 = off, 0 = ephemeral
    "metrics_port": 0,  # health plane (/healthz, /metrics); -1 = off
    "log_level": "INFO",
    # durable control plane: --state sqlite:/path or etcd:host:port is
    # shorthand for config_backend + its path/urls in one flag
    "state": "",
    # demand-driven autoscaler (off unless on): spawns/drains
    # executor_main subprocesses against this scheduler; bounds and
    # thresholds ride the autoscale.* knob family (BALLISTA_AUTOSCALE_*)
    "autoscale": "off",
}


def main(argv=None) -> int:
    # sigwait below only receives a signal that is BLOCKED; without
    # this mask SIGTERM takes the default disposition (immediate kill)
    # and the graceful-drain path never runs. Masked first thing so
    # every thread the server spawns inherits the block and the signal
    # can only be consumed by the main thread's sigwait.
    signal.pthread_sigmask(signal.SIG_BLOCK,
                           {signal.SIGINT, signal.SIGTERM})
    ap = argparse.ArgumentParser(description="ballista-tpu scheduler")
    ap.add_argument("--config-file", default=None)
    for key in DEFAULTS:
        ap.add_argument("--" + key.replace("_", "-"), default=None)
    args = ap.parse_args(argv)

    cfg = layered_config(
        "scheduler", DEFAULTS, args.config_file,
        cli={k: getattr(args, k) for k in DEFAULTS},
    )
    if cfg["state"]:
        # --state sqlite:<path> | etcd:<urls> | memory
        kind, _, rest = str(cfg["state"]).partition(":")
        cfg["config_backend"] = kind
        if kind == "sqlite" and rest:
            cfg["sqlite_path"] = rest
        elif kind == "etcd" and rest:
            cfg["etcd_urls"] = rest
    backends = ("memory", "sqlite", "etcd")
    if cfg["config_backend"] not in backends:
        # validate post-layering so env/TOML typos fail loudly instead of
        # silently falling back to the in-memory backend
        ap.error(f"config_backend must be one of {backends}, "
                 f"got {cfg['config_backend']!r}")

    logging.basicConfig(
        level=cfg["log_level"].upper(),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    from .scheduler import serve_scheduler
    from .state import MemoryBackend, SchedulerState, SqliteBackend

    if cfg["config_backend"] == "sqlite":
        backend = SqliteBackend(cfg["sqlite_path"])
    elif cfg["config_backend"] == "etcd":
        from .etcd import EtcdBackend

        backend = EtcdBackend(cfg["etcd_urls"])
    else:
        backend = MemoryBackend()
    state = SchedulerState(backend, cfg["namespace"])
    server, _svc, port = serve_scheduler(
        state, cfg["bind_host"], cfg["port"],
        speculation_age_secs=float(cfg["speculation_secs"]),
        metrics_port=int(cfg["metrics_port"]),
    )
    print(f"ballista-tpu scheduler listening on {cfg['bind_host']}:{port} "
          f"(backend={cfg['config_backend']}, ns={cfg['namespace']})",
          flush=True)
    # restart recovery: one explicit pass BEFORE executors poll — a
    # durable backend rebuilds the admission queue, replays planning
    # lost mid-flight and fails orphans loudly (memory backend: no-op)
    report = _svc.recover()
    print("control-plane recovery: "
          f"recovered_jobs={report.recovered_jobs} "
          f"queued_restored={report.queued_restored} "
          f"relaunched={report.relaunched} "
          f"inflight={report.jobs_inflight} "
          f"orphans_failed={report.orphans_failed} "
          f"tasks_requeued={report.tasks_requeued} "
          f"seconds={report.recovery_seconds}", flush=True)
    launcher = None
    if str(cfg["autoscale"]).lower() in ("on", "1", "true", "yes"):
        from .controlplane import (AutoscalerConfig,
                                   SubprocessExecutorLauncher)

        as_cfg = AutoscalerConfig.from_settings({"autoscale.enabled":
                                                 "on"})
        loop_host = ("127.0.0.1"
                     if cfg["bind_host"] in ("0.0.0.0", "::", "localhost",
                                             "127.0.0.1")
                     else cfg["bind_host"])
        launcher = SubprocessExecutorLauncher(loop_host, port)
        _svc.attach_autoscaler(as_cfg, launcher.spawn,
                               drain_fn=launcher.drain)
        print(f"autoscaler on: executors {as_cfg.min_executors}.."
              f"{as_cfg.max_executors} (backlog>={as_cfg.backlog_tasks}"
              f", cooldown={as_cfg.cooldown_secs}s)", flush=True)
    if _svc.health is not None:
        print(f"ballista-tpu scheduler health plane on "
              f"127.0.0.1:{_svc.health.port}", flush=True)
    flight_server = None
    if int(cfg["flight_port"]) >= 0:
        # Arrow Flight front-end: foreign clients (the reference's JDBC
        # driver shape — jdbc:arrow://host:flight_port) send raw SQL as
        # a DoGet ticket; the query runs through the NORMAL cluster path
        # (submit -> schedule -> executors -> fetch) via a loopback
        # client context
        from ..client import BallistaContext
        from .flight import available as flight_available, serve_flight

        if not flight_available():
            ap.error("--flight-port requires pyarrow.flight")
        # loopback target: a wildcard/loopback bind is reachable via
        # 127.0.0.1; a specific interface is only reachable at that addr
        loop_host = ("127.0.0.1"
                     if cfg["bind_host"] in ("0.0.0.0", "::", "localhost",
                                             "127.0.0.1")
                     else cfg["bind_host"])
        fctx = BallistaContext.remote(loop_host, port)

        def execute_sql(sql):
            df = fctx.sql(sql)
            if df._plan is None and df._raw_sql is None:  # DDL: CREATE
                import numpy as np  # EXTERNAL TABLE registered above

                return {"status": np.asarray(["OK"], dtype=object)}
            return df.collect()

        flight_server, fport = serve_flight(
            cfg["bind_host"], int(cfg["flight_port"]),
            execute_sql=execute_sql,
        )
        print(f"ballista-tpu Arrow Flight SQL endpoint on "
              f"{cfg['bind_host']}:{fport}", flush=True)
    stop = signal.sigwait([signal.SIGINT, signal.SIGTERM])
    if stop == signal.SIGTERM:
        # graceful degradation (admission ladder's last rung): shed NEW
        # submissions while admitted work finishes, bounded by the same
        # drain knob executors use
        print(f"signal {stop}; draining (new submissions are shed)",
              flush=True)
        _svc.begin_drain()
        import time as _time

        from .executor import drain_timeout_secs

        deadline = _time.time() + drain_timeout_secs()
        while _time.time() < deadline:
            try:
                if not _svc.progress.live_snapshots() and \
                        _svc.admission.queue_depth() == 0:
                    break
            except Exception:  # noqa: BLE001 - shutdown path
                break
            _time.sleep(0.25)
    else:
        print(f"signal {stop}; shutting down", flush=True)
    if launcher is not None:
        launcher.stop_all()
    if flight_server is not None:
        flight_server.shutdown()
    _svc.close_health()
    server.stop(grace=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
