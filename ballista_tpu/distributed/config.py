"""Layered configuration for the scheduler/executor binaries.

Mirrors the reference's configure_me layering (reference:
rust/scheduler/src/main.rs:65-66 + scheduler_config_spec.toml /
executor_config_spec.toml; documented order in
docs/user-guide/src/configuration.md:1-14):

    defaults < /etc/ballista-tpu/<role>.toml < --config-file
             < env BALLISTA_<ROLE>_* < CLI flags

Files are TOML (stdlib tomllib); keys use underscores and match the CLI
flag names (``bind_host``, ``port``, ...).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

SYSTEM_CONFIG_DIR = "/etc/ballista-tpu"


def load_toml(path: str) -> Dict[str, Any]:
    import tomllib

    with open(path, "rb") as fh:
        return tomllib.load(fh)


def layered_config(
    role: str,
    defaults: Dict[str, Any],
    config_file: Optional[str] = None,
    env: Optional[Dict[str, str]] = None,
    cli: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Merge config layers for ``role`` ("scheduler" | "executor").

    ``cli`` holds only flags the user EXPLICITLY passed (argparse values
    that are None are treated as absent). Values from files/env are
    coerced to the default's type when one exists."""
    env = os.environ if env is None else env
    out = dict(defaults)

    def apply(layer: Dict[str, Any]):
        for k, v in layer.items():
            if v is None:
                continue
            base = defaults.get(k)
            if base is not None and not isinstance(v, type(base)):
                try:
                    v = type(base)(v)
                except (TypeError, ValueError):
                    raise ValueError(
                        f"config key {k!r}: cannot coerce {v!r} to "
                        f"{type(base).__name__}"
                    )
            out[k] = v

    system_path = os.path.join(SYSTEM_CONFIG_DIR, f"{role}.toml")
    if os.path.exists(system_path):
        apply(load_toml(system_path))
    if config_file:
        apply(load_toml(config_file))
    prefix = f"BALLISTA_{role.upper()}_"
    apply({
        k[len(prefix):].lower(): v
        for k, v in env.items() if k.startswith(prefix)
    })
    apply(cli or {})
    return out
