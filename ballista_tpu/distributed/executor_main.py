"""Executor binary: ``python -m ballista_tpu.distributed.executor_main``.

(reference: rust/executor/src/main.rs:55-164 + executor_config_spec.toml
— layered config: defaults < /etc/ballista-tpu/executor.toml <
--config-file < env BALLISTA_EXECUTOR_* < CLI flags; ``--local`` embeds
a standalone scheduler in-process like the reference's local mode.)
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys

from .config import layered_config

DEFAULTS = {
    "namespace": "default",
    "scheduler_host": "localhost",
    "scheduler_port": 50050,
    "bind_host": "localhost",
    "external_host": "",
    "port": 0,  # data-plane port (0 = ephemeral)
    "work_dir": "",
    "concurrent_tasks": 4,
    "num_devices": 0,  # 0 = autodetect
    "log_level": "INFO",
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="ballista-tpu executor")
    ap.add_argument("--config-file", default=None)
    ap.add_argument("--local", action="store_true",
                    help="embed a standalone scheduler in-process")
    for key in DEFAULTS:
        ap.add_argument("--" + key.replace("_", "-"), default=None)
    args = ap.parse_args(argv)

    cfg = layered_config(
        "executor", DEFAULTS, args.config_file,
        cli={k: getattr(args, k) for k in DEFAULTS},
    )

    logging.basicConfig(
        level=cfg["log_level"].upper(),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )

    from .executor import Executor, ExecutorConfig

    scheduler_port = cfg["scheduler_port"]
    if args.local:
        from .scheduler import serve_scheduler
        from .state import MemoryBackend, SchedulerState

        state = SchedulerState(MemoryBackend(), cfg["namespace"])
        _server, _svc, scheduler_port = serve_scheduler(
            state, "localhost", 0
        )
        print(f"embedded scheduler on localhost:{scheduler_port}", flush=True)

    num_devices = cfg["num_devices"]
    if not num_devices:
        import jax

        num_devices = len(jax.devices())
    exec_cfg = ExecutorConfig(
        host=cfg["external_host"] or cfg["bind_host"],
        bind_host=cfg["bind_host"],
        port=cfg["port"],
        work_dir=cfg["work_dir"] or None,
        concurrent_tasks=cfg["concurrent_tasks"],
        scheduler_host="localhost" if args.local else cfg["scheduler_host"],
        scheduler_port=scheduler_port,
        num_devices=num_devices,
    )
    executor = Executor(exec_cfg)
    executor.start()
    print(
        f"ballista-tpu executor {executor.id[:8]} polling "
        f"{exec_cfg.scheduler_host}:{exec_cfg.scheduler_port}, data plane on "
        f"{exec_cfg.host}:{executor.port}, work_dir={exec_cfg.work_dir}",
        flush=True,
    )
    stop = signal.sigwait([signal.SIGINT, signal.SIGTERM])
    print(f"signal {stop}; shutting down", flush=True)
    executor.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
