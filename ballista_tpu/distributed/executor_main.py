"""Executor binary: ``python -m ballista_tpu.distributed.executor_main``.

(reference: rust/executor/src/main.rs:55-164 + executor_config_spec.toml —
layered config via env BALLISTA_EXECUTOR_* < CLI flags; ``--local`` embeds
a standalone scheduler in-process like the reference's local mode.)
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys


def env_default(name: str, fallback):
    v = os.environ.get(f"BALLISTA_EXECUTOR_{name.upper()}")
    if v is None:
        return fallback
    return type(fallback)(v) if fallback is not None else v


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="ballista-tpu executor")
    ap.add_argument("--namespace", default=env_default("namespace", "default"))
    ap.add_argument("--scheduler-host",
                    default=env_default("scheduler_host", "localhost"))
    ap.add_argument("--scheduler-port", type=int,
                    default=env_default("scheduler_port", 50050))
    ap.add_argument("--bind-host", default=env_default("bind_host", "localhost"))
    ap.add_argument("--external-host", default=env_default("external_host", ""))
    ap.add_argument("--port", type=int, default=env_default("port", 0),
                    help="data-plane port (0 = ephemeral)")
    ap.add_argument("--work-dir", default=env_default("work_dir", ""))
    ap.add_argument("--concurrent-tasks", type=int,
                    default=env_default("concurrent_tasks", 4))
    ap.add_argument("--num-devices", type=int,
                    default=env_default("num_devices", 0),
                    help="devices this executor owns (0 = autodetect)")
    ap.add_argument("--local", action="store_true",
                    help="embed a standalone scheduler in-process")
    ap.add_argument("--log-level", default=env_default("log_level", "INFO"))
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=args.log_level.upper(),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )

    from .executor import Executor, ExecutorConfig

    scheduler_port = args.scheduler_port
    if args.local:
        from .scheduler import serve_scheduler
        from .state import MemoryBackend, SchedulerState

        state = SchedulerState(MemoryBackend(), args.namespace)
        _server, _svc, scheduler_port = serve_scheduler(
            state, "localhost", 0
        )
        print(f"embedded scheduler on localhost:{scheduler_port}", flush=True)

    num_devices = args.num_devices
    if not num_devices:
        import jax

        num_devices = len(jax.devices())
    cfg = ExecutorConfig(
        host=args.external_host or args.bind_host,
        bind_host=args.bind_host,
        port=args.port,
        work_dir=args.work_dir or None,
        concurrent_tasks=args.concurrent_tasks,
        scheduler_host="localhost" if args.local else args.scheduler_host,
        scheduler_port=scheduler_port,
        num_devices=num_devices,
    )
    executor = Executor(cfg)
    executor.start()
    print(
        f"ballista-tpu executor {executor.id[:8]} polling "
        f"{cfg.scheduler_host}:{cfg.scheduler_port}, data plane on "
        f"{cfg.host}:{executor.port}, work_dir={cfg.work_dir}",
        flush=True,
    )
    stop = signal.sigwait([signal.SIGINT, signal.SIGTERM])
    print(f"signal {stop}; shutting down", flush=True)
    executor.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
