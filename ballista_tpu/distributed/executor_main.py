"""Executor binary: ``python -m ballista_tpu.distributed.executor_main``.

(reference: rust/executor/src/main.rs:55-164 + executor_config_spec.toml
— layered config: defaults < /etc/ballista-tpu/executor.toml <
--config-file < env BALLISTA_EXECUTOR_* < CLI flags; ``--local`` embeds
a standalone scheduler in-process like the reference's local mode.)
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys

from .config import layered_config

DEFAULTS = {
    "namespace": "default",
    "scheduler_host": "localhost",
    "scheduler_port": 50050,
    "bind_host": "localhost",
    "external_host": "",
    "port": 0,  # data-plane port (0 = ephemeral)
    "work_dir": "",
    "concurrent_tasks": 4,
    "num_devices": 0,  # 0 = autodetect
    # -- mesh group: executors on several hosts forming ONE device mesh --
    "mesh_group_size": 0,  # processes in the group; 0 = no group
    "mesh_group_rank": 0,  # this process's rank (0 = leader)
    "mesh_group_coordinator": "",  # jax.distributed coordinator host:port
    "mesh_group_channel": "",  # leader's task channel (host:port);
    #                            leader binds it, followers dial it
    "mesh_local_devices": 0,  # virtual CPU devices per process (tests)
    # C++ shuffle-server daemon serves the data plane (GIL-free); "off"
    # keeps the in-process Python server (also the automatic fallback)
    "native_dataplane": "on",
    "metrics_port": 0,  # health plane (/healthz, /metrics); -1 = off
    "log_level": "INFO",
}


def main(argv=None) -> int:
    # sigwait below only receives a signal that is BLOCKED; without
    # this mask SIGTERM takes the default disposition (immediate kill)
    # and the graceful-drain path (PR 9) never runs on the real binary.
    # Masked first thing so every thread the executor spawns inherits
    # the block and only the main thread's sigwait consumes the signal.
    signal.pthread_sigmask(signal.SIG_BLOCK,
                           {signal.SIGINT, signal.SIGTERM})
    ap = argparse.ArgumentParser(description="ballista-tpu executor")
    ap.add_argument("--config-file", default=None)
    ap.add_argument("--local", action="store_true",
                    help="embed a standalone scheduler in-process")
    for key in DEFAULTS:
        ap.add_argument("--" + key.replace("_", "-"), default=None)
    args = ap.parse_args(argv)

    cfg = layered_config(
        "executor", DEFAULTS, args.config_file,
        cli={k: getattr(args, k) for k in DEFAULTS},
    )

    logging.basicConfig(
        level=cfg["log_level"].upper(),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )

    from .dataplane import native_dataplane_enabled as _native_enabled
    from .executor import Executor, ExecutorConfig

    group_size = int(cfg["mesh_group_size"])
    group_rank = int(cfg["mesh_group_rank"])
    leader = None
    if group_size > 1:
        # join the shared jax.distributed runtime BEFORE anything
        # touches the backend, so every member sees the global mesh
        from ..parallel import multihost

        multihost.init_group(
            cfg["mesh_group_coordinator"], group_size, group_rank,
            local_device_count=int(cfg["mesh_local_devices"]) or None,
        )
        # backend init is ITSELF a cross-process rendezvous (each
        # process registers its local devices with the coordinator):
        # every member must do it now, or the first member to call
        # jax.devices() later hangs waiting for the rest
        import jax

        n_global = len(jax.devices())
        print(f"mesh group rank {group_rank}: global mesh has "
              f"{n_global} devices", flush=True)
        host, _, port_s = cfg["mesh_group_channel"].rpartition(":")
        from . import mesh_group

        if group_rank == 0:
            leader = mesh_group.GroupLeader(
                cfg["bind_host"], int(port_s), group_size - 1
            )
            print(f"mesh group leader channel on "
                  f"{cfg['bind_host']}:{leader.port}; waiting for "
                  f"{group_size - 1} follower(s)", flush=True)
            leader.wait_members()
        else:
            print(f"mesh group follower rank {group_rank} joining "
                  f"{host}:{port_s}", flush=True)
            mesh_group.run_follower(host or "localhost", int(port_s))
            return 0  # leader closed the channel: group is done

    scheduler_port = cfg["scheduler_port"]
    if args.local:
        from .scheduler import serve_scheduler
        from .state import MemoryBackend, SchedulerState

        state = SchedulerState(MemoryBackend(), cfg["namespace"])
        _server, _svc, scheduler_port = serve_scheduler(
            state, "localhost", 0
        )
        print(f"embedded scheduler on localhost:{scheduler_port}", flush=True)

    num_devices = cfg["num_devices"]
    if not num_devices:
        import jax

        num_devices = len(jax.devices())
    exec_cfg = ExecutorConfig(
        host=cfg["external_host"] or cfg["bind_host"],
        bind_host=cfg["bind_host"],
        port=cfg["port"],
        work_dir=cfg["work_dir"] or None,
        concurrent_tasks=cfg["concurrent_tasks"],
        scheduler_host="localhost" if args.local else cfg["scheduler_host"],
        scheduler_port=scheduler_port,
        num_devices=num_devices,
        native_dataplane=_native_enabled(cfg["native_dataplane"]),
        metrics_port=int(cfg["metrics_port"]),
    )
    executor = Executor(exec_cfg, mesh_group=leader)
    executor.start()
    print(
        f"ballista-tpu executor {executor.id[:8]} polling "
        f"{exec_cfg.scheduler_host}:{exec_cfg.scheduler_port}, data plane on "
        f"{exec_cfg.host}:{executor.port}, work_dir={exec_cfg.work_dir}"
        + (f", mesh group of {group_size} x "
           f"{num_devices // group_size} devices" if leader else ""),
        flush=True,
    )
    if executor.health_port is not None:
        print(f"ballista-tpu executor health plane on "
              f"127.0.0.1:{executor.health_port}", flush=True)
    stop = signal.sigwait([signal.SIGINT, signal.SIGTERM])
    drain = stop == signal.SIGTERM
    print(f"signal {stop}; shutting down"
          + (" (graceful drain)" if drain else ""), flush=True)
    if leader is not None:
        leader.close()
    # SIGTERM (the orchestrator's polite stop) drains: stop accepting,
    # let in-flight tasks finish within the bound, flush pending status
    # reports. SIGINT (ctrl-C) keeps the immediate shutdown.
    executor.stop(drain=drain)
    return 0


if __name__ == "__main__":
    sys.exit(main())
