"""Executor: pull-based worker running stage tasks on the local device(s).

Re-implements the reference executor (reference: rust/executor/src/
execution_loop.rs:31-160 poll loop, flight_service.rs:89-192 partition
execution + IPC materialization, main.rs --local embedded-scheduler mode).
Improvements over the reference by design:

- tasks execute in-process (the reference self-RPCs its own Flight port,
  execution_loop.rs:90-101, and calls that "convoluted" itself);
- the data plane is a socket server (Python or the C++ native
  shuffle_server) serving the same work_dir layout.
"""

from __future__ import annotations

import logging
import os
import random
import shutil
import tempfile
import threading
import time
import uuid
from collections import deque
from concurrent import futures
from typing import Dict, Optional

from ..errors import QueryCancelled
from ..lifecycle import CancelToken, bind_token, check_cancel
from ..observability import trace_span
from ..observability.metrics import collect_plan_metrics, metrics_enabled
from ..proto import ballista_pb2 as pb
from .. import serde
from ..testing.faults import fault_point
from .dataplane import partition_path, start_data_plane
from .scheduler import SchedulerClient
from .types import PartitionId

log = logging.getLogger("ballista.executor")

POLL_INTERVAL_SECS = 0.25  # reference: 250ms, execution_loop.rs:41
# total task-profile bytes one PollWork may carry (well under the
# transport's raised 64 MB cap; see scheduler._GRPC_MSG_OPTS)
_POLL_PROFILE_BUDGET_BYTES = 8 << 20


def _poll_backoff_max_secs() -> float:
    """Poll-loop backoff ceiling while the scheduler is unreachable."""
    try:
        return max(float(os.environ.get(
            "BALLISTA_POLL_BACKOFF_MAX_SECS", "8") or 8), POLL_INTERVAL_SECS)
    except ValueError:
        return 8.0


def drain_timeout_secs() -> float:
    """``BALLISTA_DRAIN_TIMEOUT_SECS``: how long a graceful drain lets
    in-flight tasks finish before cancelling them."""
    try:
        return max(float(os.environ.get(
            "BALLISTA_DRAIN_TIMEOUT_SECS", "20") or 20), 0.0)
    except ValueError:
        return 20.0


def _needs_mesh(plan) -> bool:
    """True when the plan contains a mesh-fused operator (its SPMD
    program must run on every process of a mesh group)."""
    from ..physical.mesh_agg import MeshAggExec, MeshJoinExec

    if isinstance(plan, (MeshAggExec, MeshJoinExec)):
        return True
    return any(_needs_mesh(c) for c in plan.children())


class ExecutorConfig:
    """(reference: executor_config_spec.toml:1-61)"""

    def __init__(self, host: str = "localhost", port: int = 0,
                 work_dir: Optional[str] = None, concurrent_tasks: int = 2,
                 scheduler_host: str = "localhost",
                 scheduler_port: int = 50050,
                 bind_host: Optional[str] = None,
                 num_devices: int = 1,
                 native_dataplane: Optional[bool] = None,
                 metrics_port: Optional[int] = None):
        # host = the address peers should dial (advertised in PollWork);
        # bind_host = the local interface the data plane listens on.
        # Distinct so NAT/port-forward setups can bind 0.0.0.0 while
        # advertising an external address.
        self.host = host
        self.bind_host = bind_host if bind_host is not None else host
        # None = resolve from BALLISTA_NATIVE_DATAPLANE (default: native)
        self.native_dataplane = native_dataplane
        self.port = port
        # devices this executor owns (reported in PollWork metadata;
        # mesh fusion is driven by these fleet reports — a client
        # mesh.devices setting is only validated against them)
        self.num_devices = num_devices
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="ballista-")
        self.concurrent_tasks = concurrent_tasks
        self.scheduler_host = scheduler_host
        self.scheduler_port = scheduler_port
        # health plane port: None = resolve BALLISTA_METRICS_PORT
        # (default off for in-process executors; the binary defaults it
        # to 0 = ephemeral ON); < 0 disables
        self.metrics_port = metrics_port


class Executor:
    def __init__(self, config: ExecutorConfig, mesh_group=None):
        self.config = config
        # mesh_group: a mesh_group.GroupLeader when this executor fronts
        # a multi-process device mesh; fused tasks are broadcast so
        # every member enters the SPMD program together
        self.mesh_group = mesh_group
        self.id = str(uuid.uuid4())
        # distributed profiler: stamp this process's identity onto every
        # trace/flight-recorder record (first writer wins — harmless for
        # in-process LocalClusters, where per-task window extraction
        # re-tags records with the owning executor's id instead)
        from ..observability.tracing import set_process_identity

        set_process_identity("executor", self.id)
        self._data_plane = start_data_plane(
            config.bind_host, config.port, config.work_dir,
            native=config.native_dataplane,
        )
        self.port = self._data_plane.port
        self._client = SchedulerClient(config.scheduler_host,
                                       config.scheduler_port)
        self._pool = futures.ThreadPoolExecutor(
            max_workers=config.concurrent_tasks
        )
        self._slots = threading.Semaphore(config.concurrent_tasks)
        self._status_lock = threading.Lock()
        self._pending_status = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # lifecycle control plane: one cancel token per active task
        # (registered BEFORE the pool accepts the work so drain sees
        # queued-but-unstarted tasks too), the draining flag PollWork
        # advertises as can_accept_task=False, and a bounded memory of
        # job ids whose partial outputs were already cleaned
        self._token_lock = threading.Lock()
        self._task_tokens: Dict[str, CancelToken] = {}  # task key -> token
        self._draining = False
        self._cleaned_jobs: deque = deque(maxlen=256)
        # live progress plane: the executing plan of every in-flight
        # task, sampled on the progress cadence and piggybacked on
        # PollWork as TaskProgress records (best-effort; see
        # observability/progress.py)
        self._progress_lock = threading.Lock()
        self._running_plans: Dict[str, dict] = {}  # task key -> entry
        self._last_progress_sample = 0.0
        # health plane: task counters (benign-race ints under the GIL,
        # same policy as observability.metrics), a ring of recent task
        # summaries, and — when enabled — /healthz + /metrics +
        # /debug/queries on a local stdlib HTTP server
        self._inflight = 0
        self.tasks_completed = 0
        self.tasks_failed = 0
        self.tasks_cancelled = 0
        from ..observability.health import (QueryLog,
                                            maybe_start_health_server,
                                            metrics_port_from_env)

        self._query_log = QueryLog()
        mport = config.metrics_port
        if mport is None:
            mport = metrics_port_from_env(-1)
        self._health = maybe_start_health_server(
            "executor", mport, samples_fn=self._metric_samples,
            query_log=self._query_log,
        )

    @property
    def health_port(self) -> Optional[int]:
        return self._health.port if self._health is not None else None

    def resource_gauges(self) -> dict:
        """Current resource gauges: shipped with every heartbeat and
        exported on the local /metrics."""
        from ..ingest import pool_queue_depth
        from ..observability import memory as obs_memory
        from . import spill as _spill

        gov = _spill.governor().stats()
        return {
            "rss_bytes": obs_memory.rss_bytes(),
            "device_bytes": obs_memory.device_bytes(),
            # clamped: the counter is a benign-race int (same policy as
            # the task counters), but a lost update must never drive a
            # negative into the uint32 proto field — that would make
            # every subsequent heartbeat raise and starve the executor
            "inflight_tasks": max(0, self._inflight),
            "ingest_pool_depth": pool_queue_depth(),
            "peak_host_bytes": obs_memory.peak_host_bytes(),
            # shuffle memory governor: in-flight buffer bytes + bytes
            # spilled to disk, so the scheduler sees memory pressure
            # per executor
            "shuffle_inflight_bytes": gov["inflight_bytes"],
            "spill_bytes_total": gov["spilled_bytes_total"],
        }

    def _metric_samples(self):
        # only the executor-specific gauges: rss/device/peak are
        # appended by the health server's base process samples — going
        # through resource_gauges() here would sample them twice per
        # scrape
        from ..ingest import pool_queue_depth

        return [
            ("ballista_inflight_tasks", {}, max(0, self._inflight)),
            ("ballista_ingest_pool_depth", {}, pool_queue_depth()),
            ("ballista_tasks_completed_total", {}, self.tasks_completed),
            ("ballista_tasks_failed_total", {}, self.tasks_failed),
            ("ballista_tasks_cancelled_total", {}, self.tasks_cancelled),
        ]

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(
            target=self._poll_loop, daemon=True, name=f"poll-{self.id[:8]}"
        )
        self._thread.start()

    def stop(self, drain: bool = False,
             drain_timeout: Optional[float] = None):
        """Stop the executor. ``drain=False`` (default) keeps the old
        immediate-shutdown behavior: running tasks are abandoned
        mid-flight. ``drain=True`` is the graceful path: stop accepting
        (PollWork advertises ``can_accept_task=False``), give in-flight
        tasks up to the drain bound to finish, cancel whatever is still
        running (their failure reports are transient-shaped, so the
        scheduler re-queues them elsewhere), and flush
        ``_pending_status`` in one final poll so completion reports are
        never lost."""
        if drain:
            self._drain(drain_timeout)
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if drain:
            # final flush AFTER the poll thread stopped: whatever
            # reports the last in-flight tasks appended still reach the
            # scheduler even though no more polls will run
            try:
                self._flush_status()
            except Exception:  # noqa: BLE001 - best-effort on shutdown
                log.warning("final status flush failed", exc_info=True)
        self._data_plane.close()
        self._pool.shutdown(wait=False)
        # release device-resident table-cache pins: a stopped executor
        # must not keep device memory pinned while the process lingers
        # (embedding tests / LocalCluster reuse the same process)
        try:
            from ..cache.residency import process_table_cache

            process_table_cache().invalidate()
        except Exception:  # noqa: BLE001 - best-effort on shutdown
            pass
        if self._health is not None:
            self._health.close()

    def _drain(self, drain_timeout: Optional[float]):
        bound = (drain_timeout if drain_timeout is not None
                 else drain_timeout_secs())
        self._draining = True
        deadline = time.time() + bound
        log.info("draining executor %s: %d active task(s), bound %.1fs",
                 self.id[:8], len(self._task_tokens), bound)
        while time.time() < deadline and self._task_tokens:
            time.sleep(0.05)
        leftover = self._fire_tokens(reason="drain")
        if leftover:
            log.warning("drain bound hit; cancelled %d in-flight task(s)",
                        leftover)
            # cooperative aborts land at the next batch boundary; give
            # them a short grace so their failure reports make the
            # final flush
            grace = time.time() + 5.0
            while time.time() < grace and self._task_tokens:
                time.sleep(0.05)

    def _fire_tokens(self, reason: str,
                     job_id: Optional[str] = None) -> int:
        """Fire the cancel tokens of active tasks (all, or one job's);
        returns how many were fired."""
        with self._token_lock:
            tokens = [t for t in self._task_tokens.values()
                      if job_id is None or t.job_id == job_id]
        n = 0
        for t in tokens:
            if t.cancel(reason):
                n += 1
        return n

    def _flush_status(self):
        """One synchronous PollWork carrying only pending reports (no
        task request): the drain path's last word to the scheduler."""
        with self._status_lock:
            pending = list(self._pending_status)
            self._pending_status.clear()
        if not pending:
            return
        params = pb.PollWorkParams(can_accept_task=False)
        params.metadata.id = self.id
        params.metadata.host = self.config.host
        params.metadata.port = self.port
        params.metadata.num_devices = self.config.num_devices
        for st in pending:
            # profiles are advisory payload; the final flush is about
            # never losing the REPORTS
            if st.HasField("completed") and st.completed.HasField("profile"):
                st.completed.ClearField("profile")
            params.task_status.append(st)
        self._client.PollWork(params)

    # -- poll loop (reference: execution_loop.rs:31-76) ----------------------

    def _poll_loop(self):
        failures = 0
        backoff = 0.0
        while not self._stop.is_set():
            try:
                self._poll_once()
            except Exception as e:  # noqa: BLE001 - retry like reference
                # jittered exponential backoff (reset on success): a
                # scheduler restart must not face a thundering herd of
                # fixed-interval retries, and a down scheduler must not
                # fill the log with one traceback per 250ms
                failures += 1
                backoff = min(max(backoff * 2, POLL_INTERVAL_SECS),
                              _poll_backoff_max_secs())
                wait = backoff * (1.0 + 0.25 * random.random())
                if failures == 1:
                    log.exception("poll failed; backing off")
                else:
                    log.warning(
                        "poll still failing (%d consecutive; %s: %s); "
                        "next retry in %.2fs", failures,
                        type(e).__name__, e, wait)
                self._stop.wait(wait)
                continue
            if failures:
                log.info("scheduler reachable again after %d failed "
                         "poll(s)", failures)
            failures = 0
            backoff = 0.0
            self._stop.wait(POLL_INTERVAL_SECS)

    def _poll_once(self):
        can_accept = self._slots.acquire(blocking=False)
        if can_accept:
            self._slots.release()
        if self._draining:
            # graceful drain: finish what's in flight, accept nothing new
            can_accept = False
        params = pb.PollWorkParams(can_accept_task=can_accept)
        params.metadata.id = self.id
        params.metadata.host = self.config.host
        params.metadata.port = self.port
        params.metadata.num_devices = self.config.num_devices
        # heartbeat resource gauges: the scheduler aggregates these
        # into its own /metrics (per-executor labels)
        g = self.resource_gauges()
        params.metadata.resources.rss_bytes = int(g["rss_bytes"])
        params.metadata.resources.device_bytes = int(g["device_bytes"])
        params.metadata.resources.inflight_tasks = int(g["inflight_tasks"])
        params.metadata.resources.ingest_pool_depth = \
            int(g["ingest_pool_depth"])
        params.metadata.resources.peak_host_bytes = \
            int(g["peak_host_bytes"])
        params.metadata.resources.shuffle_inflight_bytes = \
            int(g["shuffle_inflight_bytes"])
        params.metadata.resources.spill_bytes_total = \
            int(g["spill_bytes_total"])
        with self._status_lock:
            pending = list(self._pending_status)
            self._pending_status.clear()
        # profile windows are advisory observability payload: bound what
        # one poll ships so a burst of completions (each profile up to
        # 512 KiB) can never push the request past the transport's
        # message limit — a failed PollWork would LOSE the completion
        # reports it carried (pending was already cleared) and hang the
        # job. Reports always go; overflow profiles are dropped.
        budget = _POLL_PROFILE_BUDGET_BYTES
        for st in pending:
            if st.HasField("completed") and st.completed.HasField("profile"):
                sz = st.completed.profile.ByteSize()
                if sz > budget:
                    st.completed.ClearField("profile")
                else:
                    budget -= sz
            params.task_status.append(st)
        # live progress piggyback: advisory payload, never re-delivered
        # on a failed poll (unlike the reports above — the next sample
        # supersedes a lost one anyway)
        for tp in self._maybe_sample_progress():
            params.task_progress.append(tp)
        try:
            result = self._client.PollWork(params)
        except Exception:
            # report re-delivery: a failed poll (scheduler down, RPC
            # fault) must not LOSE the completion/failure reports it
            # carried — without them the scheduler only recovers the
            # tasks via lease reaping or speculation, minutes later.
            # Re-front them so the next successful poll delivers
            # (profiles already stripped above stay stripped: advisory)
            with self._status_lock:
                self._pending_status[:0] = pending
            raise
        for job_id in result.cancelled_jobs:
            self._handle_job_cancelled(job_id)
        if result.drain and not self._draining:
            # autoscaler scale-down piggyback: stop accepting work; the
            # poll loop keeps reporting until in-flight tasks finish
            # (executor_main exits on its own drain path afterwards)
            log.warning("executor %s: scheduler requested drain; no "
                        "longer accepting tasks", self.id[:8])
            self._draining = True
        if result.HasField("task"):
            self._run_task(result.task)

    def _maybe_sample_progress(self):
        """TaskProgress records for this poll, or [] (plane disabled,
        cadence not due, nothing running, or a triggered
        ``scheduler.progress_report`` fault). Samples never force a
        device sync (snapshot_rows resolves only ready scalars) and any
        failure here degrades to an unsampled poll — progress is
        advisory by contract."""
        from ..observability import progress as obs_progress

        interval = obs_progress.progress_interval_secs()
        if interval is None:
            return []
        now = time.time()
        if now - self._last_progress_sample < interval:
            return []
        self._last_progress_sample = now
        with self._progress_lock:
            entries = list(self._running_plans.values())
        if not entries:
            return []
        out = []
        try:
            # chaos surface: "drop" skips this round's piggyback,
            # "delay" stalls it, a "fail" raise is swallowed below —
            # results must be byte-identical under any of them
            if fault_point("scheduler.progress_report",
                           executor=self.id[:8]) == "drop":
                return []
            for entry in entries:
                if entry.get("input_total") is None:
                    # this task executes ONE partition of the shared
                    # stage plan: estimate its per-partition share
                    entry["input_total"] = obs_progress.plan_input_estimate(
                        entry["plan"], per_partition=True)
                s = obs_progress.sample_plan(
                    entry["plan"], input_rows_total=entry["input_total"])
                pid = entry["pid"]
                tp = pb.TaskProgress()
                tp.partition_id.job_id = pid.job_id
                tp.partition_id.stage_id = pid.stage_id
                tp.partition_id.partition_id = pid.partition_id
                tp.stage_version = entry["stage_version"]
                tp.operator = s["operator"] or ""
                tp.rows_so_far = max(int(s["rows_so_far"]), 0)
                tp.input_rows_total = max(int(s["input_rows_total"]), 0)
                tp.bytes_so_far = max(int(s["bytes_so_far"]), 0)
                tp.elapsed_seconds = now - entry["t0"]
                out.append(tp)
        except Exception:  # noqa: BLE001 - best-effort by contract
            log.debug("progress sample failed", exc_info=True)
            return []
        return out

    def _handle_job_cancelled(self, job_id: str):
        """A PollWorkResult carried this job id as cancelled: abort its
        running tasks at their next batch boundary and clean up partial
        stage outputs (completed shuffle files included — nothing will
        ever read them). Idempotent across polls: the id rides every
        poll for a broadcast window."""
        fired = self._fire_tokens(reason="cancelled", job_id=job_id)
        if fired:
            log.info("job %s cancelled; aborting %d running task(s)",
                     job_id, fired)
        # server-side stream abort: chunk streams this executor is
        # serving for the job terminate at their next chunk boundary
        from .dataplane import mark_job_cancelled

        mark_job_cancelled(job_id)
        if job_id not in self._cleaned_jobs:
            self._cleaned_jobs.append(job_id)
            self._cleanup_job_outputs(job_id)

    def _cleanup_job_outputs(self, job_id: str):
        path = os.path.join(self.config.work_dir, job_id)
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
            log.info("removed cancelled job outputs: %s", path)

    # -- task execution (in-process; reference: run_received_tasks) ----------

    def _run_task(self, td: pb.TaskDefinition):
        self._slots.acquire()
        pid = PartitionId(td.task_id.job_id, td.task_id.stage_id,
                          td.task_id.partition_id)
        # per-task cancel token: registered BEFORE the pool accepts the
        # work so a cancel/drain arriving while the task is still queued
        # aborts it at entry, not after a full execution
        token = CancelToken(job_id=pid.job_id)
        with self._token_lock:
            self._task_tokens[pid.key()] = token
        try:
            plan = serde.physical_from_proto(td.plan)
            # whole-stage fusion happens AFTER deserialization, executor-
            # side: the wire format never carries fused operators, and a
            # re-planned stage's fresh task re-fuses to the same value-
            # keyed signatures (zero new compiles)
            from ..physical.fusion import maybe_fuse

            plan = maybe_fuse(plan)
            shuffle = None
            if td.shuffle_output_partitions:
                hash_exprs = [
                    serde.expr_from_proto(e) for e in td.shuffle_hash_exprs
                ]
                shuffle = (hash_exprs or None, td.shuffle_output_partitions)
        except Exception as e:  # noqa: BLE001 - bad plan/wire payload
            # deserialize/fuse failed BEFORE the pool accepted the work:
            # release the slot and the registered token (a leaked token
            # would make every future drain wait its full bound) and
            # report the failure instead of wedging the task forever
            with self._token_lock:
                self._task_tokens.pop(pid.key(), None)
            self._slots.release()
            log.exception("task %s rejected before execution", pid)
            self.tasks_failed += 1
            self._report_failed(pid, f"{type(e).__name__}: {e}",
                                td.stage_version)
            return

        def work():
            from ..observability import distributed as obs_dist
            from ..observability.tracing import flow

            t0 = time.time()
            self._inflight += 1
            # live progress: expose the executing plan to the poll
            # thread's sampler for the duration of the task
            with self._progress_lock:
                self._running_plans[pid.key()] = {
                    "pid": pid, "plan": plan, "t0": t0,
                    "stage_version": td.stage_version,
                    "input_total": None,
                }
            # per-task profile window (distributed profiler): snapshot
            # the process-wide ingest/compile accumulators up front so
            # the completion payload can ship deltas alongside the
            # flight-recorder span window
            capture = obs_dist.task_profile_enabled()
            if capture:
                from ..compile import compile_stats
                from ..ingest import phase_totals

                phases0, compile0 = phase_totals(), compile_stats()
            try:
                # fault point (chaos sweep): an injected failure here is
                # a transient task failure — the scheduler re-queues it
                # within the retry budget
                fault_point("executor.task.start", task=pid.key())
                # token checked at entry (a queued task of an already-
                # cancelled job must not run at all), then bound to the
                # thread so every batch boundary under execute sees it
                token.check()
                # flow(): every span/event emitted while this task runs
                # (ingest producers included — PrefetchHandle re-binds
                # the captured flow on its pool worker) carries the
                # job/stage/task triple for cross-process correlation
                with bind_token(token), \
                        flow(job=pid.job_id, stage=pid.stage_id,
                             task=pid.key()), \
                        trace_span("executor.task", task=pid.key(),
                                   executor=self.id[:8]):
                    if self.mesh_group is not None and _needs_mesh(plan):
                        # group task: broadcast so every member process
                        # enters the SPMD program together; serialized (the
                        # collectives must align across processes)
                        with self.mesh_group.lock:
                            seq = self.mesh_group.broadcast(
                                td.SerializeToString())
                            stats = self.execute_partition(pid, plan, shuffle)
                            self.mesh_group.wait_acks(seq)
                    else:
                        stats = self.execute_partition(pid, plan, shuffle)
                profile = None
                if capture:
                    try:
                        profile = obs_dist.capture_task_profile(
                            pid.key(), t0, time.time() - t0, self.id,
                            phases0=phases0, compile0=compile0)
                    except Exception:  # noqa: BLE001 - observability
                        log.exception("task profile capture failed")
                self._report_completed(pid, stats, td.stage_version,
                                       profile=profile)
                self.tasks_completed += 1
                # same shape as the scheduler's query ring entries
                # (status/wall_seconds/output_rows — the systables
                # record contract), "rows"/"state" kept as legacy keys
                self._query_log.record({
                    "task": pid.key(), "state": "completed",
                    "status": "completed",
                    "wall_seconds": round(time.time() - t0, 4),
                    "rows": int(stats.get("num_rows", 0)),
                    "output_rows": int(stats.get("num_rows", 0)),
                })
            except QueryCancelled as e:
                # cooperative abort at a batch boundary: terminal for
                # this attempt but NOT a failure. The report is still
                # filed ("QueryCancelled:" is transient-shaped): for a
                # job-level cancel the scheduler drops it; for a drain
                # the job is live and the task re-queues elsewhere.
                log.info("task %s cancelled (%s)", pid, e.reason)
                self.tasks_cancelled += 1
                self._query_log.record({
                    "task": pid.key(), "state": "cancelled",
                    "status": "cancelled",
                    "wall_seconds": round(time.time() - t0, 4),
                    "cancel_reason": e.reason,
                })
                self._report_failed(pid, f"{type(e).__name__}: {e}",
                                    td.stage_version)
                # a JOB-level cancel removes the job's outputs (the
                # poll-side cleanup may have run before this task
                # released its write handle). A drain must NOT: the job
                # is live and this executor's earlier completed stage
                # files may still be fetched while the drain grace runs
                if e.reason != "drain":
                    self._cleanup_job_outputs(pid.job_id)
            except Exception as e:  # noqa: BLE001 - task failure
                log.exception("task %s failed", pid)
                self.tasks_failed += 1
                self._query_log.record({
                    "task": pid.key(), "state": "failed",
                    "status": "failed",
                    "wall_seconds": round(time.time() - t0, 4),
                    "error": f"{type(e).__name__}: {e}"[:300],
                })
                # prefix the exception class: the scheduler retries
                # transient (IO-shaped) failures but fails fast on
                # deterministic ones (bad plans, overflow limits)
                self._report_failed(pid, f"{type(e).__name__}: {e}",
                                    td.stage_version)
            finally:
                with self._token_lock:
                    self._task_tokens.pop(pid.key(), None)
                with self._progress_lock:
                    self._running_plans.pop(pid.key(), None)
                self._inflight -= 1
                self._slots.release()

        self._pool.submit(work)

    def execute_partition(self, pid: PartitionId, plan,
                          shuffle=None) -> dict:
        """Run one stage partition and STREAM its output to disk
        (reference: flight_service.rs:89-192). Batches are written as
        they are produced — bounded Arrow-IPC chunks through
        ``ipc.PartitionWriter`` — so the executor never holds a whole
        partition's output alongside its conversion buffers; the cancel
        token is checked at every batch pull AND every chunk write.
        With ``shuffle`` ((hash_exprs|None, n_out)) the output is
        hash/round-robin split into one shuffle-q file per consumer
        partition."""
        from ..io import ipc
        from ..ingest import cancel_plan, prime_plan

        t0 = time.time()
        # parallel ingest: start this task's leaf-scan parse+H2D on the
        # pool before pulling, so a plan with several scan leaves (e.g.
        # a merged join stage) parses them concurrently; primed handles
        # an aborted task leaves behind are cancelled, never leaked
        prime_plan(plan, partitions=[pid.partition_id])
        if shuffle is not None:
            try:
                stats = self._write_shuffled(pid, plan, shuffle, t0)
            finally:
                # handles the plan never consumed (failures) must not
                # leave producers parked on full queues
                cancel_plan(plan)
            stats["task_metrics"] = self._harvest_metrics(
                plan, time.time() - t0, stats, shuffled=True)
            return stats
        path = partition_path(self.config.work_dir, pid.job_id, pid.stage_id,
                              pid.partition_id)
        writer = ipc.PartitionWriter(path, schema=plan.output_schema(),
                                     compute_column_stats=True)
        try:
            with trace_span("dataplane.write", path=path):
                for batch in plan.execute(pid.partition_id):
                    # cooperative cancellation at the batch boundary: a
                    # fired token (job cancel, drain) stops the pull
                    # here; cancel_plan below unparks ingest producers
                    check_cancel()
                    writer.write_batch(batch)
                # empty partition: close() synthesizes one empty batch
                # with the plan schema
                stats = writer.close()
        except BaseException:
            writer.abort()
            raise
        finally:
            cancel_plan(plan)
        log.info("executed %s in %.1fs (%d rows)", pid.key(),
                 time.time() - t0, stats["num_rows"])
        out = {**stats, "path": path}
        out["task_metrics"] = self._harvest_metrics(
            plan, time.time() - t0, stats, write_secs=writer.write_seconds)
        return out

    def _harvest_metrics(self, plan, elapsed_total: float, stats: dict,
                         shuffled: bool = False,
                         write_secs: float = 0.0) -> "dict | None":
        """Per-operator metrics off the executed plan + a synthetic
        write-side row (shuffle/partition IPC write happens outside the
        plan, so bytes_written needs its own operator row; its position
        is stable across tasks of a stage, keeping positional stage
        aggregation valid)."""
        if not metrics_enabled():
            return None
        ops = collect_plan_metrics(plan)
        write_row = {
            "operator": "ShuffleWrite" if shuffled else "PartitionWrite",
            "depth": 0,
            "metrics": {"bytes_written": int(stats.get("num_bytes", 0))},
        }
        if write_secs:
            write_row["metrics"]["elapsed_write"] = write_secs
        ops.append(write_row)
        return {"operators": ops, "elapsed_total": elapsed_total}

    def _write_shuffled(self, pid: PartitionId, plan, shuffle,
                        t0: float) -> dict:
        """Streaming n_out-way shuffle write: every produced batch is
        hash-split and its slices appended to the per-consumer-partition
        stream writers IMMEDIATELY, so neither the stage output nor its
        Arrow conversion buffers ever accumulate — host memory peaks at
        one bounded chunk per writer. Record-batch structure matches the
        old materialize-then-write path (one batch per (input batch, q),
        plus chunk splits), keeping results byte-identical."""
        import jax.numpy as jnp

        from ..io import ipc
        from ..kernels.expr_eval import Evaluator
        from ..physical.operators import compute_partition_ids
        from .dataplane import shuffle_path

        hash_exprs, n_out = shuffle
        schema = plan.output_schema()
        ev = Evaluator(schema)
        writers = []
        base = None
        for q in range(n_out):
            path = shuffle_path(self.config.work_dir, pid.job_id,
                                pid.stage_id, pid.partition_id, q)
            base = path
            writers.append(ipc.PartitionWriter(path, schema=schema))
        totals = {"num_rows": 0, "num_batches": 0, "num_bytes": 0}
        offset = 0
        try:
            with trace_span("dataplane.write", task=pid.key(),
                            fan_out=n_out):
                for b in plan.execute(pid.partition_id):
                    check_cancel()
                    pids = compute_partition_ids(b, hash_exprs, n_out,
                                                 offset, ev)
                    for q in range(n_out):
                        writers[q].write_batch(b.with_selection(
                            jnp.logical_and(b.selection, pids == q)))
                    offset += b.num_rows_host()
                # per-output-partition byte histogram: the signal
                # adaptive re-planning coalesces/splits the consuming
                # stage on. Writers that saw no batches (or no rows)
                # close with one empty schema-bearing batch.
                qbytes = []
                for q in range(n_out):
                    st = writers[q].close()
                    qbytes.append(int(st["num_bytes"]))
                    for k in totals:
                        totals[k] += st[k]
        except BaseException:
            for w in writers:
                w.abort()
            raise
        totals["shuffle_partition_bytes"] = qbytes
        log.info("executed %s (shuffle x%d) in %.1fs (%d rows)", pid.key(),
                 n_out, time.time() - t0, totals["num_rows"])
        return {**totals, "path": base}

    def _report_completed(self, pid: PartitionId, stats: dict,
                          stage_version: int = 0, profile=None):
        ts = pb.TaskStatus()
        ts.partition_id.job_id = pid.job_id
        ts.partition_id.stage_id = pid.stage_id
        ts.partition_id.partition_id = pid.partition_id
        ts.stage_version = stage_version
        ts.completed.executor_id = self.id
        ts.completed.path = stats["path"]
        tm = stats.get("task_metrics")
        if tm:
            serde.task_metrics_to_proto(tm, ts.completed.metrics)
        if profile:
            serde.task_profile_to_proto(profile, ts.completed.profile)
        serde.stats_to_proto(stats, ts.completed.stats)
        with self._status_lock:
            self._pending_status.append(ts)

    def _report_failed(self, pid: PartitionId, error: str,
                       stage_version: int = 0):
        ts = pb.TaskStatus()
        ts.partition_id.job_id = pid.job_id
        ts.partition_id.stage_id = pid.stage_id
        ts.partition_id.partition_id = pid.partition_id
        ts.stage_version = stage_version
        ts.failed.error = error
        with self._status_lock:
            self._pending_status.append(ts)


# ---------------------------------------------------------------------------
# Local cluster helper (reference: executor --local mode, main.rs:101-138)
# ---------------------------------------------------------------------------


class LocalCluster:
    """In-process scheduler + N executors (for tests and single-host use)."""

    def __init__(self, num_executors: int = 2, concurrent_tasks: int = 2,
                 scheduler_port: int = 0, num_devices: int = 1,
                 speculation_age_secs: float = 60.0,
                 metrics_port: "int | None" = None,
                 backend=None):
        from .scheduler import serve_scheduler
        from .state import MemoryBackend, SchedulerState

        # metrics_port: None = off (in-process test clusters shouldn't
        # bind sockets unasked); 0 = ephemeral health plane on the
        # scheduler AND every executor
        # backend: a durable KvBackend (e.g. SqliteBackend) makes this
        # in-process cluster restart-recoverable — the controlplane
        # tests rebuild a LocalCluster over the same file
        self.state = SchedulerState(backend or MemoryBackend())
        self.server, self.service, self.port = serve_scheduler(
            self.state, "localhost", scheduler_port,
            speculation_age_secs=speculation_age_secs,
            metrics_port=metrics_port,
        )
        # remember the executor shape: the autoscaler's add_executor
        # hook spawns clones of the launch-time fleet
        self._exec_kwargs = dict(
            concurrent_tasks=concurrent_tasks,
            num_devices=num_devices,
            # executors always take an ephemeral port (several per
            # host; a fixed one could only serve the first); a
            # negative caller value means OFF here too (-1, not
            # None — None would fall back to the env default and
            # re-enable what the caller explicitly disabled)
            metrics_port=(None if metrics_port is None
                          else 0 if metrics_port >= 0 else -1),
        )
        self.executors = []
        for _ in range(num_executors):
            self.add_executor()

    def add_executor(self) -> "Executor":
        """Spawn one more in-process executor (the autoscaler's
        LocalCluster scale-up hook)."""
        cfg = ExecutorConfig(
            scheduler_host="localhost", scheduler_port=self.port,
            **self._exec_kwargs,
        )
        e = Executor(cfg)
        e.start()
        self.executors.append(e)
        return e

    def remove_executor(self, executor_id: "str | None" = None
                        ) -> "str | None":
        """Gracefully drain one executor (the autoscaler's LocalCluster
        scale-down hook): the youngest, or the one with ``executor_id``.
        Returns the drained executor's id, or None when empty."""
        if not self.executors:
            return None
        if executor_id is None:
            e = self.executors.pop()
        else:
            match = [x for x in self.executors if x.id == executor_id]
            if not match:
                return None
            e = match[0]
            self.executors.remove(e)
        e.stop(drain=True)
        return e.id

    @property
    def scheduler_health_port(self) -> "int | None":
        h = getattr(self.service, "health", None)
        return h.port if h is not None else None

    def shutdown(self):
        for e in self.executors:
            e.stop()
        self.service.close_health()
        self.server.stop(grace=None)
