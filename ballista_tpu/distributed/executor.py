"""Executor: pull-based worker running stage tasks on the local device(s).

Re-implements the reference executor (reference: rust/executor/src/
execution_loop.rs:31-160 poll loop, flight_service.rs:89-192 partition
execution + IPC materialization, main.rs --local embedded-scheduler mode).
Improvements over the reference by design:

- tasks execute in-process (the reference self-RPCs its own Flight port,
  execution_loop.rs:90-101, and calls that "convoluted" itself);
- the data plane is a socket server (Python or the C++ native
  shuffle_server) serving the same work_dir layout.
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
import time
import uuid
from concurrent import futures
from typing import Optional

from ..observability import trace_span
from ..observability.metrics import collect_plan_metrics, metrics_enabled
from ..proto import ballista_pb2 as pb
from .. import serde
from .dataplane import partition_path, start_data_plane
from .scheduler import SchedulerClient
from .types import PartitionId

log = logging.getLogger("ballista.executor")

POLL_INTERVAL_SECS = 0.25  # reference: 250ms, execution_loop.rs:41
# total task-profile bytes one PollWork may carry (well under the
# transport's raised 64 MB cap; see scheduler._GRPC_MSG_OPTS)
_POLL_PROFILE_BUDGET_BYTES = 8 << 20


def _needs_mesh(plan) -> bool:
    """True when the plan contains a mesh-fused operator (its SPMD
    program must run on every process of a mesh group)."""
    from ..physical.mesh_agg import MeshAggExec, MeshJoinExec

    if isinstance(plan, (MeshAggExec, MeshJoinExec)):
        return True
    return any(_needs_mesh(c) for c in plan.children())


class ExecutorConfig:
    """(reference: executor_config_spec.toml:1-61)"""

    def __init__(self, host: str = "localhost", port: int = 0,
                 work_dir: Optional[str] = None, concurrent_tasks: int = 2,
                 scheduler_host: str = "localhost",
                 scheduler_port: int = 50050,
                 bind_host: Optional[str] = None,
                 num_devices: int = 1,
                 native_dataplane: Optional[bool] = None,
                 metrics_port: Optional[int] = None):
        # host = the address peers should dial (advertised in PollWork);
        # bind_host = the local interface the data plane listens on.
        # Distinct so NAT/port-forward setups can bind 0.0.0.0 while
        # advertising an external address.
        self.host = host
        self.bind_host = bind_host if bind_host is not None else host
        # None = resolve from BALLISTA_NATIVE_DATAPLANE (default: native)
        self.native_dataplane = native_dataplane
        self.port = port
        # devices this executor owns (reported in PollWork metadata;
        # mesh fusion is driven by these fleet reports — a client
        # mesh.devices setting is only validated against them)
        self.num_devices = num_devices
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="ballista-")
        self.concurrent_tasks = concurrent_tasks
        self.scheduler_host = scheduler_host
        self.scheduler_port = scheduler_port
        # health plane port: None = resolve BALLISTA_METRICS_PORT
        # (default off for in-process executors; the binary defaults it
        # to 0 = ephemeral ON); < 0 disables
        self.metrics_port = metrics_port


class Executor:
    def __init__(self, config: ExecutorConfig, mesh_group=None):
        self.config = config
        # mesh_group: a mesh_group.GroupLeader when this executor fronts
        # a multi-process device mesh; fused tasks are broadcast so
        # every member enters the SPMD program together
        self.mesh_group = mesh_group
        self.id = str(uuid.uuid4())
        # distributed profiler: stamp this process's identity onto every
        # trace/flight-recorder record (first writer wins — harmless for
        # in-process LocalClusters, where per-task window extraction
        # re-tags records with the owning executor's id instead)
        from ..observability.tracing import set_process_identity

        set_process_identity("executor", self.id)
        self._data_plane = start_data_plane(
            config.bind_host, config.port, config.work_dir,
            native=config.native_dataplane,
        )
        self.port = self._data_plane.port
        self._client = SchedulerClient(config.scheduler_host,
                                       config.scheduler_port)
        self._pool = futures.ThreadPoolExecutor(
            max_workers=config.concurrent_tasks
        )
        self._slots = threading.Semaphore(config.concurrent_tasks)
        self._status_lock = threading.Lock()
        self._pending_status = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # health plane: task counters (benign-race ints under the GIL,
        # same policy as observability.metrics), a ring of recent task
        # summaries, and — when enabled — /healthz + /metrics +
        # /debug/queries on a local stdlib HTTP server
        self._inflight = 0
        self.tasks_completed = 0
        self.tasks_failed = 0
        from ..observability.health import (QueryLog,
                                            maybe_start_health_server,
                                            metrics_port_from_env)

        self._query_log = QueryLog()
        mport = config.metrics_port
        if mport is None:
            mport = metrics_port_from_env(-1)
        self._health = maybe_start_health_server(
            "executor", mport, samples_fn=self._metric_samples,
            query_log=self._query_log,
        )

    @property
    def health_port(self) -> Optional[int]:
        return self._health.port if self._health is not None else None

    def resource_gauges(self) -> dict:
        """Current resource gauges: shipped with every heartbeat and
        exported on the local /metrics."""
        from ..ingest import pool_queue_depth
        from ..observability import memory as obs_memory

        return {
            "rss_bytes": obs_memory.rss_bytes(),
            "device_bytes": obs_memory.device_bytes(),
            # clamped: the counter is a benign-race int (same policy as
            # the task counters), but a lost update must never drive a
            # negative into the uint32 proto field — that would make
            # every subsequent heartbeat raise and starve the executor
            "inflight_tasks": max(0, self._inflight),
            "ingest_pool_depth": pool_queue_depth(),
            "peak_host_bytes": obs_memory.peak_host_bytes(),
        }

    def _metric_samples(self):
        # only the executor-specific gauges: rss/device/peak are
        # appended by the health server's base process samples — going
        # through resource_gauges() here would sample them twice per
        # scrape
        from ..ingest import pool_queue_depth

        return [
            ("ballista_inflight_tasks", {}, max(0, self._inflight)),
            ("ballista_ingest_pool_depth", {}, pool_queue_depth()),
            ("ballista_tasks_completed_total", {}, self.tasks_completed),
            ("ballista_tasks_failed_total", {}, self.tasks_failed),
        ]

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(
            target=self._poll_loop, daemon=True, name=f"poll-{self.id[:8]}"
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self._data_plane.close()
        self._pool.shutdown(wait=False)
        if self._health is not None:
            self._health.close()

    # -- poll loop (reference: execution_loop.rs:31-76) ----------------------

    def _poll_loop(self):
        while not self._stop.is_set():
            try:
                self._poll_once()
            except Exception:  # noqa: BLE001 - warn and retry like reference
                log.exception("poll failed; retrying")
            self._stop.wait(POLL_INTERVAL_SECS)

    def _poll_once(self):
        can_accept = self._slots.acquire(blocking=False)
        if can_accept:
            self._slots.release()
        params = pb.PollWorkParams(can_accept_task=can_accept)
        params.metadata.id = self.id
        params.metadata.host = self.config.host
        params.metadata.port = self.port
        params.metadata.num_devices = self.config.num_devices
        # heartbeat resource gauges: the scheduler aggregates these
        # into its own /metrics (per-executor labels)
        g = self.resource_gauges()
        params.metadata.resources.rss_bytes = int(g["rss_bytes"])
        params.metadata.resources.device_bytes = int(g["device_bytes"])
        params.metadata.resources.inflight_tasks = int(g["inflight_tasks"])
        params.metadata.resources.ingest_pool_depth = \
            int(g["ingest_pool_depth"])
        params.metadata.resources.peak_host_bytes = \
            int(g["peak_host_bytes"])
        with self._status_lock:
            pending = list(self._pending_status)
            self._pending_status.clear()
        # profile windows are advisory observability payload: bound what
        # one poll ships so a burst of completions (each profile up to
        # 512 KiB) can never push the request past the transport's
        # message limit — a failed PollWork would LOSE the completion
        # reports it carried (pending was already cleared) and hang the
        # job. Reports always go; overflow profiles are dropped.
        budget = _POLL_PROFILE_BUDGET_BYTES
        for st in pending:
            if st.HasField("completed") and st.completed.HasField("profile"):
                sz = st.completed.profile.ByteSize()
                if sz > budget:
                    st.completed.ClearField("profile")
                else:
                    budget -= sz
            params.task_status.append(st)
        result = self._client.PollWork(params)
        if result.HasField("task"):
            self._run_task(result.task)

    # -- task execution (in-process; reference: run_received_tasks) ----------

    def _run_task(self, td: pb.TaskDefinition):
        self._slots.acquire()
        pid = PartitionId(td.task_id.job_id, td.task_id.stage_id,
                          td.task_id.partition_id)
        plan = serde.physical_from_proto(td.plan)
        # whole-stage fusion happens AFTER deserialization, executor-
        # side: the wire format never carries fused operators, and a
        # re-planned stage's fresh task re-fuses to the same value-keyed
        # signatures (zero new compiles)
        from ..physical.fusion import maybe_fuse

        plan = maybe_fuse(plan)
        shuffle = None
        if td.shuffle_output_partitions:
            hash_exprs = [
                serde.expr_from_proto(e) for e in td.shuffle_hash_exprs
            ]
            shuffle = (hash_exprs or None, td.shuffle_output_partitions)

        def work():
            from ..observability import distributed as obs_dist
            from ..observability.tracing import flow

            t0 = time.time()
            self._inflight += 1
            # per-task profile window (distributed profiler): snapshot
            # the process-wide ingest/compile accumulators up front so
            # the completion payload can ship deltas alongside the
            # flight-recorder span window
            capture = obs_dist.task_profile_enabled()
            if capture:
                from ..compile import compile_stats
                from ..ingest import phase_totals

                phases0, compile0 = phase_totals(), compile_stats()
            try:
                # flow(): every span/event emitted while this task runs
                # (ingest producers included — PrefetchHandle re-binds
                # the captured flow on its pool worker) carries the
                # job/stage/task triple for cross-process correlation
                with flow(job=pid.job_id, stage=pid.stage_id,
                          task=pid.key()), \
                        trace_span("executor.task", task=pid.key(),
                                   executor=self.id[:8]):
                    if self.mesh_group is not None and _needs_mesh(plan):
                        # group task: broadcast so every member process
                        # enters the SPMD program together; serialized (the
                        # collectives must align across processes)
                        with self.mesh_group.lock:
                            seq = self.mesh_group.broadcast(
                                td.SerializeToString())
                            stats = self.execute_partition(pid, plan, shuffle)
                            self.mesh_group.wait_acks(seq)
                    else:
                        stats = self.execute_partition(pid, plan, shuffle)
                profile = None
                if capture:
                    try:
                        profile = obs_dist.capture_task_profile(
                            pid.key(), t0, time.time() - t0, self.id,
                            phases0=phases0, compile0=compile0)
                    except Exception:  # noqa: BLE001 - observability
                        log.exception("task profile capture failed")
                self._report_completed(pid, stats, td.stage_version,
                                       profile=profile)
                self.tasks_completed += 1
                # same shape as the scheduler's query ring entries
                # (status/wall_seconds/output_rows — the systables
                # record contract), "rows"/"state" kept as legacy keys
                self._query_log.record({
                    "task": pid.key(), "state": "completed",
                    "status": "completed",
                    "wall_seconds": round(time.time() - t0, 4),
                    "rows": int(stats.get("num_rows", 0)),
                    "output_rows": int(stats.get("num_rows", 0)),
                })
            except Exception as e:  # noqa: BLE001 - task failure
                log.exception("task %s failed", pid)
                self.tasks_failed += 1
                self._query_log.record({
                    "task": pid.key(), "state": "failed",
                    "status": "failed",
                    "wall_seconds": round(time.time() - t0, 4),
                    "error": f"{type(e).__name__}: {e}"[:300],
                })
                # prefix the exception class: the scheduler retries
                # transient (IO-shaped) failures but fails fast on
                # deterministic ones (bad plans, overflow limits)
                self._report_failed(pid, f"{type(e).__name__}: {e}",
                                    td.stage_version)
            finally:
                self._inflight -= 1
                self._slots.release()

        self._pool.submit(work)

    def execute_partition(self, pid: PartitionId, plan,
                          shuffle=None) -> dict:
        """Run one stage partition and materialize its output
        (reference: flight_service.rs:89-192). With ``shuffle``
        ((hash_exprs|None, n_out)) the output is hash/round-robin split
        into one shuffle-q file per consumer partition."""
        from ..io import ipc
        from ..ingest import cancel_plan, prime_plan

        t0 = time.time()
        # parallel ingest: start this task's leaf-scan parse+H2D on the
        # pool before pulling, so a plan with several scan leaves (e.g.
        # a merged join stage) parses them concurrently; primed handles
        # an aborted task leaves behind are cancelled, never leaked
        prime_plan(plan, partitions=[pid.partition_id])
        try:
            batches = list(plan.execute(pid.partition_id))
        finally:
            # handles the plan never consumed (limit short-circuits,
            # failures) must not leave producers parked on full queues
            cancel_plan(plan)
        if shuffle is not None:
            stats = self._write_shuffled(pid, plan, batches, shuffle, t0)
            stats["task_metrics"] = self._harvest_metrics(
                plan, time.time() - t0, stats, shuffled=True)
            return stats
        path = partition_path(self.config.work_dir, pid.job_id, pid.stage_id,
                              pid.partition_id)
        tw = time.time()
        with trace_span("dataplane.write", path=path):
            if batches:
                stats = ipc.write_partition(path, batches)
            else:
                # empty partition: write an empty file with the plan schema
                from ..columnar import empty_batch

                stats = ipc.write_partition(
                    path, [empty_batch(plan.output_schema())])
        log.info("executed %s in %.1fs (%d rows)", pid.key(),
                 time.time() - t0, stats["num_rows"])
        out = {**stats, "path": path}
        out["task_metrics"] = self._harvest_metrics(
            plan, time.time() - t0, stats, write_secs=time.time() - tw)
        return out

    def _harvest_metrics(self, plan, elapsed_total: float, stats: dict,
                         shuffled: bool = False,
                         write_secs: float = 0.0) -> "dict | None":
        """Per-operator metrics off the executed plan + a synthetic
        write-side row (shuffle/partition IPC write happens outside the
        plan, so bytes_written needs its own operator row; its position
        is stable across tasks of a stage, keeping positional stage
        aggregation valid)."""
        if not metrics_enabled():
            return None
        ops = collect_plan_metrics(plan)
        write_row = {
            "operator": "ShuffleWrite" if shuffled else "PartitionWrite",
            "depth": 0,
            "metrics": {"bytes_written": int(stats.get("num_bytes", 0))},
        }
        if write_secs:
            write_row["metrics"]["elapsed_write"] = write_secs
        ops.append(write_row)
        return {"operators": ops, "elapsed_total": elapsed_total}

    def _write_shuffled(self, pid: PartitionId, plan, batches, shuffle,
                        t0: float) -> dict:
        import jax.numpy as jnp

        from ..io import ipc
        from ..kernels.expr_eval import Evaluator
        from ..physical.operators import compute_partition_ids
        from .dataplane import shuffle_path

        hash_exprs, n_out = shuffle
        schema = plan.output_schema()
        ev = Evaluator(schema)
        if not batches:
            from ..columnar import empty_batch

            batches = [empty_batch(schema)]
        totals = {"num_rows": 0, "num_batches": 0, "num_bytes": 0}
        masked = [[] for _ in range(n_out)]
        offset = 0
        for b in batches:
            pids = compute_partition_ids(b, hash_exprs, n_out, offset, ev)
            for q in range(n_out):
                masked[q].append(
                    b.with_selection(jnp.logical_and(b.selection, pids == q))
                )
            offset += b.num_rows_host()
        base = None
        # per-output-partition byte histogram: the signal adaptive
        # re-planning coalesces/splits the consuming stage on
        qbytes = []
        with trace_span("dataplane.write", task=pid.key(), fan_out=n_out):
            for q in range(n_out):
                path = shuffle_path(self.config.work_dir, pid.job_id,
                                    pid.stage_id, pid.partition_id, q)
                base = path
                st = ipc.write_partition(path, masked[q],
                                         compute_column_stats=False)
                qbytes.append(int(st["num_bytes"]))
                for k in totals:
                    totals[k] += st[k]
        totals["shuffle_partition_bytes"] = qbytes
        log.info("executed %s (shuffle x%d) in %.1fs (%d rows)", pid.key(),
                 n_out, time.time() - t0, totals["num_rows"])
        return {**totals, "path": base}

    def _report_completed(self, pid: PartitionId, stats: dict,
                          stage_version: int = 0, profile=None):
        ts = pb.TaskStatus()
        ts.partition_id.job_id = pid.job_id
        ts.partition_id.stage_id = pid.stage_id
        ts.partition_id.partition_id = pid.partition_id
        ts.stage_version = stage_version
        ts.completed.executor_id = self.id
        ts.completed.path = stats["path"]
        tm = stats.get("task_metrics")
        if tm:
            serde.task_metrics_to_proto(tm, ts.completed.metrics)
        if profile:
            serde.task_profile_to_proto(profile, ts.completed.profile)
        serde.stats_to_proto(stats, ts.completed.stats)
        with self._status_lock:
            self._pending_status.append(ts)

    def _report_failed(self, pid: PartitionId, error: str,
                       stage_version: int = 0):
        ts = pb.TaskStatus()
        ts.partition_id.job_id = pid.job_id
        ts.partition_id.stage_id = pid.stage_id
        ts.partition_id.partition_id = pid.partition_id
        ts.stage_version = stage_version
        ts.failed.error = error
        with self._status_lock:
            self._pending_status.append(ts)


# ---------------------------------------------------------------------------
# Local cluster helper (reference: executor --local mode, main.rs:101-138)
# ---------------------------------------------------------------------------


class LocalCluster:
    """In-process scheduler + N executors (for tests and single-host use)."""

    def __init__(self, num_executors: int = 2, concurrent_tasks: int = 2,
                 scheduler_port: int = 0, num_devices: int = 1,
                 speculation_age_secs: float = 60.0,
                 metrics_port: "int | None" = None):
        from .scheduler import serve_scheduler
        from .state import MemoryBackend, SchedulerState

        # metrics_port: None = off (in-process test clusters shouldn't
        # bind sockets unasked); 0 = ephemeral health plane on the
        # scheduler AND every executor
        self.state = SchedulerState(MemoryBackend())
        self.server, self.service, self.port = serve_scheduler(
            self.state, "localhost", scheduler_port,
            speculation_age_secs=speculation_age_secs,
            metrics_port=metrics_port,
        )
        self.executors = []
        for _ in range(num_executors):
            cfg = ExecutorConfig(
                scheduler_host="localhost", scheduler_port=self.port,
                concurrent_tasks=concurrent_tasks,
                num_devices=num_devices,
                # executors always take an ephemeral port (several per
                # host; a fixed one could only serve the first); a
                # negative caller value means OFF here too (-1, not
                # None — None would fall back to the env default and
                # re-enable what the caller explicitly disabled)
                metrics_port=(None if metrics_port is None
                              else 0 if metrics_port >= 0 else -1),
            )
            e = Executor(cfg)
            e.start()
            self.executors.append(e)

    @property
    def scheduler_health_port(self) -> "int | None":
        h = getattr(self.service, "health", None)
        return h.port if h is not None else None

    def shutdown(self):
        for e in self.executors:
            e.stop()
        self.service.close_health()
        self.server.stop(grace=None)
