"""Remote client: submit plan, poll status, fetch results.

(reference: rust/client/src/context.rs:161-239 BallistaDataFrame::collect —
submit -> 100ms GetJobStatus poll -> Flight-fetch every result partition.)
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

import numpy as np

from ..errors import (AdmissionRejected, ClusterError, PlanError,
                      QueryCancelled)
from ..proto import ballista_pb2 as pb
from .. import serde
from .dataplane import fetch_partition_bytes
from .scheduler import SchedulerClient

POLL_SECS = 0.1  # reference: 100ms, context.rs:183-201


def _deadline_secs(settings: Optional[Dict[str, str]]) -> float:
    """``job.deadline`` setting: server-side deadline in seconds (0 =
    none). Unlike ``job.timeout`` — which only bounds how long THIS
    client waits — the deadline rides ExecuteQueryParams and the
    scheduler's reap pass cancels the job once it expires, even when
    the submitting client is long gone."""
    raw = (settings or {}).get("job.deadline", 0)
    try:
        return max(float(raw), 0.0)
    except ValueError:
        raise ClusterError(f"invalid job.deadline setting: {raw!r} "
                           "(expected seconds as a number)") from None


def _job_id_or_shed(result: pb.ExecuteQueryResult) -> str:
    """Admission plane: a shed submission comes back with a structured
    retryable error instead of a live job id — raise it as
    :class:`AdmissionRejected` (``remote_collect`` honors the
    retry-after within the client's job timeout)."""
    if result.error:
        parsed = AdmissionRejected.parse(result.error)
        if parsed is not None:
            raise AdmissionRejected(parsed[0],
                                    result.retry_after_secs or parsed[1])
        raise ClusterError(result.error)
    return result.job_id


def submit_plan(host: str, port: int, logical_plan,
                settings: Optional[Dict[str, str]] = None) -> str:
    client = SchedulerClient(host, port)
    try:
        params = pb.ExecuteQueryParams()
        params.logical_plan.CopyFrom(serde.plan_to_proto(logical_plan))
        for k, v in (settings or {}).items():
            params.settings[k] = v
        params.deadline_secs = _deadline_secs(settings)
        return _job_id_or_shed(client.ExecuteQuery(params))
    finally:
        client.close()


def cancel_job(host: str, port: int, job_id: str,
               reason: str = "client") -> bool:
    """Cooperatively cancel a running job (CancelJob RPC). Returns True
    when this call moved the job to its terminal Cancelled state (False:
    unknown job or already terminal). Queued tasks are dropped at the
    scheduler; running tasks abort at their next batch boundary once
    their executor's poll carries the id."""
    client = SchedulerClient(host, port)
    try:
        res = client.CancelJob(
            pb.CancelJobParams(job_id=job_id, reason=reason))
        return res.cancelled
    finally:
        client.close()


def _cancel_on_timeout_enabled() -> bool:
    """``BALLISTA_CANCEL_ON_TIMEOUT`` (default on): a client-side job
    timeout issues a best-effort CancelJob before raising, so an
    abandoned client doesn't leak a running job. ``0``/``off`` restores
    the old abandon-the-job behavior."""
    return os.environ.get("BALLISTA_CANCEL_ON_TIMEOUT", "on").lower() \
        not in ("0", "off", "false", "no")


def _sql_references_table(sql: str, name: str) -> bool:
    """True when ``name`` appears in a table position (after FROM/JOIN or
    a FROM-list comma). Token-based so column aliases, string literals,
    and comments named like the table don't count."""
    from ..sql.lexer import tokenize

    try:
        toks = tokenize(sql)
    except Exception:
        return False  # unparseable here -> let the server report it
    lname = name.lower()
    prev = None
    in_from = False  # inside a FROM list, where commas introduce tables
    for t in toks:
        if t.kind == "kw":
            if t.value == "from":
                in_from = True
            elif t.value in ("where", "group", "having", "order", "limit",
                             "select", "on"):
                in_from = False
        if (t.kind == "ident" and t.value.lower() == lname and prev is not None
                and (prev.is_kw("from", "join") or
                     (in_from and prev.kind == "op" and prev.value == ","))):
            return True
        prev = t
    return False


def submit_sql(host: str, port: int, sql: str, catalog,
               settings: Optional[Dict[str, str]] = None) -> str:
    """Raw-SQL submission: the scheduler plans server-side against the
    catalog descriptors carried with the query (parity with the
    reference's sql-or-plan ExecuteQuery, rust/scheduler/src/lib.rs:
    236-247). ``catalog`` maps name -> sql.planner.CatalogTable."""
    client = SchedulerClient(host, port)
    try:
        params = pb.ExecuteQueryParams()
        params.sql = sql
        for k, v in (settings or {}).items():
            params.settings[k] = v
        for name, ct in (catalog or {}).items():
            if ct.source is None:
                # plan-backed view (register_table): views are planned
                # client-side and cannot ship as a source descriptor.
                # Fail here (actionably) if the query references it.
                if _sql_references_table(sql, name):
                    raise PlanError(
                        f"view {name!r} was registered from a DataFrame and "
                        "cannot be used with server-side SQL planning; plan "
                        "client-side (settings['plan.server']='off') or "
                        "register the underlying source instead"
                    )
                continue
            entry = params.catalog.add()
            entry.name = name
            entry.source.CopyFrom(
                serde.source_to_proto(ct.source, ct.primary_key)
            )
        params.deadline_secs = _deadline_secs(settings)
        return _job_id_or_shed(client.ExecuteQuery(params))
    finally:
        client.close()


def _emit_progress(result, job_id: str, on_progress, last: list,
                   status: str = "running") -> None:
    """Invoke the caller's progress callback from the status poll when
    the scheduler's snapshot changed. Best-effort: a raising callback
    is logged, never the query's problem."""
    if on_progress is None or not result.HasField("progress"):
        return
    from .. import serde as _serde

    snap = _serde.job_progress_from_proto(result.progress, job_id,
                                          status=status)
    from ..observability.progress import emit_if_changed, force_completed

    if status == "completed":
        # the client can observe the terminal KV before the tracker's
        # final snapshot freezes (the hook runs after the status save):
        # the terminal callback must still report exactly 1.0 — job
        # AND stage rows
        force_completed(snap)

    last[:] = [emit_if_changed(on_progress, snap,
                               last[-1] if last else None)]


def wait_for_job(host: str, port: int, job_id: str,
                 timeout: float = 300.0,
                 on_progress=None) -> pb.GetJobStatusResult:
    client = SchedulerClient(host, port)
    last: list = []
    try:
        deadline = time.time() + timeout
        while True:
            result = client.GetJobStatus(pb.GetJobStatusParams(job_id=job_id))
            which = result.status.WhichOneof("status")
            if which == "completed":
                # terminal callback: the tracker's frozen final
                # snapshot reports fraction exactly 1.0
                _emit_progress(result, job_id, on_progress, last,
                               status="completed")
                return result
            if which == "failed":
                # terminal callback carries the terminal status — a
                # progress UI must not show "running" as the job dies
                _emit_progress(result, job_id, on_progress, last,
                               status="failed")
                err = result.status.failed.error
                parsed = AdmissionRejected.parse(err)
                if parsed is not None or \
                        result.status.failed.retry_after_secs > 0:
                    # a queue-timeout shed: retryable by contract
                    reason, after = parsed or ("queue-timeout", 0.0)
                    raise AdmissionRejected(
                        reason,
                        result.status.failed.retry_after_secs or after,
                        job_id=job_id)
                raise ClusterError(
                    f"job {job_id} failed: {err}", job_id=job_id,
                )
            if which == "cancelled":
                # terminal Cancelled (client CancelJob, server deadline,
                # slow-query kill, drain): distinct from failure so
                # callers can tell "stopped on purpose" from "broke"
                _emit_progress(result, job_id, on_progress, last,
                               status="cancelled")
                raise QueryCancelled(
                    result.status.cancelled.reason or "unknown",
                    job_id=job_id,
                )
            # non-terminal: the snapshot's status mirrors the oneof
            # (queued jobs must not read "running" — ONE shape with
            # fetch_job_progress)
            _emit_progress(result, job_id, on_progress, last,
                           status="queued" if which == "queued"
                           else "running")
            if time.time() > deadline:
                if _cancel_on_timeout_enabled():
                    # best-effort: an abandoned client must not leak a
                    # running job burning executor slots; the job id on
                    # the error lets the caller inspect system.queries
                    try:
                        client.CancelJob(pb.CancelJobParams(
                            job_id=job_id, reason="timeout"))
                    except Exception:  # noqa: BLE001 - best-effort
                        pass
                raise ClusterError(
                    f"job {job_id} timed out after {timeout:.1f}s "
                    "(best-effort CancelJob issued; see system.queries)"
                    if _cancel_on_timeout_enabled() else
                    f"job {job_id} timed out after {timeout:.1f}s",
                    job_id=job_id,
                )
            time.sleep(POLL_SECS)
    finally:
        client.close()


def _job_timeout(settings: Optional[Dict[str, str]],
                 override: Optional[float]) -> float:
    """Seconds to wait for a remote job: explicit arg > ``job.timeout``
    setting > 300 (large-SF runs on few cores legitimately exceed the
    default)."""
    if override is not None:
        return override
    raw = (settings or {}).get("job.timeout", 300.0)
    try:
        return float(raw)
    except ValueError:
        raise ClusterError(f"invalid job.timeout setting: {raw!r} "
                           "(expected seconds as a number)") from None


class CancelRequested:
    """Sentinel ``BallistaContext.cancel()`` drops into an in-flight
    collect's job-id sink: a cancel that lands BETWEEN admission-retry
    attempts (the shed job is already terminal, so CancelJob had
    nothing to hit) must still stop the retry loop — resubmitting a
    query the user just cancelled breaks the cancel contract."""

    __slots__ = ("reason",)

    def __init__(self, reason: str = "client"):
        self.reason = reason


def _cancel_requested(job_id_out):
    return next((x for x in (job_id_out or [])
                 if isinstance(x, CancelRequested)), None)


def _admission_retry_enabled() -> bool:
    """``BALLISTA_ADMISSION_RETRY`` (default on): ``remote_collect``
    honors a shed's retry-after — sleep and resubmit within the
    client's job timeout. ``0``/``off`` surfaces the AdmissionRejected
    immediately (callers running their own backoff)."""
    return os.environ.get("BALLISTA_ADMISSION_RETRY", "on").lower() \
        not in ("0", "off", "false", "no")


def _collect_with_admission_retry(deadline_secs: float, submit_fn,
                                  wait_fn, job_id_out=None,
                                  cancel_fn=None):
    """One submit+wait attempt loop honoring admission retry-after:
    a shed (at the gate, or a queue-timeout mid-wait) sleeps the
    server's retry_after_secs and resubmits, as long as the NEXT
    attempt still fits inside the caller's job-timeout budget. The
    timeout stays one end-to-end bound across attempts — admission
    pressure never extends how long a caller can block.

    ``job_id_out`` is populated at SUBMIT time (and replaced on a
    resubmission): a concurrent ``ctx.cancel()`` must reach the job
    WHILE this thread waits on it, not after."""
    deadline_ts = time.time() + deadline_secs
    while True:
        mark = _cancel_requested(job_id_out)
        if mark is not None:
            raise QueryCancelled(mark.reason)
        try:
            job_id = submit_fn()
            if job_id_out is not None:
                # PRESERVE any sentinel a racing ctx.cancel() appended
                # while the submit RPC was in flight — a plain replace
                # would destroy it and the cancel would be lost
                job_id_out[:] = [x for x in job_id_out
                                 if isinstance(x, CancelRequested)] \
                    + [job_id]
            mark = _cancel_requested(job_id_out)
            if mark is not None:
                # the cancel raced the submit: the job exists but the
                # canceller's CancelJob pass never saw its id — issue
                # it here before raising
                if cancel_fn is not None:
                    try:
                        cancel_fn(job_id, mark.reason)
                    except Exception:  # noqa: BLE001 - best-effort
                        pass
                raise QueryCancelled(mark.reason, job_id=job_id)
            return job_id, wait_fn(job_id,
                                   max(deadline_ts - time.time(), 0.01))
        except AdmissionRejected as e:
            wait = min(max(e.retry_after_secs, 0.05), 30.0)
            if not _admission_retry_enabled() or \
                    time.time() + wait >= deadline_ts:
                raise
            time.sleep(wait)


def remote_collect(host: str, port: int, logical_plan,
                   settings: Optional[Dict[str, str]] = None,
                   timeout: Optional[float] = None,
                   metrics_out: Optional[list] = None,
                   job_id_out: Optional[list] = None,
                   on_progress=None):
    """Submit + poll + fetch -> pandas DataFrame. ``metrics_out``
    (when a list) receives the job's per-stage QueryMetrics, which ride
    the completed JobStatus (ctx.last_query_metrics()); ``job_id_out``
    receives the scheduler-assigned job id (the handle the distributed
    profiler's GetJobProfile / /debug/profile/<job_id> take);
    ``on_progress`` receives live progress snapshots off the status
    poll (the ONE shape — see observability/progress.py). Admission
    sheds are retried per their retry-after within the job timeout."""
    from ..execution import resolve_scalar_subqueries

    deadline = _job_timeout(settings, timeout)  # fail fast pre-submit
    logical_plan = resolve_scalar_subqueries(logical_plan)
    _job_id, result = _collect_with_admission_retry(
        deadline,
        lambda: submit_plan(host, port, logical_plan, settings),
        lambda jid, left: wait_for_job(host, port, jid, left,
                                       on_progress=on_progress),
        job_id_out=job_id_out,
        cancel_fn=lambda jid, reason: cancel_job(host, port, jid,
                                                 reason))
    _deliver_metrics(result, metrics_out)
    return _fetch_result_frames(result)


def remote_sql_collect(host: str, port: int, sql: str, catalog,
                       settings: Optional[Dict[str, str]] = None,
                       timeout: Optional[float] = None,
                       metrics_out: Optional[list] = None,
                       job_id_out: Optional[list] = None,
                       on_progress=None):
    """Raw-SQL round trip: submit SQL + catalog, poll, fetch."""
    deadline = _job_timeout(settings, timeout)  # fail fast pre-submit
    _job_id, result = _collect_with_admission_retry(
        deadline,
        lambda: submit_sql(host, port, sql, catalog, settings),
        lambda jid, left: wait_for_job(host, port, jid, left,
                                       on_progress=on_progress),
        job_id_out=job_id_out,
        cancel_fn=lambda jid, reason: cancel_job(host, port, jid,
                                                 reason))
    _deliver_metrics(result, metrics_out)
    return _fetch_result_frames(result)


def fetch_job_progress(host: str, port: int, job_id: str
                       ) -> Optional[dict]:
    """One live progress snapshot for a job (ctx.job_progress()):
    the extended GetJobStatus's progress field, or None when the
    scheduler's tracker doesn't know the job."""
    client = SchedulerClient(host, port)
    try:
        result = client.GetJobStatus(pb.GetJobStatusParams(job_id=job_id))
    finally:
        client.close()
    if not result.HasField("progress"):
        return None
    from .. import serde as _serde

    which = result.status.WhichOneof("status")
    status = {"queued": "queued", "running": "running",
              "completed": "completed", "failed": "failed",
              "cancelled": "cancelled"}.get(which, "unknown")
    snap = _serde.job_progress_from_proto(result.progress, job_id,
                                          status=status)
    if status == "completed":
        # same race as _emit_progress: the completed KV can be visible
        # before the tracker's finish() freezes (or while its TTL cache
        # holds a pre-terminal snapshot) — a completed job must never
        # read below 1.0
        from ..observability.progress import force_completed

        force_completed(snap)
    return snap


def fetch_job_profile(host: str, port: int, job_id: str,
                      client: "SchedulerClient | None" = None) -> dict:
    """Fetch the job's merged profile artifact from the scheduler
    (distributed profiler). Raises ClusterError when the scheduler
    holds no profile data for the job. Pass ``client`` to reuse one
    channel across a polling loop."""
    import json

    own = client is None
    if own:
        client = SchedulerClient(host, port)
    try:
        res = client.GetJobProfile(pb.GetJobProfileParams(job_id=job_id))
    finally:
        if own:
            client.close()
    if res.error:
        raise ClusterError(res.error)
    return json.loads(res.artifact_json.decode())


def fetch_system_table(host: str, port: int, table: str) -> list:
    """Fetch one system.* table's rows from the scheduler's snapshot
    (GetSystemTable RPC) — what a remote context's system-table scans
    read, so they see cluster state instead of the client process."""
    import json

    client = SchedulerClient(host, port)
    try:
        res = client.GetSystemTable(pb.GetSystemTableParams(table=table))
    finally:
        client.close()
    if res.error:
        raise ClusterError(res.error)
    return json.loads(res.rows_json.decode())


def _deliver_metrics(result: pb.GetJobStatusResult,
                     metrics_out: Optional[list]) -> None:
    if metrics_out is None:
        return
    sm = result.status.completed.stage_metrics
    if sm:
        from ..observability.metrics import QueryMetrics

        metrics_out.append(QueryMetrics(serde.stage_metrics_from_proto(sm)))


def _fetch_result_frames(result: pb.GetJobStatusResult):
    import pandas as pd

    from ..io import ipc
    locations = sorted(
        result.status.completed.partition_location,
        key=lambda l: l.partition_id.partition_id,
    )
    # latency ledger: the client envelope separates moving result bytes
    # (result_transfer) from turning them into host arrays/DataFrames
    # (host_decode); stamps no-op outside an active collect window
    from ..observability.ledger import ledger_phase

    frames = []
    for loc in locations:
        with ledger_phase("result_transfer"):
            if loc.path and os.path.exists(loc.path):
                raw = open(loc.path, "rb").read()
            else:
                raw = fetch_partition_bytes(
                    loc.executor_meta.host, loc.executor_meta.port,
                    loc.partition_id.job_id, loc.partition_id.stage_id,
                    loc.partition_id.partition_id,
                )
        with ledger_phase("host_decode"):
            names, arrays, nulls, dicts, kinds = \
                ipc.read_partition_arrays(raw)
            cols = {}
            for name in names:
                kind, scale = kinds.get(name, ("", 0))
                from ..columnar import decode_physical_array

                if kind.startswith("list:"):
                    from ..columnar import decode_list_rows

                    cols[name] = decode_list_rows(
                        arrays[name], kind.split(":", 1)[1], scale,
                        nulls[name]
                    )
                    continue
                cols[name] = decode_physical_array(
                    arrays[name],
                    "utf8" if name in dicts else kind,
                    scale,
                    dicts.get(name),
                    nulls[name],
                )
            frames.append(pd.DataFrame(cols))
    if not frames:
        return pd.DataFrame()
    with ledger_phase("host_decode"):
        return pd.concat(frames, ignore_index=True)
