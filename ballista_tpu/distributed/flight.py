"""Arrow Flight front-end: the any-language data plane.

The reference's cross-language story is Arrow Flight — its JDBC driver
opens a FlightClient and sends the RAW SQL BYTES as the Ticket of a
``DoGet``, then reads the schema-first FlightData stream (reference:
jvm/jdbc/.../FlightStatement.java:44-63, Driver.java:33-47; server side
flight_service.rs:80-228). Round 2 shipped only a bespoke length-prefixed
socket protocol, which no foreign client can speak; this module restores
the interop contract with a REAL Arrow Flight gRPC server (pyarrow.flight)
fronting the engine:

- Ticket = raw SQL bytes        -> plan + execute, stream the result table
  (exactly the JDBC driver's byte exchange);
- Ticket = serialized pb.Action -> FetchPartition / FetchShufflePartition
  streams a materialized partition file (Flight-spoken twin of the raw
  data plane in distributed/dataplane.py, which stays the executor<->
  executor fast path).

Results stream as standard Arrow IPC record batches, so any Flight
client (Java/C++/Go/Python) can consume them without this codebase.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

try:  # pyarrow is optional at runtime; gate cleanly when absent
    import pyarrow as pa
    import pyarrow.flight as paflight

    _PA_ERR = None
except Exception as _e:  # noqa: BLE001 - record why it's unavailable
    pa, paflight = None, None
    _PA_ERR = _e

from ..errors import IoError
from ..proto import ballista_pb2 as pb


def available() -> bool:
    return paflight is not None


def _table_from_pydict(data: dict) -> "pa.Table":
    """Engine result (numpy logical arrays) -> Arrow table. Object
    arrays (strings with None) and datetime64[D] map to utf8/date32."""
    cols = {}
    for name, arr in data.items():
        a = np.asarray(arr)
        if a.dtype == object:
            cols[name] = pa.array(a.tolist(), type=pa.string())
        elif np.issubdtype(a.dtype, np.datetime64):
            # date32 results decode as datetime64[D]; timestamp_ns as
            # datetime64[ns] — preserve sub-day precision for the latter
            if np.datetime_data(a.dtype)[0] == "D":
                cols[name] = pa.array(a)
            else:
                cols[name] = pa.array(a.astype("datetime64[ns]"))
        else:
            cols[name] = pa.array(a)
    return pa.table(cols)


class BallistaFlightServer(paflight.FlightServerBase if paflight else object):
    """Flight service over a query-execution callback + partition store.

    ``execute_sql(sql) -> dict[str, np.ndarray]`` runs a query (standalone
    context or cluster client — the server doesn't care); ``work_dir``
    enables partition-fetch tickets against materialized stage output.
    """

    def __init__(self, location: str,
                 execute_sql=None, work_dir: Optional[str] = None,
                 **kwargs):
        if paflight is None:  # pragma: no cover - env without pyarrow
            raise IoError(f"pyarrow.flight unavailable: {_PA_ERR}")
        super().__init__(location, **kwargs)
        self._execute_sql = execute_sql
        self._work_dir = work_dir

    # -- DoGet: the one RPC the reference JDBC driver uses ------------------

    def do_get(self, context, ticket):
        payload = ticket.ticket
        action = pb.Action()
        parsed = False
        try:
            action.ParseFromString(payload)
            parsed = action.WhichOneof("action_type") in (
                "fetch_partition", "fetch_shuffle", "sql",
            )
        except Exception:  # noqa: BLE001 - not a proto: raw SQL ticket
            parsed = False
        if parsed and action.WhichOneof("action_type") == "fetch_partition":
            return self._get_partition(
                action.fetch_partition.job_id,
                action.fetch_partition.stage_id,
                action.fetch_partition.partition_id, None,
            )
        if parsed and action.WhichOneof("action_type") == "fetch_shuffle":
            fs = action.fetch_shuffle
            return self._get_partition(
                fs.producer.job_id, fs.producer.stage_id,
                fs.producer.partition_id, fs.output_partition,
            )
        sql = (action.sql if parsed and
               action.WhichOneof("action_type") == "sql"
               else payload.decode("utf-8", errors="replace"))
        return self._get_sql(sql)

    def _get_sql(self, sql: str):
        if self._execute_sql is None:
            raise paflight.FlightServerError("this endpoint serves no SQL")
        data = self._execute_sql(sql)
        if hasattr(data, "columns") and hasattr(data, "to_dict"):  # pandas
            table = pa.Table.from_pandas(data, preserve_index=False)
        else:
            table = _table_from_pydict(data)
        return paflight.RecordBatchStream(table)

    def _get_partition(self, job_id: str, stage_id: int, partition_id: int,
                       shuffle_output: Optional[int]):
        if self._work_dir is None:
            raise paflight.FlightServerError(
                "this endpoint serves no partitions")
        from .dataplane import partition_path, shuffle_path

        if shuffle_output is None:
            path = partition_path(self._work_dir, job_id, stage_id,
                                  partition_id)
        else:
            path = shuffle_path(self._work_dir, job_id, stage_id,
                                partition_id, shuffle_output)
        # partitions are materialized AS Arrow IPC (io/ipc.py) — stream
        # format from the chunked writers, legacy file format from older
        # data — so they stream verbatim, dictionary encoding preserved
        from ..io import ipc as _ipc

        reader = _ipc.open_arrow_reader(path)
        return paflight.RecordBatchStream(reader.read_all())

    # -- discovery RPCs (minimal but spec-conformant) -----------------------

    def get_flight_info(self, context, descriptor):
        # SQL rides in the command descriptor; the endpoint echoes it as
        # the DoGet ticket (standard Flight submit-then-fetch shape)
        ticket = paflight.Ticket(descriptor.command or b"")
        endpoint = paflight.FlightEndpoint(ticket, [])
        return paflight.FlightInfo(
            pa.schema([]), descriptor, [endpoint], -1, -1,
        )

    def list_flights(self, context, criteria):
        return iter(())


def serve_flight(host: str = "0.0.0.0", port: int = 0,
                 execute_sql=None, work_dir: Optional[str] = None):
    """Start a Flight server on a background thread; returns
    (server, bound_port)."""
    location = f"grpc://{host}:{port}"
    server = BallistaFlightServer(location, execute_sql=execute_sql,
                                  work_dir=work_dir)
    t = threading.Thread(target=server.serve, daemon=True,
                         name="flight-server")
    t.start()
    return server, server.port
