"""Scheduler restart recovery: one explicit pass over the durable
backend before serving.

``SchedulerState._rehydrate`` (run at construction) already rebuilds
the stage-dependency bookkeeping and re-queues the pending/running
tasks of non-terminal jobs. :func:`recover` layers the control-plane
semantics on top:

1. **Trust only routable shuffle outputs** — a task recorded
   ``completed`` whose producing executor has no durable address
   record cannot serve its partitions: it is reset to pending (its
   consumers leave the ready queue) and the producer stage re-queues,
   exactly the ``recover_fetch_failure`` shape without waiting for a
   consumer to trip first.
2. **Replay lost planning** — a non-terminal job without the journal's
   ``planned`` marker crashed mid-plan: its partial stage/task rows
   are wiped and planning re-runs from the journaled submission
   (admitted jobs relaunch; queued jobs re-enter the admission queue).
3. **Restore the admission queue** — journaled queued-but-unadmitted
   submissions rebuild their :class:`Decision` (priority, deadline and
   ORIGINAL enqueue time preserved, so re-pumping keeps the
   priority/deadline order and queue timeouts keep counting from the
   first enqueue) and re-enter the queue, marked ``recovered`` for
   GetJobStatus. Server-side deadlines re-arm from the journal.
4. **Fail orphans loudly** — a non-terminal job with neither stages
   nor a journal record (journal degraded, or pre-durability rows)
   moves to terminal ``failed`` so its waiting client gets an answer
   instead of a hang; ``system.sessions``/history stay consistent
   because the terminal transition flows through the normal
   ``save_job_status`` path.

The pass is idempotent (running it on a fresh or memory-backed state
is a no-op), emits one ``controlplane.recover`` trace event with every
counter, and never raises: a partially-unreadable backend recovers
what it can and reports the rest.
"""

from __future__ import annotations

import logging
import pickle
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

log = logging.getLogger("ballista.controlplane")


@dataclass
class RecoveryReport:
    """Counters from one :func:`recover` pass (the shape
    ``bench_serving``'s restart phase and the chaos tests assert on)."""

    jobs_seen: int = 0            # non-terminal jobs found in the backend
    jobs_inflight: int = 0        # planned jobs resumed task-level
    tasks_requeued: int = 0       # ready-queue entries after the pass
    producers_reset: int = 0      # completed tasks with unroutable outputs
    queued_restored: int = 0      # admission-queue entries rebuilt
    relaunched: int = 0           # admitted jobs re-planned from journal
    orphans_failed: int = 0       # unrecoverable jobs failed loudly
    deadlines_restored: int = 0
    recovery_seconds: float = 0.0
    errors: List[str] = field(default_factory=list)

    def as_dict(self) -> dict:
        return asdict(self)

    @property
    def recovered_jobs(self) -> int:
        return self.jobs_inflight + self.queued_restored + self.relaunched


def _nonterminal_jobs(state) -> Dict[str, object]:
    """job_id -> JobStatus for every non-terminal persisted job."""
    prefix = state._k("jobs") + "/"
    out = {}
    for k, v in state.kv.get_from_prefix(prefix):
        try:
            status = pickle.loads(v)
        except Exception:  # noqa: BLE001 - torn record: skip
            continue
        if status.state in ("completed", "failed", "cancelled"):
            continue
        out[k[len(prefix):]] = status
    return out


def _reset_unroutable_outputs(state, job_id: str) -> int:
    """Reset completed tasks whose producing executor left no durable
    address record (their shuffle outputs are unreachable); pull their
    consumers from the ready queue and re-queue the producer stage."""
    reset = 0
    with state._lock:
        for sid in state.stage_ids(job_id):
            lost = [
                t for t in state.get_task_statuses(job_id, sid)
                if t.state == "completed"
                and (not t.executor_id
                     or state.executor_address(t.executor_id) is None)
            ]
            if not lost:
                continue
            for t in lost:
                state._reset_task(t.partition)
            reset += len(lost)
            consumers = {
                s for (j, s), deps in state._stage_deps.items()
                if j == job_id and sid in deps
            }
            state._ready = [
                p for p in state._ready
                if not (p.job_id == job_id and p.stage_id in consumers)
            ]
            deps = state._stage_deps.get((job_id, sid), [])
            if all(state._stage_complete(job_id, d) for d in deps):
                state._enqueue_stage(job_id, sid)
    return reset


def _wipe_partial_plan(state, job_id: str) -> None:
    """Remove a crashed planning pass's partial stage/task rows so the
    replay starts clean (and the stale ready-queue entries with them)."""
    with state._lock:
        for prefix in (state._k("stages", job_id) + "/",
                       state._k("tasks", job_id) + "/"):
            for k, _v in state.kv.get_from_prefix(prefix):
                state.kv.delete(k)
        for sid in [s for (j, s) in list(state._stage_deps)
                    if j == job_id]:
            state._stage_deps.pop((job_id, sid), None)
            state._stage_parts.pop((job_id, sid), None)
            state._stage_mesh.pop((job_id, sid), None)
            state._stage_versions.pop((job_id, sid), None)
        state._ready = [p for p in state._ready if p.job_id != job_id]


def _args_from_entry(entry: dict):
    """Rebuild the planning args tuple ExecuteQuery would have built."""
    from ... import serde
    from ...proto import ballista_pb2 as pb

    job_id = entry["job_id"]
    settings = dict(entry.get("settings") or {})
    if entry.get("plan_bytes"):
        node = pb.LogicalPlanNode()
        node.ParseFromString(entry["plan_bytes"])
        return (job_id, serde.plan_from_proto(node), settings, None, None)
    catalog = []
    for raw in entry.get("catalog") or []:
        ct = pb.CatalogTable()
        ct.ParseFromString(raw)
        catalog.append(ct)
    return (job_id, None, settings, entry.get("sql") or "", catalog)


def recover(service) -> RecoveryReport:
    """Run the full recovery pass against ``service``'s state/journal/
    admission plane. Safe on any backend; returns the counter report."""
    from ...observability.tracing import trace_event
    from ..admission import AdmissionConfig, Decision

    state = service.state
    journal = service.journal
    report = RecoveryReport()
    t0 = time.time()
    try:
        jobs = _nonterminal_jobs(state)
    except Exception as e:  # noqa: BLE001 - degrade, never refuse
        log.exception("recovery scan failed; serving without recovery")
        report.errors.append(f"scan: {e}")
        report.recovery_seconds = time.time() - t0
        return report
    report.jobs_seen = len(jobs)
    entries = {e["job_id"]: e for e in journal.submissions()}
    now = time.time()
    for job_id, _status in sorted(jobs.items()):
        entry = entries.get(job_id)
        try:
            if journal.is_planned(job_id):
                # planning completed before the crash: task-level
                # recovery (the ready queue was rehydrated; add the
                # unroutable-output check on top)
                report.producers_reset += _reset_unroutable_outputs(
                    state, job_id)
                report.jobs_inflight += 1
                state._job_started.setdefault(job_id, now)
                service.progress.register_job(job_id)
                service.admission.restore_admitted(
                    job_id, (entry or {}).get("session_id")
                    or "anonymous")
            elif entry is not None:
                _wipe_partial_plan(state, job_id)
                args = _args_from_entry(entry)
                state._job_started.setdefault(
                    job_id, entry.get("enqueued_at") or now)
                service.progress.register_job(job_id)
                if entry.get("deadline_ts"):
                    state.save_job_deadline(job_id, entry["deadline_ts"])
                    report.deadlines_restored += 1
                if entry.get("action") == "queue":
                    cfg = AdmissionConfig.from_settings(
                        entry.get("settings"))
                    d = Decision(
                        "queue", job_id,
                        entry.get("session_id") or "anonymous",
                        reason=entry.get("reason") or "recovered",
                        retry_after_secs=cfg.retry_after_secs,
                        config=cfg,
                        deadline_ts=entry.get("deadline_ts"),
                        enqueued_at=entry.get("enqueued_at") or now,
                        recovered=True,
                    )
                    service.admission.enqueue(d, args)
                    report.queued_restored += 1
                else:
                    # admitted but crashed mid-plan: replay planning
                    # (the slot it held re-occupies first)
                    service.admission.restore_admitted(
                        job_id, entry.get("session_id") or "anonymous")
                    service._launch_job(args)
                    report.relaunched += 1
            else:
                from ..types import JobStatus

                state.save_job_status(job_id, JobStatus(
                    "failed",
                    error="job lost at scheduler restart (no durable "
                          "submission record)"))
                report.orphans_failed += 1
        except Exception as e:  # noqa: BLE001 - recover what we can
            log.exception("recovery failed for job %s", job_id)
            report.errors.append(f"{job_id}: {e}")
    report.tasks_requeued = state.ready_queue_depth()
    report.recovery_seconds = round(time.time() - t0, 4)
    if report.queued_restored:
        # re-pump NOW: restored entries launch in priority/deadline
        # order without waiting for the first heartbeat
        try:
            service.admission.pump(force=True)
        except Exception:  # noqa: BLE001 - the next pump retries
            log.exception("post-recovery pump failed")
    counters = {k: v for k, v in report.as_dict().items()
                if k != "errors"}
    try:
        trace_event("controlplane.recover", **counters)
    except Exception:  # noqa: BLE001 - observability only
        pass
    if report.jobs_seen or report.errors:
        log.warning("control-plane recovery: %s", counters)
    else:
        log.info("control-plane recovery: clean state, nothing to do")
    return report
