"""Control-plane journal: restart-safe submission records.

Job statuses, stage plans and task rows already flow through the
configured :class:`KvBackend` (``SchedulerState`` persists them), so a
durable backend survives most of a restart for free. What does NOT
survive is everything the admission plane and the planning pipeline
keep in process memory:

- a queued-but-unadmitted submission's planning payload (the raw SQL +
  catalog, or the logical-plan proto bytes) lives only in the
  in-memory admission queue;
- whether an admitted job's planning pass FINISHED — a crash mid-plan
  leaves a partial stage set that would hang forever.

The journal closes both holes with two key families under the state's
namespace:

- ``cpq/{job_id}`` — one serializable record per accepted (admitted OR
  queued) submission, written at decision time in ``ExecuteQuery`` and
  deleted at the job's terminal transition. The record holds exactly
  what a restarted scheduler needs to re-run the launch:
  settings/sql/catalog bytes or plan bytes, priority, deadline,
  enqueue time and the gate's reason.
- ``cpplanned/{job_id}`` — a marker written AFTER ``enqueue_job``
  lands: its presence means the stage set is complete and task-level
  recovery applies; its absence means planning must be replayed from
  the ``cpq`` record (any partial stage/task rows are wiped first).

Failure posture: journal writes are advisory durability, not
correctness — a backend error degrades to in-memory with one loud
structured warning (``controlplane.degraded``) and queries keep
flowing. A scheduler that cannot journal serves exactly like the
pre-durability engine.
"""

from __future__ import annotations

import logging
import pickle
import time
from typing import List, Optional

log = logging.getLogger("ballista.controlplane")

QUEUE_PREFIX = "cpq"
PLANNED_PREFIX = "cpplanned"


class ControlPlaneJournal:
    """Journal of accepted submissions over the scheduler's KvBackend."""

    def __init__(self, state):
        self._state = state
        self._degraded = False

    # -- degradation ---------------------------------------------------------

    def _guard(self, op: str, fn, default=None):
        """Run one backend operation; on failure degrade loudly ONCE
        (per journal) and keep serving from memory."""
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - degrade, never refuse
            if not self._degraded:
                self._degraded = True
                log.error(
                    "control-plane journal degraded to in-memory: "
                    "backend %s failed (%s: %s) — queued submissions "
                    "will NOT survive a scheduler restart",
                    op, type(e).__name__, e)
                try:
                    from ...observability.tracing import trace_event

                    trace_event("controlplane.degraded", op=op,
                                error=str(e)[:200])
                except Exception:  # noqa: BLE001 - observability only
                    pass
            else:
                log.debug("journal %s failed (degraded)", op,
                          exc_info=True)
            return default

    @property
    def degraded(self) -> bool:
        return self._degraded

    # -- submission records --------------------------------------------------

    def record_submission(self, job_id: str, session_id: str,
                          settings: dict, sql: Optional[str] = None,
                          catalog: Optional[List[bytes]] = None,
                          plan_bytes: Optional[bytes] = None,
                          action: str = "admit", reason: str = "",
                          priority: float = 0.0,
                          deadline_ts: Optional[float] = None,
                          enqueued_at: Optional[float] = None) -> None:
        entry = {
            "job_id": job_id,
            "session_id": session_id,
            "settings": dict(settings or {}),
            "sql": sql,
            "catalog": list(catalog or []),
            "plan_bytes": plan_bytes,
            "action": action,
            "reason": reason,
            "priority": float(priority),
            "deadline_ts": deadline_ts,
            "enqueued_at": (enqueued_at if enqueued_at
                            else time.time()),
        }
        st = self._state
        self._guard("put", lambda: st.kv.put(
            st._k(QUEUE_PREFIX, job_id), pickle.dumps(entry)))

    def drop_submission(self, job_id: str) -> None:
        st = self._state
        self._guard("delete", lambda: st.kv.delete(
            st._k(QUEUE_PREFIX, job_id)))
        self._guard("delete", lambda: st.kv.delete(
            st._k(PLANNED_PREFIX, job_id)))

    def submissions(self) -> List[dict]:
        """Every journaled (non-terminal) submission, oldest first."""
        st = self._state
        rows = self._guard("scan", lambda: st.kv.get_from_prefix(
            st._k(QUEUE_PREFIX) + "/"), default=[])
        out = []
        for _k, v in rows or []:
            try:
                out.append(pickle.loads(v))
            except Exception:  # noqa: BLE001 - skip torn records
                log.warning("skipping undecodable journal record %s", _k)
        out.sort(key=lambda e: e.get("enqueued_at") or 0.0)
        return out

    # -- planned marker ------------------------------------------------------

    def mark_planned(self, job_id: str) -> None:
        """The job's full stage set + task rows are persisted and its
        ready stages are enqueued: restart recovery may trust them."""
        st = self._state
        self._guard("put", lambda: st.kv.put(
            st._k(PLANNED_PREFIX, job_id), b"1"))

    def is_planned(self, job_id: str) -> bool:
        st = self._state
        v = self._guard("get", lambda: st.kv.get(
            st._k(PLANNED_PREFIX, job_id)))
        return v is not None
