"""Demand-driven executor autoscaler.

The reference scales executors with k8s replica counts, decoupled from
the scheduler; this engine's fleet was fixed at launch. The autoscaler
closes the loop inside the scheduler: a small decision loop reads the
demand signals the engine already computes —

- **backlog**: ready-queue depth plus admission-queue depth (the PR 15
  saturation signals),
- **latency**: the live rate-based ETA plane (PR 10) — the max
  ``eta_seconds`` across in-flight jobs,
- **supply**: live executor leases + in-flight task gauges,

and lands on one of three actions per tick: **scale-up** (spawn one
executor via the installed hook), **scale-down** (drain one idle
executor after a cooldown), or hold. The fleet is bounded by
``autoscale.min_executors``/``autoscale.max_executors``; one action
per ``autoscale.cooldown_secs`` keeps the loop from flapping.

Spawn hooks: :meth:`LocalCluster.add_executor` in-process, or
:class:`SubprocessExecutorLauncher` for the real
``executor_main`` binary. Scale-down always goes through the graceful
path — in-process executors get ``Executor.stop(drain=True)``;
subprocess executors get SIGTERM (executor_main's drain signal) after
the scheduler's ``PollWorkResult.drain`` piggyback told them to stop
accepting work.

Every decision is visible: a bounded ring serves ``system.autoscaler``
rows, counters/gauges ride the scheduler's /metrics, and each action
emits a ``controlplane.autoscale`` trace event. The
``autoscaler.spawn`` fault point makes spawn failures a first-class
chaos surface (transient by contract: a failed spawn skips the tick
and the next one retries).

Knobs (settings > env ``BALLISTA_AUTOSCALE_*`` > default, the
admission.* resolution order): see :class:`AutoscalerConfig`.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ...errors import FaultInjected
from ...testing.faults import fault_point

log = logging.getLogger("ballista.autoscaler")


@dataclass(frozen=True)
class AutoscalerConfig:
    """The ``autoscale.*`` knob section. Disabled by default: an
    unconfigured cluster keeps its launch-time fleet exactly."""

    enabled: bool = False
    # fleet bounds (min is also the idle floor scale-down respects)
    min_executors: int = 1
    max_executors: int = 4
    # scale up when backlog (ready + admission queue) reaches this
    backlog_tasks: int = 8
    # ... or when any live job's rate-based ETA exceeds this (0 = off)
    eta_secs: float = 0.0
    # at most one scaling action per cooldown window
    cooldown_secs: float = 5.0
    # drain an executor only after the cluster has been idle this long
    idle_secs: float = 30.0
    # decision loop cadence
    interval_secs: float = 1.0

    @staticmethod
    def from_settings(settings: Optional[Dict[str, str]] = None,
                      env: Optional[Dict[str, str]] = None
                      ) -> "AutoscalerConfig":
        s = settings or {}
        env = os.environ if env is None else env

        def raw(key: str):
            if key in s:
                return s[key]
            return env.get("BALLISTA_" + key.upper().replace(".", "_"))

        def number(key: str, default: float, cast=float):
            v = raw(key)
            if v is None:
                return default
            try:
                n = cast(str(v).strip())
            except ValueError:
                raise ValueError(
                    f"config key {key!r}: expected a number, got {v!r}"
                ) from None
            if n < 0:
                raise ValueError(f"config key {key!r}: must be >= 0")
            return n

        def boolean(key: str, default: bool) -> bool:
            v = raw(key)
            if v is None:
                return default
            from ...adaptive.config import _as_bool

            return _as_bool(v, key, default)

        cfg = AutoscalerConfig(
            enabled=boolean("autoscale.enabled", False),
            min_executors=number("autoscale.min_executors", 1, int),
            max_executors=number("autoscale.max_executors", 4, int),
            backlog_tasks=number("autoscale.backlog_tasks", 8, int),
            eta_secs=number("autoscale.eta_secs", 0.0),
            cooldown_secs=number("autoscale.cooldown_secs", 5.0),
            idle_secs=number("autoscale.idle_secs", 30.0),
            interval_secs=number("autoscale.interval_secs", 1.0),
        )
        if cfg.max_executors and cfg.min_executors > cfg.max_executors:
            raise ValueError(
                "autoscale.min_executors exceeds autoscale.max_executors"
            )
        return cfg


class Autoscaler:
    """The decision loop. ``signal_fn`` returns the demand snapshot
    (``backlog``, ``inflight``, ``executors``, ``eta_seconds``);
    ``spawn_fn()`` adds one executor, ``drain_fn()`` drains one idle
    executor and returns an identifier (or None when nothing is
    drainable). Both hooks run OUTSIDE the decision lock."""

    DECISION_RING = 256

    def __init__(self, config: AutoscalerConfig,
                 signal_fn: Callable[[], dict],
                 spawn_fn: Callable[[], object],
                 drain_fn: Callable[[], Optional[str]]):
        self.config = config
        self.signal_fn = signal_fn
        self.spawn_fn = spawn_fn
        self.drain_fn = drain_fn
        self._lock = threading.Lock()
        self._decisions: deque = deque(maxlen=self.DECISION_RING)
        self._last_action = 0.0
        self._idle_since: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.scale_ups_total = 0
        self.scale_downs_total = 0
        self.target = config.min_executors

    # -- loop ----------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("autoscaler tick failed")
            self._stop.wait(self.config.interval_secs)

    # -- one decision --------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """Evaluate the signals once; returns the action taken
        ("scale-up" | "scale-down") or None for a hold. Exposed for
        tests — the loop is just tick() on a timer."""
        cfg = self.config
        now = time.time() if now is None else now
        sig = self.signal_fn() or {}
        backlog = int(sig.get("backlog") or 0)
        inflight = int(sig.get("inflight") or 0)
        n = int(sig.get("executors") or 0)
        eta = float(sig.get("eta_seconds") or 0.0)
        busy = backlog > 0 or inflight > 0
        with self._lock:
            if busy:
                self._idle_since = None
            elif self._idle_since is None:
                self._idle_since = now
            idle_for = (now - self._idle_since
                        if self._idle_since is not None else 0.0)
            cooled = now - self._last_action >= cfg.cooldown_secs
        action = reason = None
        if n < cfg.min_executors:
            action, reason = "scale-up", "min-floor"
        elif cooled and n < cfg.max_executors and (
                backlog >= cfg.backlog_tasks
                or (cfg.eta_secs and eta >= cfg.eta_secs)):
            action = "scale-up"
            reason = ("backlog" if backlog >= cfg.backlog_tasks
                      else "eta")
        elif cooled and not busy and n > cfg.min_executors and \
                idle_for >= cfg.idle_secs:
            action, reason = "scale-down", "idle"
        if action is None:
            return None
        return self._act(action, reason, now,
                         backlog=backlog, inflight=inflight,
                         executors=n, eta=eta)

    def _act(self, action: str, reason: str, now: float, *,
             backlog: int, inflight: int, executors: int,
             eta: float) -> Optional[str]:
        drained = None
        try:
            if action == "scale-up":
                # chaos surface: a triggered fail skips this tick; the
                # demand signal persists so the next tick retries
                fault_point("autoscaler.spawn", executors=executors)
                self.spawn_fn()
            else:
                drained = self.drain_fn()
                if drained is None:
                    return None  # nothing idle enough to drain
        except FaultInjected as e:
            log.warning("autoscaler spawn fault injected; retrying "
                        "next tick: %s", e)
            return None
        except Exception:  # noqa: BLE001 - hook failure: hold
            log.exception("autoscaler %s hook failed", action)
            return None
        with self._lock:
            self._last_action = now
            if action == "scale-up":
                self.scale_ups_total += 1
                self.target = min(executors + 1,
                                  self.config.max_executors or
                                  executors + 1)
            else:
                self.scale_downs_total += 1
                self.target = max(executors - 1,
                                  self.config.min_executors)
            self._decisions.append({
                "decided_at": now,
                "action": action,
                "reason": reason,
                "executors": executors,
                "target": self.target,
                "backlog": backlog,
                "inflight_tasks": inflight,
                "eta_seconds": round(eta, 3) if eta else None,
                "drained": drained,
            })
        log.warning("autoscaler %s (%s): executors %d -> target %d "
                    "(backlog=%d inflight=%d eta=%.1fs)", action,
                    reason, executors, self.target, backlog, inflight,
                    eta)
        try:
            from ...observability.tracing import trace_event

            trace_event("controlplane.autoscale", action=action,
                        reason=reason, executors=executors,
                        target=self.target, backlog=backlog)
        except Exception:  # noqa: BLE001 - observability only
            pass
        return action

    def decision_rows(self) -> List[dict]:
        """``system.autoscaler``: recent decisions, oldest first."""
        with self._lock:
            return [dict(r) for r in self._decisions]


class SubprocessExecutorLauncher:
    """Spawn/drain hooks over the real executor binary
    (``python -m ballista_tpu.distributed.executor_main``). Spawned
    processes inherit the environment plus any overrides; drain sends
    SIGTERM — executor_main's graceful-drain signal — to the youngest
    live child (LIFO keeps the launch-time fleet stable)."""

    def __init__(self, scheduler_host: str, scheduler_port: int,
                 extra_args: Optional[List[str]] = None,
                 env: Optional[Dict[str, str]] = None):
        self.scheduler_host = scheduler_host
        self.scheduler_port = scheduler_port
        self.extra_args = list(extra_args or [])
        self.env = env
        self._procs: List[subprocess.Popen] = []
        self._lock = threading.Lock()

    def spawn(self) -> subprocess.Popen:
        argv = [
            sys.executable, "-m",
            "ballista_tpu.distributed.executor_main",
            "--scheduler-host", self.scheduler_host,
            "--scheduler-port", str(self.scheduler_port),
        ] + self.extra_args
        proc = subprocess.Popen(argv, env=self.env)
        with self._lock:
            self._procs.append(proc)
        log.info("spawned executor subprocess pid=%d", proc.pid)
        return proc

    def drain(self) -> Optional[str]:
        import signal as _signal

        with self._lock:
            self._reap_locked()
            if not self._procs:
                return None
            proc = self._procs.pop()
        proc.send_signal(_signal.SIGTERM)
        log.info("draining executor subprocess pid=%d (SIGTERM)",
                 proc.pid)
        return str(proc.pid)

    def _reap_locked(self) -> None:
        self._procs = [p for p in self._procs if p.poll() is None]

    def alive(self) -> int:
        with self._lock:
            self._reap_locked()
            return len(self._procs)

    def stop_all(self, timeout: float = 10.0) -> None:
        with self._lock:
            procs, self._procs = self._procs, []
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + timeout
        for p in procs:
            try:
                p.wait(timeout=max(deadline - time.time(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
