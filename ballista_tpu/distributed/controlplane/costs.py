"""Cost feedback: observed stage costs steer the NEXT initial plan.

Flare's lesson (PAPERS.md) is that work a serving system repeats must
amortize to ~0. Adaptive re-planning already fixes partition counts
and join strategy MID-flight from observed stage metrics — but every
fresh submission of the same query shape starts from the same static
defaults and pays the same first-stage mistake again. This store
closes the loop: at each job's terminal transition the scheduler folds
the observed per-stage costs (``StageMetrics``) into one durable
record keyed by the plan's stable digest (the same
``compile_signature``-style identity the profiler stamps on slow-query
summaries), and the planner consults it BEFORE ``plan_logical``:

- **shuffle partition counts** — ``join.partitions`` (and a
  configured ``agg.partitions``) are sized so each shuffled partition
  carries about ``controlplane.cost_target_partition_bytes`` of the
  query's OBSERVED shuffle volume, instead of the static default 8;
- **broadcast-vs-shuffle join choice** — a query whose observed
  shuffle volume is tiny relative to the target raises
  ``join.partition_threshold`` (prefer the merged-build/broadcast
  form); one whose volume dwarfs it lowers the threshold (prefer
  co-partitioned buckets).

Explicit client settings ALWAYS win — advice only fills knobs the
submission left at their defaults — and AQE still corrects mid-flight,
so a stale record degrades performance, never correctness. Decisions
annotate EXPLAIN (a ``cost_feedback`` row) and trace as
``controlplane.costs``.

Records live under ``costs/{digest}`` in the scheduler's KvBackend
(EWMA over runs, so drift follows the data); the same degrade-loudly
posture as the journal applies.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
import pickle
import time
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("ballista.controlplane")

COST_PREFIX = "costs"
# EWMA weight of the newest run
ALPHA = 0.5
# partition-count advice stays inside sane bounds
MIN_PARTITIONS = 1
MAX_PARTITIONS = 64
# threshold nudges: multiply/divide by this factor
THRESHOLD_STEP = 4

DEFAULT_TARGET_PARTITION_BYTES = 64 * 1024 * 1024


def _setting(settings: Optional[Dict[str, str]], key: str):
    """settings > env BALLISTA_CONTROLPLANE_* > None (same resolution
    order as the admission.* family)."""
    s = settings or {}
    if key in s:
        return s[key]
    return os.environ.get("BALLISTA_" + key.upper().replace(".", "_"))


def cost_feedback_enabled(settings: Optional[Dict[str, str]] = None) -> bool:
    raw = _setting(settings, "controlplane.cost_feedback")
    if raw is None:
        return True
    from ...adaptive.config import _as_bool

    return _as_bool(raw, "controlplane.cost_feedback", True)


def target_partition_bytes(settings: Optional[Dict[str, str]] = None) -> int:
    raw = _setting(settings, "controlplane.cost_target_partition_bytes")
    if raw is None:
        return DEFAULT_TARGET_PARTITION_BYTES
    try:
        n = int(str(raw).strip())
    except ValueError:
        raise ValueError(
            "config key 'controlplane.cost_target_partition_bytes': "
            f"expected an integer, got {raw!r}") from None
    return max(n, 1)


def _stage_costs(stage_metrics: dict) -> Tuple[float, int]:
    """(task_seconds, shuffle_bytes) observed for one completed job.
    ``shuffle_bytes`` counts what NON-FINAL stages materialized into
    the data plane (ShuffleWrite/PartitionWrite bytes_written — the
    same metering unit system.sessions uses)."""
    task_seconds = 0.0
    shuffle_bytes = 0
    final_sid = max(stage_metrics) if stage_metrics else None
    for sid, st in stage_metrics.items():
        task_seconds += float(st.get("elapsed_total", 0.0))
        if sid == final_sid:
            continue
        for op in st.get("operators") or []:
            if op.get("operator") in ("ShuffleWrite", "PartitionWrite"):
                shuffle_bytes += int(
                    (op.get("metrics") or {}).get("bytes_written", 0))
    return task_seconds, shuffle_bytes


class CostFeedbackStore:
    """Per-plan-digest observed costs over the scheduler's KvBackend."""

    def __init__(self, state):
        self._state = state
        self._degraded = False

    def _guard(self, op: str, fn, default=None):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - degrade, never refuse
            if not self._degraded:
                self._degraded = True
                log.error("cost-feedback store degraded to no-op: "
                          "backend %s failed (%s: %s)", op,
                          type(e).__name__, e)
            return default

    # -- observe -------------------------------------------------------------

    def observe(self, digest: str, stage_metrics: dict,
                wall_seconds: float = 0.0) -> Optional[dict]:
        """Fold one completed job's stage costs into the digest's
        record (EWMA). Advisory: never raises."""
        if not digest or not stage_metrics:
            return None
        task_seconds, shuffle_bytes = _stage_costs(stage_metrics)
        st = self._state
        key = st._k(COST_PREFIX, digest)
        prev = None
        raw = self._guard("get", lambda: st.kv.get(key))
        if raw is not None:
            try:
                prev = pickle.loads(raw)
            except Exception:  # noqa: BLE001 - torn record: restart
                prev = None

        def ewma(old, new):
            return new if old is None else \
                (1.0 - ALPHA) * float(old) + ALPHA * float(new)

        rec = {
            "digest": digest,
            "runs": int((prev or {}).get("runs", 0)) + 1,
            "wall_seconds": ewma((prev or {}).get("wall_seconds"),
                                 wall_seconds),
            "task_seconds": ewma((prev or {}).get("task_seconds"),
                                 task_seconds),
            "shuffle_bytes": ewma((prev or {}).get("shuffle_bytes"),
                                  shuffle_bytes),
            "num_stages": len(stage_metrics),
            "updated_at": time.time(),
        }
        self._guard("put", lambda: st.kv.put(key, pickle.dumps(rec)))
        return rec

    def lookup(self, digest: str) -> Optional[dict]:
        if not digest:
            return None
        st = self._state
        raw = self._guard("get", lambda: st.kv.get(
            st._k(COST_PREFIX, digest)))
        if raw is None:
            return None
        try:
            return pickle.loads(raw)
        except Exception:  # noqa: BLE001 - torn record
            return None

    # -- advise --------------------------------------------------------------

    def advise(self, digest: Optional[str], opts,
               settings: Optional[Dict[str, str]] = None):
        """Return ``(opts, notes)``: a PlannerOptions copy with
        history-informed defaults filled in, plus human-readable notes
        (EXPLAIN's ``cost_feedback`` row + trace events). Explicitly
        configured knobs are never overridden; no history or disabled
        feedback returns ``opts`` unchanged."""
        notes: List[str] = []
        if digest is None or not cost_feedback_enabled(settings):
            return opts, notes
        rec = self.lookup(digest)
        if rec is None:
            return opts, notes
        s = settings or {}
        target = target_partition_bytes(settings)
        shuffle_bytes = float(rec.get("shuffle_bytes") or 0.0)
        changes = {}
        if shuffle_bytes > 0 and "join.partitions" not in s:
            n = min(max(math.ceil(shuffle_bytes / target),
                        MIN_PARTITIONS), MAX_PARTITIONS)
            if n != opts.join_partitions:
                changes["join_partitions"] = n
                notes.append(
                    f"join.partitions {opts.join_partitions} -> {n} "
                    f"(observed ~{int(shuffle_bytes)}B shuffled over "
                    f"{rec['runs']} run(s), target {target}B/partition)")
        if shuffle_bytes > 0 and opts.agg_partitions and \
                "agg.partitions" not in s:
            n = min(max(math.ceil(shuffle_bytes / target),
                        MIN_PARTITIONS), MAX_PARTITIONS)
            if n != opts.agg_partitions:
                changes["agg_partitions"] = n
                notes.append(
                    f"agg.partitions {opts.agg_partitions} -> {n}")
        thr = opts.join_partition_threshold
        if thr is not None and "join.partitioned.threshold" not in s:
            if shuffle_bytes and shuffle_bytes < target:
                changes["join_partition_threshold"] = thr * THRESHOLD_STEP
                notes.append(
                    f"join threshold {thr} -> {thr * THRESHOLD_STEP}: "
                    "observed shuffle volume is small — prefer the "
                    "merged-build (broadcast) join")
            elif shuffle_bytes > 8 * target:
                lowered = max(thr // THRESHOLD_STEP, 1)
                changes["join_partition_threshold"] = lowered
                notes.append(
                    f"join threshold {thr} -> {lowered}: observed "
                    "shuffle volume is large — prefer the "
                    "co-partitioned (shuffled) join")
        if not changes:
            return opts, notes
        # EXPLAIN annotation rides the options into the planner: the
        # Explain branch renders a cost_feedback row from these notes
        changes["cost_notes"] = tuple(notes)
        opts = dataclasses.replace(opts, **changes)
        try:
            from ...observability.tracing import trace_event

            trace_event("controlplane.costs", digest=digest[:16],
                        runs=rec.get("runs"), notes="; ".join(notes))
        except Exception:  # noqa: BLE001 - observability only
            pass
        return opts, notes
