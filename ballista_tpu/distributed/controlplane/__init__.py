"""Durable elastic control plane (ROADMAP item 4).

The reference Ballista's HA story is a sled/etcd ``ConfigBackendClient``
holding ALL scheduler state so a standby can take over (reference:
rust/scheduler/src/state/etcd.rs), and its k8s deployment scales
executors independently of the scheduler. This package closes the same
gap for this engine with three coordinated legs:

- :mod:`.journal` — every control-plane transition that only lived in
  one process's memory (admission-queue entries, the planned marker)
  is journaled through the configured :class:`KvBackend`, so a
  scheduler restart against the same sqlite file / etcd namespace
  loses NOTHING a client is still waiting on.
- :mod:`.recovery` — one explicit :func:`recover` pass a restarted
  scheduler runs before serving: re-pumps queued-but-unadmitted
  submissions in priority/deadline order, re-queues live tasks of
  in-flight jobs whose producers' shuffle outputs are still routable,
  fails orphans loudly, and emits a ``controlplane.recover`` trace
  event with counters.
- :mod:`.autoscaler` — a demand-driven loop over queue depth, the
  rate-based ETA plane and the admission saturation signals that
  spawns executors (LocalCluster hook or a subprocess launcher for
  the real binary) and drains idle ones, bounded by
  ``autoscale.min/max_executors``; every decision lands in
  ``system.autoscaler`` and Prometheus gauges.
- :mod:`.costs` — observed per-stage costs keyed by plan digest feed
  the NEXT submission's initial plan (shuffle partition counts,
  broadcast-vs-shuffle join choice); AQE still corrects mid-flight.

Failure posture (shared by every leg): a backend that errors degrades
the control plane to in-memory with ONE loud structured warning —
queries are never refused because durability is unavailable.
"""

from .autoscaler import (Autoscaler, AutoscalerConfig,
                         SubprocessExecutorLauncher)
from .costs import CostFeedbackStore
from .journal import ControlPlaneJournal
from .recovery import RecoveryReport, recover

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "ControlPlaneJournal",
    "CostFeedbackStore",
    "RecoveryReport",
    "SubprocessExecutorLauncher",
    "recover",
]
