"""Distributed runtime: scheduler (control plane), executor (data plane),
cluster state, and shuffle.

Architecture mirrors the reference cluster design (reference:
docs/architecture.md:5-46): one or more schedulers turn submitted plans
into stage DAGs whose partition-tasks are pulled by executors over gRPC;
stage outputs are materialized and fetched between executors through a
data-plane socket protocol, with an ICI ``all_to_all`` fast path when
producer and consumer share a TPU mesh (ballista_tpu.parallel).
"""
