"""Executor mesh groups: one fused task, many processes, one mesh.

A mesh group is a set of executor processes (typically one per TPU
host) that joined a shared ``jax.distributed`` runtime
(parallel/multihost.py) and therefore see ONE global device mesh.
The group acts as a single logical executor:

- the LEADER (rank 0) polls the scheduler normally and reports the
  GLOBAL device count, so mesh fusion plans against the whole group;
- when the leader receives a mesh-fused task it broadcasts the task
  bytes to the followers over the group channel, then every process
  enters the same SPMD program together — ``lax.all_to_all`` crosses
  host boundaries inside the accelerator fabric (ICI in-slice, DCN
  across hosts), which is the NCCL/MPI-analogue scale-out the SURVEY
  calls for (§5.8) instead of moving partitions through the host data
  plane;
- outputs are all_gather-replicated (physical/mesh_agg.py
  ``_host_visible``), so the leader alone materializes and reports.

v1 limitations (documented, tested): group tasks run one at a time
(collectives must align across processes); a follower crash mid-task
can strand the leader inside a collective — the scheduler's task lease
reaping then re-queues the work, but the group itself must be
restarted.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import List, Optional

log = logging.getLogger("ballista.mesh_group")

_ACK_OK = 0
_ACK_FAILED = 1

# Longest a single group task may run on a follower before the leader
# gives up waiting for its ack. A timeout here is a GROUP failure (the
# SPMD streams desynchronize), so it is deliberately generous; override
# via BALLISTA_MESH_GROUP_ACK_TIMEOUT for larger-than-usual workloads.
import os as _os

ACK_TIMEOUT_SECS = float(
    _os.environ.get("BALLISTA_MESH_GROUP_ACK_TIMEOUT", 3600)
)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("mesh group channel closed")
        buf.extend(chunk)
    return bytes(buf)


class GroupLeader:
    """Rank-0 side of the group channel.

    Every broadcast carries a monotonically increasing sequence number
    that followers ECHO with their ack; ``wait_acks`` discards acks from
    older broadcasts, so a leader-side task failure (which skips waiting)
    can never desynchronize completion status onto the next task.
    """

    def __init__(self, bind_host: str, port: int, num_followers: int,
                 accept_timeout: float = 60.0):
        self.num_followers = num_followers
        self.lock = threading.Lock()  # one group task at a time
        self._srv = socket.create_server((bind_host, port))
        self.port = self._srv.getsockname()[1]
        self._conns: List[socket.socket] = []
        self._accept_timeout = accept_timeout
        self._seq = 0

    def wait_members(self) -> None:
        self._srv.settimeout(self._accept_timeout)
        while len(self._conns) < self.num_followers:
            conn, addr = self._srv.accept()
            # ack wait bound = the longest a group task may run on a
            # follower; generous because exceeding it desynchronizes the
            # group's SPMD streams (leader re-broadcasts while the
            # follower is still inside the old task's collectives)
            conn.settimeout(ACK_TIMEOUT_SECS)
            self._conns.append(conn)
            log.info("mesh group follower joined from %s (%d/%d)", addr,
                     len(self._conns), self.num_followers)

    def broadcast(self, payload: bytes) -> int:
        self._seq += 1
        for c in self._conns:
            c.sendall(struct.pack(">QI", self._seq, len(payload)) + payload)
        return self._seq

    def wait_acks(self, seq: Optional[int] = None) -> None:
        seq = self._seq if seq is None else seq
        errors = []
        for i, c in enumerate(self._conns):
            while True:
                (ack_seq,) = struct.unpack(">Q", _recv_exact(c, 8))
                status = _recv_exact(c, 1)[0]
                msg = b""
                if status != _ACK_OK:
                    (n,) = struct.unpack(">I", _recv_exact(c, 4))
                    msg = _recv_exact(c, n)
                if ack_seq < seq:
                    continue  # stale ack from a task the leader abandoned
                break
            if status != _ACK_OK:
                errors.append(
                    f"follower {i}: {msg.decode(errors='replace')}")
        if errors:
            raise RuntimeError("; ".join(errors))

    def close(self) -> None:
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        self._srv.close()


def run_follower(leader_host: str, leader_port: int,
                 connect_timeout: float = 60.0) -> None:
    """Follower loop: receive fused tasks from the leader and enter
    their SPMD programs in lockstep; never talks to the scheduler.
    Returns when the leader closes the channel."""
    from ..proto import ballista_pb2 as pb
    from .. import serde

    # retry with backoff: jax.distributed.initialize is a BARRIER, so
    # the leader only binds the channel after every member's init —
    # a follower leaving the barrier first would lose the race
    import time as _time

    deadline = _time.time() + connect_timeout
    while True:
        try:
            sock = socket.create_connection((leader_host, leader_port),
                                            timeout=5.0)
            break
        except OSError:
            if _time.time() >= deadline:
                raise
            _time.sleep(0.2)
    sock.settimeout(None)  # tasks arrive whenever the leader has one
    log.info("mesh group follower connected to %s:%d", leader_host,
             leader_port)
    while True:
        try:
            seq, n = struct.unpack(">QI", _recv_exact(sock, 12))
        except ConnectionError:
            log.info("mesh group channel closed; follower exiting")
            return
        td = pb.TaskDefinition()
        td.ParseFromString(_recv_exact(sock, n))
        try:
            plan = serde.physical_from_proto(td.plan)
            nparts = plan.output_partitioning().num_partitions
            for p in range(nparts):
                for _ in plan.execute(p):
                    pass  # outputs are replicated; the leader materializes
            sock.sendall(struct.pack(">Q", seq) + bytes([_ACK_OK]))
        except Exception as e:  # noqa: BLE001 - report to the leader
            log.exception("follower task failed")
            msg = f"{type(e).__name__}: {e}".encode()
            sock.sendall(struct.pack(">Q", seq) + bytes([_ACK_FAILED])
                         + struct.pack(">I", len(msg)) + msg)
