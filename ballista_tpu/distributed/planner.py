"""Distributed planner: physical plan -> stage DAG.

Re-implements the reference's ``DistributedPlanner`` splitting rules
(reference: rust/scheduler/src/planner.rs:96-198):

- a ``MergeExec`` boundary turns its child into a new query stage and
  replaces it with an ``UnresolvedShuffleExec``;
- a final-mode ``HashAggregateExec``'s child (the partial side) becomes a
  stage;
- an output-partitioning change (RepartitionExec) becomes a stage whose
  output is hash-partitioned at materialization time;
- join children pass through (the build side's MergeExec already forms a
  stage).

Stage ids start at 1 (reference: planner.rs:201-204); the root plan becomes
the final stage.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..errors import PlanError
from ..physical.aggregate import HashAggregateExec
from ..physical.base import PhysicalPlan
from ..physical.join import JoinExec
from ..physical.operators import MergeExec, RepartitionExec
from ..physical.shuffle import (
    QueryStageExec,
    ShuffleReaderExec,
    UnresolvedShuffleExec,
)
from .types import PartitionLocation


class DistributedPlanner:
    def __init__(self):
        self._next_stage_id = 0

    def _new_stage_id(self) -> int:
        self._next_stage_id += 1
        return self._next_stage_id

    def plan_query_stages(
        self, job_id: str, plan: PhysicalPlan
    ) -> List[QueryStageExec]:
        """Returns all stages; the last one is the final (root) stage."""
        new_plan, stages = self._plan_internal(job_id, plan)
        stages.append(QueryStageExec(job_id, self._new_stage_id(), new_plan))
        return stages

    def _plan_internal(
        self, job_id: str, plan: PhysicalPlan
    ) -> Tuple[PhysicalPlan, List[QueryStageExec]]:
        stages: List[QueryStageExec] = []
        children = plan.children()
        if not children:
            return plan, stages

        new_children: List[PhysicalPlan] = []
        for child in children:
            c_plan, c_stages = self._plan_internal(job_id, child)
            stages.extend(c_stages)
            new_children.append(c_plan)

        if isinstance(plan, RepartitionExec):
            # hash-partitioned shuffle: the producing stage's tasks (one per
            # child partition) write one shuffle-q file per consumer
            # partition; the consumer reads the q-files of every producer
            child = new_children[0]
            stage = QueryStageExec(
                job_id, self._new_stage_id(), child,
                shuffle_hash_exprs=plan.hash_exprs,
                shuffle_output_partitions=plan.num_partitions,
            )
            stages.append(stage)
            return (
                UnresolvedShuffleExec(
                    [stage.stage_id], child.output_schema(),
                    plan.num_partitions,
                ),
                stages,
            )

        if isinstance(plan, MergeExec):
            # child becomes a stage; this node reads its shuffled output
            child = new_children[0]
            stage = QueryStageExec(job_id, self._new_stage_id(), child)
            stages.append(stage)
            unresolved = UnresolvedShuffleExec(
                [stage.stage_id],
                child.output_schema(),
                child.output_partitioning().num_partitions,
            )
            return plan.with_new_children([unresolved]), stages

        if isinstance(plan, HashAggregateExec) and plan.mode == "final":
            child = new_children[0]
            if not isinstance(child, UnresolvedShuffleExec):
                stage = QueryStageExec(job_id, self._new_stage_id(), child)
                stages.append(stage)
                child = UnresolvedShuffleExec(
                    [stage.stage_id],
                    stage.output_schema(),
                    stage.output_partitioning().num_partitions,
                )
            return plan.with_new_children([child]), stages

        return plan.with_new_children(new_children), stages


def find_unresolved_shuffles(plan: PhysicalPlan) -> List[UnresolvedShuffleExec]:
    """(reference: state/mod.rs:372-385)"""
    out = []
    if isinstance(plan, UnresolvedShuffleExec):
        out.append(plan)
    for c in plan.children():
        out.extend(find_unresolved_shuffles(c))
    return out


def remove_unresolved_shuffles(
    plan: PhysicalPlan,
    locations: Dict[int, List[PartitionLocation]],  # stage_id -> locations
    reader_info: "Dict[int, dict] | None" = None,
) -> PhysicalPlan:
    """Substitute resolved ShuffleReaderExecs (reference:
    planner.rs:236-269).

    ``reader_info`` (stage_id -> {"read_partitions", "hash_columns",
    "original_partitions"}) carries the adaptive reader layout and the
    producing stage's hash-partitioning columns into the reader, so the
    in-task plan both respects AQE decisions and reports trustworthy
    co-partitioning (the ``Partitioning("unknown", n)`` fix)."""
    if isinstance(plan, UnresolvedShuffleExec):
        locs: List[PartitionLocation] = []
        for sid in plan.query_stage_ids:
            if sid not in locations:
                raise PlanError(f"no locations for stage {sid}")
            locs.extend(
                sorted(locations[sid], key=lambda l: l.partition_id)
            )
        info = {}
        if reader_info and len(plan.query_stage_ids) == 1:
            info = reader_info.get(plan.query_stage_ids[0]) or {}
        return ShuffleReaderExec(
            locs, plan.output_schema(),
            read_partitions=info.get("read_partitions"),
            hash_columns=tuple(info.get("hash_columns") or ()),
            original_partitions=info.get("original_partitions", 0),
        )
    children = plan.children()
    if not children:
        return plan
    return plan.with_new_children(
        [remove_unresolved_shuffles(c, locations, reader_info)
         for c in children]
    )
