"""Cluster state: KV backend abstraction + scheduler state machine.

Re-implements the reference's scheduler state layer (reference:
rust/scheduler/src/state/mod.rs — ``ConfigBackendClient`` KV trait at
:46-59, key scheme /ballista/{ns}/... at :387-434, task assignment at
:182-260, job-status synthesis at :267-358) with two backends:

- ``MemoryBackend``: in-process dict (the reference's sled standalone);
- ``SqliteBackend``: durable file-backed store (survives scheduler restart,
  the role etcd/sled-on-disk plays for the reference).

Improvement over the reference (its own TODO at state/mod.rs:263 "We should
get rid of this to be able to scale"): task assignment keeps an explicit
ready-queue of schedulable tasks instead of rescanning every task row under
a global lock — stage-dependency checks run only when a stage completes.
"""

from __future__ import annotations

import logging
import os
import pickle
import sqlite3
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..errors import ClusterError
from ..testing.faults import fault_point
from .types import (
    ExecutorMeta,
    JobStatus,
    PartitionId,
    PartitionLocation,
    StagePlan,
    TaskStatus,
)

log = logging.getLogger("ballista.state")

EXECUTOR_LEASE_SECS = 60  # reference: LEASE_TIME, state/mod.rs:42


# ---------------------------------------------------------------------------
# KV backends
# ---------------------------------------------------------------------------


class KvBackend:
    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def get_from_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        raise NotImplementedError

    def put(self, key: str, value: bytes, lease_secs: Optional[int] = None):
        raise NotImplementedError

    def delete(self, key: str):
        raise NotImplementedError

    def lock(self):
        raise NotImplementedError


class MemoryBackend(KvBackend):
    def __init__(self):
        self._data: Dict[str, Tuple[bytes, Optional[float]]] = {}
        self._lock = threading.RLock()

    def _expired(self, expiry: Optional[float]) -> bool:
        return expiry is not None and time.time() > expiry

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            v = self._data.get(key)
            if v is None or self._expired(v[1]):
                return None
            return v[0]

    def get_from_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        with self._lock:
            return [
                (k, v)
                for k, (v, exp) in sorted(self._data.items())
                if k.startswith(prefix) and not self._expired(exp)
            ]

    def put(self, key: str, value: bytes, lease_secs: Optional[int] = None):
        with self._lock:
            expiry = time.time() + lease_secs if lease_secs else None
            self._data[key] = (value, expiry)

    def delete(self, key: str):
        with self._lock:
            self._data.pop(key, None)

    def lock(self):
        return self._lock


class SqliteBackend(KvBackend):
    """Durable KV over sqlite (WAL). One connection per thread."""

    def __init__(self, path: str):
        self._path = path
        self._tls = threading.local()
        self._lock = threading.RLock()
        with self._conn() as c:
            c.execute(
                "CREATE TABLE IF NOT EXISTS kv ("
                "key TEXT PRIMARY KEY, value BLOB, expiry REAL)"
            )

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self._path, timeout=30)
            # crash atomicity: WAL keeps readers unblocked; FULL makes
            # each commit durable before the statement returns, so a
            # SIGKILLed writer leaves whole committed rows or nothing —
            # never a torn record (the restart-recovery contract)
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=FULL")
            conn.execute("PRAGMA busy_timeout=30000")
            self._tls.conn = conn
        return conn

    def get(self, key: str) -> Optional[bytes]:
        row = self._conn().execute(
            "SELECT value, expiry FROM kv WHERE key=?", (key,)
        ).fetchone()
        if row is None:
            return None
        if row[1] is not None and time.time() > row[1]:
            return None
        return row[0]

    def get_from_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        rows = self._conn().execute(
            "SELECT key, value, expiry FROM kv WHERE key >= ? AND key < ? "
            "ORDER BY key",
            (prefix, prefix + "\xff"),
        ).fetchall()
        now = time.time()
        return [(k, v) for k, v, e in rows if e is None or now <= e]

    def put(self, key: str, value: bytes, lease_secs: Optional[int] = None):
        expiry = time.time() + lease_secs if lease_secs else None
        with self._conn() as c:
            c.execute(
                "INSERT OR REPLACE INTO kv (key, value, expiry) VALUES (?,?,?)",
                (key, value, expiry),
            )

    def delete(self, key: str):
        with self._conn() as c:
            c.execute("DELETE FROM kv WHERE key=?", (key,))

    def lock(self):
        return self._lock


# ---------------------------------------------------------------------------
# Scheduler state
# ---------------------------------------------------------------------------


def _pad_stage_row(row: tuple) -> tuple:
    """Pad stage rows persisted by older schedulers to the current
    7-field shape (plan_bytes, nparts, deps, shuffle_spec, mesh,
    version, reader_layouts) — positional defaults, so a 5-field row
    gets version 0 (not a mis-slotted mesh count)."""
    defaults = (None, 0, 0, None)  # spec, mesh, version, layouts
    return tuple(row) + defaults[len(row) - 3:]


class SchedulerState:
    """Namespaced cluster state + scheduling queues.

    Key scheme (reference: state/mod.rs:387-434):
      /ballista/{ns}/executors/{id}
      /ballista/{ns}/jobs/{job_id}
      /ballista/{ns}/stages/{job_id}/{stage_id}
      /ballista/{ns}/tasks/{job_id}/{stage_id}/{partition}
    """

    def __init__(self, backend: KvBackend, namespace: str = "default"):
        self.kv = backend
        self.ns = namespace
        self._lock = threading.RLock()
        # ready-queue of (job_id, stage_id, partition) runnable now
        self._ready: List[PartitionId] = []
        # stage dependency bookkeeping: (job, stage) -> [dep stage ids]
        self._stage_deps: Dict[Tuple[str, int], List[int]] = {}
        self._stage_parts: Dict[Tuple[str, int], int] = {}
        # (job, stage) -> devices a task needs (0 = any)
        self._stage_mesh: Dict[Tuple[str, int], int] = {}
        # (job, stage) -> current stage-plan version (adaptive re-plans
        # bump it; reports from older versions are dropped)
        self._stage_versions: Dict[Tuple[str, int], int] = {}
        # adaptive re-plan hook, installed by the scheduler service:
        # callable(state, job_id, completed_stage_id, ready_sids,
        # blocked_sids) invoked (under the state lock) when a stage
        # completes, BEFORE its newly-unblocked dependents are enqueued
        self.replan_hook = None
        # tasks already handed out as speculative duplicates (at most one
        # duplicate per task), tasks with one absorbed failure while a
        # twin copy was still in flight, and the last speculation scan
        # time — all guarded by self._lock
        self._speculated: set = set()
        self._spec_failed_once: set = set()
        self._last_spec_scan = 0.0
        # health plane: ring of recent query summaries (+ slow-query
        # log over BALLISTA_SLOW_QUERY_SECS) and job outcome counters,
        # fed by save_job_status transitions
        from ..observability.health import QueryLog

        self.query_log = QueryLog()
        # live progress plane: /debug/queries + system.queries carry
        # IN-FLIGHT rows (status "running", live wall seconds) next to
        # the terminal ring entries
        self.query_log.live_fn = self.live_query_records
        # last-heartbeat wall times (scheduler-side clock): feeds the
        # heartbeat_age_seconds / stale columns of system.executors
        self._heartbeats: Dict[str, float] = {}
        self.jobs_submitted = 0
        self.jobs_completed = 0
        self.jobs_failed = 0
        self.jobs_cancelled = 0
        self._job_started: Dict[str, float] = {}
        # lifecycle control plane: recently-cancelled job ids (piggy-
        # backed on PollWorkResult until they age out), server-side
        # deadlines (absolute wall times; in-memory — a restarted
        # scheduler re-queues work but drops pending deadlines), and
        # the deadline-scan throttle stamp — all guarded by self._lock
        self._cancelled_jobs: Dict[str, float] = {}
        self._job_deadlines: Dict[str, float] = {}
        self._last_deadline_scan = 0.0
        # distributed profiler: per-job logical-plan digests (so a slow
        # query is identifiable after the fact without re-planning) and
        # the terminal-transition hook the scheduler service installs —
        # profile_hook(job_id, summary, status) may build the merged
        # artifact and enrich the summary before it enters the query log
        self._job_digests: Dict[str, str] = {}
        self.profile_hook = None
        # admission plane: queue_info_fn(job_id) -> {"queue_position",
        # "reason", "queued_seconds"} | None, installed by the scheduler
        # service so queued system.queries rows show their position
        self.queue_info_fn = None
        self._rehydrate()

    def _rehydrate(self):
        """Rebuild in-memory scheduling state from a durable backend after a
        scheduler restart: stage deps/partition counts from the persisted
        stage rows, and the ready-queue from tasks that were pending when
        the previous scheduler died (running tasks are re-queued too — the
        old executor's completion report would be lost)."""
        # chaos surface: a backend read fault here is a restart against
        # a flaky store — the scheduler serves with whatever loaded
        fault_point("state.load", ns=self.ns)
        stage_rows = self.kv.get_from_prefix(self._k("stages"))
        if not stage_rows:
            return
        prefix = self._k("stages") + "/"
        with self._lock:
            jobs = set()
            for k, v in stage_rows:
                job_id, sid = k[len(prefix):].split("/")
                sid = int(sid)
                row = _pad_stage_row(pickle.loads(v))
                _, nparts, deps = row[:3]
                self._stage_deps[(job_id, sid)] = list(deps)
                self._stage_parts[(job_id, sid)] = nparts
                self._stage_mesh[(job_id, sid)] = row[4] or 0
                self._stage_versions[(job_id, sid)] = row[5] or 0
                jobs.add(job_id)
            for job_id in jobs:
                js = self.get_job_status(job_id)
                if js is not None and js.state in ("completed", "failed",
                                                   "cancelled"):
                    continue
                for sid in self.stage_ids(job_id):
                    deps = self._stage_deps.get((job_id, sid), [])
                    if not all(self._stage_complete(job_id, d) for d in deps):
                        continue
                    for t in self.get_task_statuses(job_id, sid):
                        if t.state in (None, "running"):
                            self._ready.append(t.partition)

    # -- keys ---------------------------------------------------------------

    def _k(self, *parts) -> str:
        return "/ballista/" + self.ns + "/" + "/".join(str(p) for p in parts)

    # -- executors ----------------------------------------------------------

    def save_executor_metadata(self, meta: ExecutorMeta):
        with self._lock:
            self._heartbeats[meta.id] = time.time()
        self.kv.put(self._k("executors", meta.id), pickle.dumps(meta),
                    lease_secs=EXECUTOR_LEASE_SECS)
        # durable (unleased) address record: shuffle locations must stay
        # resolvable after a lease hiccup — liveness and addressing are
        # separate concerns (the reference never lease-gates addresses,
        # state/mod.rs:85-90)
        self.kv.put(self._k("executors_meta", meta.id), pickle.dumps(meta))

    def get_executors_metadata(self) -> List[ExecutorMeta]:
        # trailing '/' so the unleased executors_meta/ records don't match
        return [
            pickle.loads(v)
            for _, v in self.kv.get_from_prefix(self._k("executors") + "/")
        ]

    def live_executor_ids(self) -> set:
        """Executors with an unexpired lease."""
        return {e.id for e in self.get_executors_metadata()}

    def all_executor_metadata(self) -> List[ExecutorMeta]:
        """Every executor ever registered, lease state ignored (the
        durable address records): system.executors builds from this so
        stale/dead executors stay VISIBLE from SQL instead of silently
        vanishing with their lease."""
        return [
            pickle.loads(v)
            for _, v in self.kv.get_from_prefix(
                self._k("executors_meta") + "/")
        ]

    def executor_heartbeats(self) -> Dict[str, float]:
        """executor id -> last PollWork wall time (this scheduler
        lifetime; a restarted scheduler starts empty, so pre-restart
        executors read as never-heartbeated until they poll again)."""
        with self._lock:
            return dict(self._heartbeats)

    def executor_address(self, executor_id: str) -> Optional[ExecutorMeta]:
        """Last-known address, regardless of lease state."""
        v = self.kv.get(self._k("executors_meta", executor_id))
        return pickle.loads(v) if v is not None else None

    # -- jobs ---------------------------------------------------------------

    def save_job_status(self, job_id: str, status: JobStatus):
        self.kv.put(self._k("jobs", job_id), pickle.dumps(status))
        # health plane bookkeeping: time the queued -> terminal window
        # and push a summary into the query ring buffer exactly once
        # per job (terminal states may be re-saved idempotently)
        if status.state == "queued":
            self.jobs_submitted += 1
            self._job_started.setdefault(job_id, time.time())
        elif status.state in ("completed", "failed", "cancelled"):
            with self._lock:
                self._job_deadlines.pop(job_id, None)
                # per-job speculation state is dead with the job: these
                # sets (and the recovery counter below) otherwise grow
                # for the scheduler's lifetime (leak test pins this)
                if self._speculated:
                    self._speculated = {
                        p for p in self._speculated
                        if p.job_id != job_id}
                if self._spec_failed_once:
                    self._spec_failed_once = {
                        p for p in self._spec_failed_once
                        if p.job_id != job_id}
            self.kv.delete(self._k("recoveries", job_id))
            t0 = self._job_started.pop(job_id, None)
            if t0 is not None:
                if status.state == "completed":
                    self.jobs_completed += 1
                elif status.state == "cancelled":
                    self.jobs_cancelled += 1
                else:
                    self.jobs_failed += 1
                # ONE record shape for every surface (/debug/queries,
                # the durable history log, system.queries): built by
                # the shared systables layer so they cannot drift
                from ..observability import systables

                out_rows = None
                sm = getattr(status, "stage_metrics", None)
                if sm:
                    try:
                        from ..observability.metrics import QueryMetrics

                        out_rows = QueryMetrics(sm).total_output_rows()
                    except Exception:  # noqa: BLE001 - advisory
                        out_rows = None
                summary = systables.build_query_record(
                    job_id, status.state, time.time() - t0,
                    # pop: the digest's job is done (the summary
                    # carries it on), and the dict must not grow one
                    # entry per job for the scheduler's lifetime
                    plan_digest=self._job_digests.pop(job_id, None),
                    output_rows=out_rows,
                    num_stages=len(self.stage_ids(job_id)),
                    started_at=t0,
                    error=status.error,
                    cancel_reason=getattr(status, "cancel_reason", None),
                    origin="cluster",
                )
                if self.profile_hook is not None:
                    # runs ONCE per job (t0 was just popped); may build
                    # the merged profile artifact and attach its path to
                    # the summary. Best-effort: observability must never
                    # take the job's terminal transition down.
                    try:
                        self.profile_hook(job_id, summary, status)
                    except Exception:  # noqa: BLE001
                        log.exception("profile hook failed for job %s",
                                      job_id)
                systables.record_query(summary,
                                       query_log=self.query_log)

    def get_job_status(self, job_id: str) -> Optional[JobStatus]:
        v = self.kv.get(self._k("jobs", job_id))
        return pickle.loads(v) if v is not None else None

    def job_started_at(self, job_id: str) -> Optional[float]:
        """Submission wall time while the job is non-terminal (the
        terminal transition pops it)."""
        return self._job_started.get(job_id)

    def live_query_records(self) -> List[dict]:
        """In-flight query rows for /debug/queries + system.queries:
        one per non-terminal job, status "queued"/"running" with LIVE
        wall seconds. Overwritten by the terminal ring record the
        moment the job finishes (the terminal transition pops
        _job_started first)."""
        from ..observability import systables

        out = []
        now = time.time()
        for job_id, t0 in list(self._job_started.items()):
            try:
                js = self.get_job_status(job_id)
            except Exception:  # noqa: BLE001 - diagnosis plane
                continue
            state = js.state if js is not None else "queued"
            if state not in ("queued", "running"):
                continue
            rec = systables.build_query_record(
                job_id, state, now - t0,
                plan_digest=self._job_digests.get(job_id),
                num_stages=len(self.stage_ids(job_id)) or None,
                started_at=t0, origin="cluster",
            )
            if state == "queued" and self.queue_info_fn is not None:
                try:
                    info = self.queue_info_fn(job_id)
                except Exception:  # noqa: BLE001 - advisory
                    info = None
                if info:
                    rec["queue_position"] = info["queue_position"]
            out.append(rec)
        return out

    def save_job_digest(self, job_id: str, digest: str):
        """Stable digest of the job's logical plan (in-memory, advisory:
        feeds slow-query summaries and profile artifact labels)."""
        self._job_digests[job_id] = digest

    def get_job_digest(self, job_id: str) -> Optional[str]:
        return self._job_digests.get(job_id)

    def save_job_settings(self, job_id: str, settings: Dict[str, str]):
        """Client ``settings`` of the submitted query, kept for the
        lifetime of the job: adaptive re-planning reads its knobs from
        here so the SUBMITTING client's configuration governs."""
        self.kv.put(self._k("jobconf", job_id), pickle.dumps(dict(settings)))

    def get_job_settings(self, job_id: str) -> Dict[str, str]:
        v = self.kv.get(self._k("jobconf", job_id))
        return pickle.loads(v) if v is not None else {}

    # -- job lifecycle: cancellation + deadlines -----------------------------
    # The reference cannot stop work at all (no CancelJob; a client
    # timeout only stops WAITING). Cancellation here is cooperative:
    # the job moves to a terminal Cancelled state, its queued tasks are
    # dropped, and executors learn via the PollWorkResult piggyback to
    # abort running tasks at batch boundaries.

    # how long a cancelled job id keeps riding PollWorkResult: every
    # executor polls multiple times within this window, so each sees
    # the cancel at least once even across a scheduler hiccup
    CANCEL_BROADCAST_SECS = 60.0

    def cancel_job(self, job_id: str, reason: str = "client") -> bool:
        """Move the job to terminal ``cancelled`` (idempotent: False
        when unknown or already terminal), drop its queued tasks, and
        start broadcasting the id to polling executors."""
        with self._lock:
            status = self.get_job_status(job_id)
            if status is None or status.state in ("completed", "failed",
                                                  "cancelled"):
                return False
            self._cancelled_jobs[job_id] = time.time()
            # queued tasks stop here; running ones abort executor-side
            self._ready = [p for p in self._ready if p.job_id != job_id]
            self.save_job_status(job_id, JobStatus(
                "cancelled", error=f"cancelled ({reason})",
                cancel_reason=reason,
            ))
        log.warning("cancelled job %s (%s)", job_id, reason)
        from ..observability.tracing import trace_event

        trace_event("lifecycle.cancel", job=job_id, reason=reason)
        return True

    def is_job_cancelled(self, job_id: str) -> bool:
        with self._lock:
            if job_id in self._cancelled_jobs:
                return True
        # a restarted scheduler loses the in-memory set but not the KV
        status = self.get_job_status(job_id)
        return status is not None and status.state == "cancelled"

    def cancelled_job_ids(self) -> List[str]:
        """Recently-cancelled job ids for the PollWorkResult piggyback
        (pruned past CANCEL_BROADCAST_SECS so the list stays bounded)."""
        now = time.time()
        with self._lock:
            stale = [j for j, t in self._cancelled_jobs.items()
                     if now - t > self.CANCEL_BROADCAST_SECS]
            for j in stale:
                del self._cancelled_jobs[j]
            return sorted(self._cancelled_jobs)

    def save_job_deadline(self, job_id: str, deadline_ts: float):
        """Absolute wall time after which reap_expired_jobs cancels the
        job (server-side: holds even when the client is gone)."""
        with self._lock:
            self._job_deadlines[job_id] = float(deadline_ts)

    def get_job_deadline(self, job_id: str) -> Optional[float]:
        with self._lock:
            return self._job_deadlines.get(job_id)

    def reap_expired_jobs(self, min_interval_secs: float = 1.0
                          ) -> List[str]:
        """Cancel jobs past their server-side deadline, and — when
        ``BALLISTA_SLOW_QUERY_KILL_SECS`` is set — jobs running longer
        than the kill threshold (upgrading the slow-query LOG to a
        kill). Runs from the PollWork reap pass, throttled. Returns the
        job ids it cancelled."""
        now = time.time()
        with self._lock:
            if now - self._last_deadline_scan < min_interval_secs:
                return []
            self._last_deadline_scan = now
            expired = [j for j, dl in self._job_deadlines.items()
                       if now > dl]
        touched = [j for j in expired if self.cancel_job(j, "deadline")]
        from ..observability.health import slow_query_kill_secs

        kill = slow_query_kill_secs()
        if kill is not None:
            overdue = [j for j, t0 in list(self._job_started.items())
                       if now - t0 >= kill]
            touched.extend(
                j for j in overdue
                if self.cancel_job(j, "slow-query-kill"))
        return touched

    # -- stages -------------------------------------------------------------

    def save_stage_plan(self, job_id: str, stage_id: int, plan_bytes: bytes,
                        num_partitions: int, dep_stage_ids: List[int],
                        shuffle_spec: "tuple | None" = None,
                        mesh_devices: int = 0, version: int = 0,
                        reader_layouts: "dict | None" = None):
        # shuffle_spec: (serialized hash expr bytes list | None, n_outputs)
        # mesh_devices: devices a task of this stage needs (mesh-fused
        # stages only; 0 = any executor can run it)
        # version / reader_layouts: adaptive re-planning state (StagePlan)
        self.kv.put(
            self._k("stages", job_id, stage_id),
            pickle.dumps(
                (plan_bytes, num_partitions, dep_stage_ids, shuffle_spec,
                 mesh_devices, version, reader_layouts)
            ),
        )
        with self._lock:
            self._stage_deps[(job_id, stage_id)] = list(dep_stage_ids)
            self._stage_parts[(job_id, stage_id)] = num_partitions
            self._stage_mesh[(job_id, stage_id)] = mesh_devices
            self._stage_versions[(job_id, stage_id)] = version

    def get_stage_plan(self, job_id: str, stage_id: int) -> StagePlan:
        v = self.kv.get(self._k("stages", job_id, stage_id))
        if v is None:
            raise ClusterError(f"no stage plan {job_id}/{stage_id}")
        return StagePlan(*_pad_stage_row(pickle.loads(v)))

    def update_stage_plan(self, job_id: str, stage_id: int,
                          plan_bytes: "bytes | None" = None,
                          num_partitions: "int | None" = None,
                          shuffle_spec: "tuple | None | str" = "keep",
                          reader_layouts: "dict | None" = None) -> int:
        """Adaptive re-plan of a NOT-YET-RUN stage: rewrite the stored
        row, bump its version, and rebuild its (pending) task rows for
        the new partition count. Returns the new version. Caller must
        have verified no task of the stage has started; the version
        bump protects against the narrow dispatch race that remains
        (see accept_report_version)."""
        with self._lock:
            row = self.get_stage_plan(job_id, stage_id)
            version = row.version + 1
            new_spec = row.shuffle_spec if shuffle_spec == "keep" \
                else shuffle_spec
            self.save_stage_plan(
                job_id, stage_id,
                plan_bytes if plan_bytes is not None else row.plan_bytes,
                num_partitions if num_partitions is not None
                else row.num_partitions,
                row.deps, new_spec, row.mesh_devices, version,
                reader_layouts if reader_layouts is not None
                else row.reader_layouts,
            )
            # task rows: drop every old row (the count may shrink) and
            # recreate the new set pending
            for t in self.get_task_statuses(job_id, stage_id):
                self.kv.delete(
                    self._k("tasks", job_id, stage_id,
                            t.partition.partition_id)
                )
            n = num_partitions if num_partitions is not None \
                else row.num_partitions
            for p in range(n):
                self.save_task_status(
                    TaskStatus(PartitionId(job_id, stage_id, p))
                )
            # purge stale ready-queue entries (old partition ids), then
            # re-seed if the stage is already unblocked
            self._ready = [
                p for p in self._ready
                if not (p.job_id == job_id and p.stage_id == stage_id)
            ]
            deps = self._stage_deps.get((job_id, stage_id), [])
            if all(self._stage_complete(job_id, d) for d in deps):
                self._enqueue_stage(job_id, stage_id)
            return version

    def stage_version(self, job_id: str, stage_id: int) -> int:
        with self._lock:
            return self._stage_versions.get((job_id, stage_id), 0)

    def accept_report_version(self, st: TaskStatus) -> bool:
        """False when the report comes from a superseded stage version
        (the executor ran a task cut before an adaptive re-plan): the
        caller must drop it. A current-version twin may be stranded in
        "running" by the dispatch race — reset + re-queue it so the
        stage cannot hang."""
        pid = st.partition
        key = (pid.job_id, pid.stage_id)
        with self._lock:
            cur = self._stage_versions.get(key, 0)
            if (st.stage_version or 0) == cur:
                return True
            n = self._stage_parts.get(key, 0)
            if pid.partition_id < n and not self.is_completed(pid):
                prior = next(
                    (t for t in self.get_task_statuses(pid.job_id,
                                                       pid.stage_id)
                     if t.partition.partition_id == pid.partition_id),
                    None,
                )
                # reset only a row STRANDED at a superseded version (the
                # dispatch race); a running row already at the current
                # version is a healthy re-dispatched copy — resetting it
                # would spawn a redundant third execution
                if prior is not None and prior.state == "running" and \
                        (getattr(prior, "stage_version", 0) or 0) != cur:
                    self._reset_task(pid)
                    deps = self._stage_deps.get(key, [])
                    if all(self._stage_complete(pid.job_id, d)
                           for d in deps):
                        self._enqueue_stage(pid.job_id, pid.stage_id)
            log.info("dropping stale v%d report for %s (stage now v%d)",
                     st.stage_version or 0, pid.key(), cur)
            return False

    def stage_started(self, job_id: str, stage_id: int) -> bool:
        """True when any task of the stage has been dispatched (or
        finished): adaptive re-planning must leave such stages alone."""
        return any(t.state is not None
                   for t in self.get_task_statuses(job_id, stage_id))

    def shuffle_partition_histogram(self, job_id: str, stage_id: int):
        """Observed shuffle output of a COMPLETED hash/round-robin
        shuffle stage: ``(bytes_per_output, per_producer)`` where
        ``per_producer[q][p]`` is the bytes producer task p wrote for
        output partition q. None when the stage is not a shuffle, is
        incomplete, or its tasks predate the histogram field."""
        row = self.get_stage_plan(job_id, stage_id)
        if row.shuffle_spec is None:
            return None
        n_out = row.shuffle_spec[1]
        done = [t for t in self.get_task_statuses(job_id, stage_id)
                if t.state == "completed"]
        if len(done) < row.num_partitions:
            return None
        per = [[0] * row.num_partitions for _ in range(n_out)]
        for t in done:
            h = (t.stats or {}).get("shuffle_partition_bytes")
            if not h or len(h) != n_out:
                return None
            p = t.partition.partition_id
            for q in range(n_out):
                per[q][p] = int(h[q])
        return [sum(per[q]) for q in range(n_out)], per

    def stage_output_bytes(self, job_id: str, stage_id: int
                           ) -> Optional[int]:
        """Total bytes a completed stage materialized (all tasks), or
        None while incomplete — the join-demotion size signal."""
        row = self.get_stage_plan(job_id, stage_id)
        done = [t for t in self.get_task_statuses(job_id, stage_id)
                if t.state == "completed"]
        if len(done) < row.num_partitions:
            return None
        return sum(int((t.stats or {}).get("num_bytes", 0)) for t in done)

    def stage_consumers(self, job_id: str, stage_id: int) -> List[int]:
        """Stage ids that list ``stage_id`` as a dependency."""
        with self._lock:
            return [sid for (j, sid), deps in self._stage_deps.items()
                    if j == job_id and stage_id in deps]

    def stage_ids(self, job_id: str) -> List[int]:
        prefix = self._k("stages", job_id) + "/"
        return sorted(
            int(k[len(prefix):]) for k, _ in self.kv.get_from_prefix(prefix)
        )

    # -- tasks --------------------------------------------------------------

    def save_task_status(self, st: TaskStatus):
        fault_point("state.save", task=st.partition.key())
        self.kv.put(
            self._k("tasks", st.partition.job_id, st.partition.stage_id,
                    st.partition.partition_id),
            pickle.dumps(st),
        )

    def get_task_statuses(self, job_id: str,
                          stage_id: Optional[int] = None) -> List[TaskStatus]:
        # trailing '/' so stage 1 doesn't prefix-match stages 10..19
        prefix = (
            self._k("tasks", job_id, stage_id) + "/"
            if stage_id is not None
            else self._k("tasks", job_id) + "/"
        )
        return [pickle.loads(v) for _, v in self.kv.get_from_prefix(prefix)]

    # -- scheduling ---------------------------------------------------------

    def enqueue_job(self, job_id: str):
        """Called once stage plans + empty task rows are persisted: seed the
        ready-queue with every stage that has no pending dependencies."""
        with self._lock:
            for sid in self.stage_ids(job_id):
                deps = self._stage_deps.get((job_id, sid), [])
                if not deps:
                    self._enqueue_stage(job_id, sid)

    def _enqueue_stage(self, job_id: str, stage_id: int):
        """Enqueue the stage's PENDING tasks (state None) that are not
        already queued — idempotent, so recovery can re-trigger it after
        resetting lost tasks without double-running live ones. A
        cancelled job enqueues nothing (recovery/completion paths may
        still fire for late reports)."""
        if job_id in self._cancelled_jobs:
            return
        n = self._stage_parts[(job_id, stage_id)]
        started = {
            t.partition.partition_id
            for t in self.get_task_statuses(job_id, stage_id)
            if t.state is not None
        }
        queued = {
            p.partition_id for p in self._ready
            if p.job_id == job_id and p.stage_id == stage_id
        }
        for p in range(n):
            if p not in started and p not in queued:
                self._ready.append(PartitionId(job_id, stage_id, p))

    def ready_queue_depth(self) -> int:
        with self._lock:
            return len(self._ready)

    def next_task(self, num_devices: int = 0) -> Optional[PartitionId]:
        """Pop the first ready task the calling executor can run: a
        mesh-fused stage's tasks only go to executors reporting at least
        that many devices (0 = caller capacity unknown, accept any)."""
        with self._lock:
            # purge tasks of cancelled jobs first: a stage completion
            # racing the cancel may have re-enqueued some
            if self._cancelled_jobs:
                self._ready = [p for p in self._ready
                               if p.job_id not in self._cancelled_jobs]
            for i, pid in enumerate(self._ready):
                need = self._stage_mesh.get((pid.job_id, pid.stage_id), 0)
                if need and num_devices and num_devices < need:
                    continue
                return self._ready.pop(i)
        return None

    def is_completed(self, pid: PartitionId) -> bool:
        v = self.kv.get(self._k("tasks", pid.job_id, pid.stage_id,
                                pid.partition_id))
        return v is not None and pickle.loads(v).state == "completed"

    def task_completed(self, st: TaskStatus):
        """Record completion; if a whole stage just completed, unlock its
        dependents (event-driven, replacing the reference's full scan).
        First result wins: when speculation duplicated the task, the
        second completion report is dropped so consumers keep fetching
        from the location already recorded."""
        job_id = st.partition.job_id
        stage_id = st.partition.stage_id
        with self._lock:
            prior = next(
                (t for t in self.get_task_statuses(job_id, stage_id)
                 if t.partition.partition_id == st.partition.partition_id),
                None,
            )
            if prior is not None and prior.state == "completed":
                return  # a duplicate (speculative) completion lost the race
            self.save_task_status(st)
            stage_tasks = self.get_task_statuses(job_id, stage_id)
            n = self._stage_parts.get((job_id, stage_id))
            done = [t for t in stage_tasks if t.state == "completed"]
            if n is None or len(done) < n:
                return
            # stage complete: enqueue dependents whose deps are all complete
            # (_enqueue_stage only picks up still-pending tasks, so this is
            # safe to re-trigger after recovery resets)
            ready, blocked = [], []
            for (j, sid), deps in list(self._stage_deps.items()):
                if j != job_id or stage_id not in deps:
                    continue
                if all(self._stage_complete(j, d) for d in deps):
                    ready.append(sid)
                else:
                    blocked.append(sid)
            if self.replan_hook is not None and (ready or blocked):
                # adaptive re-planning window: dependents' plans may be
                # rewritten from the completed stage's observed metrics
                # BEFORE any of their tasks is enqueued. Best-effort: a
                # re-plan failure must never take the job down with it —
                # the static plan is always a correct fallback.
                try:
                    self.replan_hook(self, job_id, stage_id, ready, blocked)
                except Exception:  # noqa: BLE001 - keep static plan
                    log.exception(
                        "adaptive re-plan failed for job %s after stage "
                        "%d; continuing with the static plan",
                        job_id, stage_id,
                    )
            for sid in ready:
                self._enqueue_stage(job_id, sid)

    def _stage_complete(self, job_id: str, stage_id: int) -> bool:
        n = self._stage_parts.get((job_id, stage_id), 0)
        done = [
            t for t in self.get_task_statuses(job_id, stage_id)
            if t.state == "completed"
        ]
        return len(done) >= n

    def stage_locations(self, job_id: str, stages=None
                        ) -> Dict[int, List[PartitionLocation]]:
        """Completed-task locations per stage (for shuffle resolution).
        `stages` restricts the scan so an unroutable, already-consumed
        stage elsewhere in the job can't fail an unrelated resolution."""
        out: Dict[int, List[PartitionLocation]] = {}
        executors = {e.id: e for e in self.get_executors_metadata()}
        for t in self.get_task_statuses(job_id):
            if t.state != "completed":
                continue
            if stages is not None and t.partition.stage_id not in stages:
                continue
            e = executors.get(t.executor_id)
            if e is None and t.executor_id:
                # lease expired: fall back to the durable address record —
                # the data may still be served; if not, the consumer fails
                # with a tagged ShuffleFetchError and recovery re-queues
                # the producer
                e = self.executor_address(t.executor_id)
            if e is None:
                # no route to the data at all: fail resolution with the
                # tagged error NOW so the caller triggers producer
                # recovery, instead of emitting host="",port=0 for a
                # consumer to trip over
                from ..errors import ShuffleFetchError

                raise ShuffleFetchError(
                    t.partition.stage_id, [t.partition.partition_id],
                    t.executor_id or "",
                    "completed task has no routable executor address",
                )
            host, port = e.host, e.port
            out.setdefault(t.partition.stage_id, []).append(
                PartitionLocation(
                    job_id=t.partition.job_id,
                    stage_id=t.partition.stage_id,
                    partition_id=t.partition.partition_id,
                    executor_id=t.executor_id or "",
                    host=host,
                    port=port,
                    path=t.path or "",
                    stats=t.stats,
                )
            )
        return out

    # -- failure recovery ----------------------------------------------------
    # The reference detects failures but never recovers (any failed task
    # fails the job, state/mod.rs:342-346; lost shuffle data hangs or
    # errors). We re-queue lost producer partitions on tagged fetch
    # failures and re-queue running tasks of dead executors, with a
    # per-job retry cap.

    DEFAULT_MAX_RECOVERIES = 3

    @property
    def MAX_RECOVERIES_PER_JOB(self) -> int:
        """``BALLISTA_MAX_TASK_RECOVERIES`` (default 3): recovery
        EVENTS allowed per job across all recovery paths (transient
        retry, fetch recovery, lease reap) before the job fails with
        the underlying error. Read per use so the chaos sweep and
        operators can tune the budget without restarting."""
        try:
            return max(int(os.environ.get(
                "BALLISTA_MAX_TASK_RECOVERIES", "")
                or self.DEFAULT_MAX_RECOVERIES), 0)
        except ValueError:
            return self.DEFAULT_MAX_RECOVERIES

    def _recovery_count(self, job_id: str) -> int:
        v = self.kv.get(self._k("recoveries", job_id))
        return int(v) if v else 0

    def _bump_recovery(self, job_id: str) -> int:
        n = self._recovery_count(job_id) + 1
        self.kv.put(self._k("recoveries", job_id), str(n).encode())
        return n

    def _reset_task(self, pid: PartitionId):
        self.save_task_status(TaskStatus(pid))

    def recover_fetch_failure(self, st: TaskStatus) -> bool:
        """Attempt recovery from a consumer task that failed with a tagged
        ShuffleFetchError: reset the lost producer partitions and the
        consumer task to pending and re-queue the producers. Returns True
        if recovery was initiated (caller must NOT record the failure)."""
        from ..errors import ShuffleFetchError

        parsed = ShuffleFetchError.parse(st.error or "")
        if parsed is None:
            return False
        job_id = st.partition.job_id
        dep_stage, lost_parts, _executor = parsed
        with self._lock:
            known = self._stage_parts.get((job_id, dep_stage))
            if known is None:
                return False
            # concurrent consumers failing on the SAME lost producer join
            # the in-flight recovery instead of burning retry budget
            statuses = {
                t.partition.partition_id: t.state
                for t in self.get_task_statuses(job_id, dep_stage)
            }
            fresh = [
                p for p in lost_parts
                if 0 <= p < known and statuses.get(p) == "completed"
            ]
            if fresh and self._bump_recovery(job_id) > \
                    self.MAX_RECOVERIES_PER_JOB:
                return False
            for p in fresh:
                self._reset_task(PartitionId(job_id, dep_stage, p))
            self._reset_task(st.partition)
            # queued tasks of stages depending on the now-incomplete
            # producer would fail location resolution — pull them out;
            # stage re-completion re-enqueues them
            consumers = {
                sid for (j, sid), deps in self._stage_deps.items()
                if j == job_id and dep_stage in deps
            }
            self._ready = [
                p for p in self._ready
                if not (p.job_id == job_id and p.stage_id in consumers)
            ]
            self._enqueue_stage(job_id, dep_stage)
        return True

    # error-class prefixes considered transient (executor tags failures
    # with the exception class name); deterministic failures — plan bugs,
    # capacity limits — fail fast like the reference
    TRANSIENT_ERRORS = ("IoError:", "OSError:", "ConnectionError:",
                        "ConnectionResetError:", "ConnectionRefusedError:",
                        "TimeoutError:", "BrokenPipeError:",
                        # injected faults deliberately look transient so
                        # the chaos sweep exercises the retry budget
                        "FaultInjected:",
                        # a DRAINING executor cancels its in-flight
                        # tasks; the job is still live — re-queue them
                        # (job-cancel reports never reach here: PollWork
                        # drops reports for cancelled jobs)
                        "QueryCancelled:")

    def recover_transient_failure(self, st: TaskStatus) -> bool:
        """Re-queue a task that failed with an IO-shaped (transient)
        error, within the job's recovery budget. The reference fails the
        whole job on ANY task failure (state/mod.rs:342-346)."""
        err = st.error or ""
        if not err.startswith(self.TRANSIENT_ERRORS):
            return False
        with self._lock:
            if (st.partition.job_id, st.partition.stage_id) not in \
                    self._stage_parts:
                return False
            if self._bump_recovery(st.partition.job_id) > \
                    self.MAX_RECOVERIES_PER_JOB:
                return False
            self._reset_task(st.partition)
            self._enqueue_stage(st.partition.job_id, st.partition.stage_id)
        return True

    SPECULATION_SCAN_INTERVAL_SECS = 5.0

    def speculative_task(self, num_devices: int = 0,
                         age_secs: float = 60.0,
                         executor_id: str = "",
                         min_interval_secs: Optional[float] = None,
                         lag_fn=None) -> Optional[PartitionId]:
        """Straggler mitigation the reference lacks entirely: when an
        executor is idle and nothing is ready, hand out a DUPLICATE of a
        long-running task (first completion wins — task_completed drops
        later reports, so the recorded completion's location is
        self-consistent). Each task is duplicated at most once, never on
        the executor already running it (a duplicate on the same executor
        would race the original on the same work_dir path), and fruitless
        full-task scans are throttled like reap_lost_tasks (a successful
        scan doesn't delay the next one — only the idle-poll storm with
        nothing to speculate is capped).

        ``lag_fn(task_status) -> bool | None`` is the RATE-based
        trigger (the scheduler wires the progress tracker's
        ``is_lagging`` here): True = the task's observed rate trails
        its stage median by ``BALLISTA_SPECULATION_LAG_FACTOR`` —
        duplicate it regardless of age; False = the task is measurably
        healthy — do NOT duplicate it even past the age threshold;
        None = no samples — fall back to the wall-clock age trigger."""
        if min_interval_secs is None:
            min_interval_secs = self.SPECULATION_SCAN_INTERVAL_SECS
        now = time.time()
        with self._lock:
            if now - self._last_spec_scan < min_interval_secs:
                return None
            # stamp BEFORE scanning (atomic check-and-set like
            # reap_lost_tasks) so concurrent idle polls can't all start
            # full scans; cleared again if this scan finds a candidate
            self._last_spec_scan = now
        for k, v in self.kv.get_from_prefix(self._k("jobs")):
            if pickle.loads(v).state not in ("queued", "running"):
                continue
            job_id = k.rsplit("/", 1)[1]
            with self._lock:
                for t in self.get_task_statuses(job_id):
                    key = t.partition
                    if (t.state == "running" and t.started_at
                            and key not in self._speculated
                            and t.executor_id != executor_id):
                        lagging = None
                        if lag_fn is not None:
                            try:
                                lagging = lag_fn(t)
                            except Exception:  # noqa: BLE001 - advisory
                                lagging = None
                        if lagging is None:
                            # no rate samples: the old age trigger
                            if now - t.started_at <= age_secs:
                                continue
                        elif not lagging:
                            continue
                        need = self._stage_mesh.get(
                            (job_id, t.partition.stage_id), 0)
                        if need and num_devices and num_devices < need:
                            continue
                        self._speculated.add(key)
                        # a successful scan doesn't delay the next one
                        self._last_spec_scan = 0.0
                        return t.partition
        return None

    def absorb_speculative_failure(self, pid: PartitionId) -> bool:
        """A task with an in-flight speculative duplicate reported a
        failure while its twin may still be running: absorb the FIRST
        such failure (return True — the caller must not record it or
        trigger recovery); the second failure means both copies died and
        flows through the normal failure path."""
        with self._lock:
            if pid not in self._speculated or self.is_completed(pid):
                return False
            if pid in self._spec_failed_once:
                return False
            self._spec_failed_once.add(pid)
            return True

    def reap_lost_tasks(self, min_interval_secs: float = 5.0) -> List[str]:
        """Re-queue running tasks whose executor's lease has expired (the
        executor died mid-task; its completion report will never arrive).
        One executor-death event costs ONE unit of the job's recovery
        budget regardless of how many of its tasks were in flight.
        Throttled; returns the job ids it touched so the caller can
        re-synthesize their status (budget exhaustion marks tasks failed,
        and nothing else would ever surface that to the client)."""
        now = time.time()
        with self._lock:
            if now - getattr(self, "_last_reap", 0.0) < min_interval_secs:
                return []
            self._last_reap = now
        live = self.live_executor_ids()
        touched: List[str] = []
        for k, v in self.kv.get_from_prefix(self._k("jobs")):
            status = pickle.loads(v)
            if status.state not in ("queued", "running"):
                continue
            job_id = k.rsplit("/", 1)[1]
            with self._lock:
                lost = [
                    t for t in self.get_task_statuses(job_id)
                    if t.state == "running" and t.executor_id
                    and t.executor_id not in live
                ]
                if not lost:
                    continue
                touched.append(job_id)
                if self._bump_recovery(job_id) > self.MAX_RECOVERIES_PER_JOB:
                    for t in lost:
                        self.save_task_status(TaskStatus(
                            t.partition, "failed",
                            error=f"executor {t.executor_id} lost and "
                                  "retry budget exhausted",
                        ))
                    continue
                for t in lost:
                    self._reset_task(t.partition)
                for sid in {t.partition.stage_id for t in lost}:
                    self._enqueue_stage(job_id, sid)
        return touched

    # -- job status synthesis (reference: state/mod.rs:267-358) --------------

    def synchronize_job_status(self, job_id: str):
        status = self.get_job_status(job_id)
        if status is None or status.state in ("completed", "failed",
                                              "cancelled"):
            return
        if self.is_job_cancelled(job_id):
            return  # cancel marked but terminal save still in flight
        tasks = self.get_task_statuses(job_id)
        if not tasks:
            return
        if any(t.state == "failed" for t in tasks):
            err = next(t.error for t in tasks if t.state == "failed")
            self.save_job_status(job_id, JobStatus("failed", error=err))
            return
        final_sid = max(self.stage_ids(job_id))
        final_tasks = [t for t in tasks if t.partition.stage_id == final_sid]
        n = self._stage_parts.get((job_id, final_sid), len(final_tasks))
        done = [t for t in final_tasks if t.state == "completed"]
        if final_tasks and len(done) >= n:
            from ..errors import ShuffleFetchError

            try:
                locs = self.stage_locations(
                    job_id, stages={final_sid}
                ).get(final_sid, [])
            except ShuffleFetchError as e:
                # a completed result partition lost its executor before the
                # client fetched it — re-queue the producer (within budget)
                # rather than publishing an unroutable location
                if not self.recover_fetch_failure(
                    TaskStatus(
                        PartitionId(job_id, final_sid, e.partition_ids[0]),
                        "failed", error=str(e),
                    )
                ):
                    self.save_job_status(
                        job_id, JobStatus("failed", error=str(e))
                    )
                return
            self.save_job_status(
                job_id,
                JobStatus("completed", partition_locations=locs,
                          stage_metrics=self._aggregate_stage_metrics(tasks)),
            )
        elif any(t.state is not None for t in tasks):
            self.save_job_status(job_id, JobStatus("running"))

    def _aggregate_stage_metrics(self, tasks) -> Dict[int, dict]:
        """Merge completed tasks' per-operator metrics per stage (tasks of
        one stage share a plan shape, so operator rows align
        positionally). Returned with the completed JobStatus so the
        client's ``ctx.last_query_metrics()`` gets a per-stage breakdown
        without extra RPCs."""
        from ..observability.metrics import merge_operator_metrics

        by_stage: Dict[int, List] = {}
        for t in tasks:
            tm = getattr(t, "metrics", None)
            if t.state == "completed" and tm:
                by_stage.setdefault(t.partition.stage_id, []).append(tm)
        out: Dict[int, dict] = {}
        for sid, tms in by_stage.items():
            out[sid] = {
                "num_tasks": len(tms),
                "elapsed_total": sum(tm.get("elapsed_total", 0.0)
                                     for tm in tms),
                "operators": merge_operator_metrics(
                    tm.get("operators") or [] for tm in tms),
            }
        return out
