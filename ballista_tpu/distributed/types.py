"""Scheduler domain types (reference: rust/core/src/serde/scheduler/mod.rs:
34-253 — Action/PartitionId/PartitionLocation/ExecutorMeta/PartitionStats)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass(frozen=True)
class ExecutorMeta:
    id: str
    host: str
    port: int  # data-plane port
    num_devices: int = 1
    # last-heartbeat resource gauges (rss_bytes, device_bytes,
    # inflight_tasks, ingest_pool_depth, peak_host_bytes) for the
    # scheduler's health plane; None from executors predating the field
    resources: Optional[Dict[str, int]] = None


@dataclass(frozen=True)
class PartitionId:
    job_id: str
    stage_id: int
    partition_id: int

    def key(self) -> str:
        return f"{self.job_id}/{self.stage_id}/{self.partition_id}"


@dataclass
class PartitionLocation:
    job_id: str
    stage_id: int
    partition_id: int  # PRODUCER partition for shuffled stages
    executor_id: str
    host: str
    port: int
    path: str = ""
    stats: Optional[Dict[str, int]] = None
    # hash-shuffled stages: which consumer partition this file feeds
    shuffle_output: Optional[int] = None


@dataclass
class TaskStatus:
    partition: PartitionId
    # one of: None (pending), "running", "completed", "failed"
    state: Optional[str] = None
    executor_id: Optional[str] = None
    error: Optional[str] = None
    path: Optional[str] = None
    stats: Optional[Dict[str, int]] = None
    # assignment wall time; drives straggler detection (speculation)
    started_at: Optional[float] = None
    # per-operator execution metrics shipped with completion
    # ({"operators": [...], "elapsed_total": float}; see observability)
    metrics: Optional[dict] = None
    # version of the stage plan the task ran against; reports from a
    # superseded version (adaptive re-planning) are dropped
    stage_version: int = 0


@dataclass
class StagePlan:
    """One stage row as stored by the scheduler state (see
    SchedulerState.save_stage_plan for field semantics)."""

    plan_bytes: bytes
    num_partitions: int
    deps: list
    shuffle_spec: Optional[tuple] = None
    mesh_devices: int = 0
    # bumped each time adaptive re-planning rewrites the stage; task
    # definitions carry it and status reports echo it back
    version: int = 0
    # adaptive reader layouts: dep stage_id -> List[List[(out_lo,
    # out_hi, prod_lo, prod_hi)]] (see adaptive/rules.py)
    reader_layouts: Optional[dict] = None


@dataclass
class JobStatus:
    state: str  # queued|running|completed|failed|cancelled
    error: Optional[str] = None
    partition_locations: Optional[list] = None
    # stage_id -> aggregated task metrics (filled when completed)
    stage_metrics: Optional[dict] = None
    # terminal "cancelled" provenance: client|timeout|deadline|
    # slow-query-kill|drain (read with getattr — durable backends may
    # hold pickles from before the field existed)
    cancel_reason: Optional[str] = None
