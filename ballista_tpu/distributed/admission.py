"""Overload-safe multi-tenant admission plane: quotas, priorities,
load shedding.

Every ``ExecuteQuery`` submission passes a gate BEFORE any planning
work starts. The gate reads the per-session metering the progress
plane accumulates (``system.sessions`` — observability/progress.py)
and the live cluster load (ready-queue depth + executor-heartbeat
in-flight tasks) against per-session quotas and a global saturation
bound, and lands on one rung of the degradation ladder:

    admit  ->  queue  ->  shed

- **admit** — the job plans and runs exactly as before (the default:
  every quota knob defaults to unlimited, so an unconfigured cluster
  behaves identically to the pre-admission engine).
- **queue** — transient pressure (session/cluster concurrency caps, a
  saturated ready queue) holds the submission in a bounded admission
  queue ordered by ``admission.priority`` (higher first), then the
  job's server-side deadline (sooner first), then arrival. The job is
  visible as status=queued with its queue position via GetJobStatus,
  ``/debug/jobs`` and ``system.queries``; it is bounded by
  ``admission.queue_timeout_secs`` (shed on expiry), by its own
  deadline, and by the existing CancelJob path — a queued submission
  can never stall silently.
- **shed** — non-transient pressure (an exhausted cumulative session
  budget, a full admission queue, a draining scheduler) rejects the
  submission with a structured retryable error
  (:class:`~ballista_tpu.errors.AdmissionRejected`) carrying
  ``retry_after_secs``; ``remote_collect`` honors it within the
  client's job timeout.

Configuration rides the established knob registry: per key,
``settings["admission.X"]`` > env ``BALLISTA_ADMISSION_X`` > default
(same resolution order as ``adaptive.*``). Decisions emit
``admission.*`` trace events, Prometheus gauges/counters + a
queue-wait histogram, and ``system.admission`` rows; the
``scheduler.admit`` / ``scheduler.admission_queue`` fault points feed
the chaos overload sweep (tests/test_admission.py).

The queue itself is in-memory scheduler state, but every accepted
submission is ALSO journaled through the control plane
(distributed/controlplane/journal.py) at decision time: a scheduler
restarted against a durable backend rebuilds queued (never-admitted)
submissions — priority, deadline and original enqueue time preserved
— in its ``recover()`` pass, marked ``recovered`` in queue-info and
GetJobStatus. Only a memory-backed (or journal-degraded) scheduler
keeps the old contract: queued submissions drop and their waiting
clients see an unknown-job error and resubmit.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..errors import AdmissionRejected, FaultInjected
from ..testing.faults import fault_point

log = logging.getLogger("ballista.admission")


def _as_bool(raw, key: str, default: bool) -> bool:
    # one truthy/falsy contract for every knob section (adaptive owns
    # the canonical tuple — a drift here would split the config dialect)
    from ..adaptive.config import _as_bool as _adaptive_bool

    return _adaptive_bool(raw, key, default)


@dataclass(frozen=True)
class AdmissionConfig:
    """The ``admission.*`` knob section. Every limit defaults to 0 =
    unlimited, so admission is a no-op until an operator (env) or a
    session (settings) configures pressure bounds."""

    enabled: bool = True
    # -- per-session quotas (session.id travels with the query settings)
    # concurrent admitted (non-terminal) jobs per session; excess QUEUES
    max_session_jobs: int = 0
    # cumulative budgets vs the system.sessions meter; excess SHEDS
    session_task_seconds: float = 0.0
    session_shuffle_bytes: int = 0
    session_host_bytes: int = 0
    # -- global bounds
    # concurrent admitted jobs across all sessions; excess QUEUES
    max_running_jobs: int = 0
    # ready-queue depth + heartbeat in-flight tasks; past it, QUEUE
    saturation_tasks: int = 0
    # admission queue bound; past it, SHED (default 64: a bounded queue
    # is the point — unbounded waiting is the failure mode this plane
    # exists to remove)
    max_queue_depth: int = 64
    # a submission queued longer than this is SHED with retry-after
    queue_timeout_secs: float = 30.0
    # the retry-after hint stamped on sheds
    retry_after_secs: float = 1.0
    # ordering: higher priority pops first (per-query setting)
    priority: float = 0.0

    @staticmethod
    def from_settings(settings: Optional[Dict[str, str]] = None,
                      env: Optional[Dict[str, str]] = None
                      ) -> "AdmissionConfig":
        s = settings or {}
        env = os.environ if env is None else env

        def raw(key: str):
            if key in s:
                return s[key]
            return env.get("BALLISTA_" + key.upper().replace(".", "_"))

        def boolean(key: str, default: bool) -> bool:
            v = raw(key)
            return default if v is None else _as_bool(v, key, default)

        def number(key: str, default: float, cast=float):
            v = raw(key)
            if v is None:
                return default
            try:
                n = cast(str(v).strip())
            except ValueError:
                raise ValueError(
                    f"config key {key!r}: expected a number, got {v!r}"
                ) from None
            if n < 0:
                raise ValueError(f"config key {key!r}: must be >= 0")
            return n

        return AdmissionConfig(
            enabled=boolean("admission.enabled", True),
            max_session_jobs=number("admission.max_session_jobs", 0, int),
            session_task_seconds=number(
                "admission.session_task_seconds", 0.0),
            session_shuffle_bytes=number(
                "admission.session_shuffle_bytes", 0, int),
            session_host_bytes=number(
                "admission.session_host_bytes", 0, int),
            max_running_jobs=number("admission.max_running_jobs", 0, int),
            saturation_tasks=number("admission.saturation_tasks", 0, int),
            max_queue_depth=number("admission.max_queue_depth", 64, int),
            queue_timeout_secs=number(
                "admission.queue_timeout_secs", 30.0),
            retry_after_secs=number("admission.retry_after_secs", 1.0),
            # priority may legitimately be negative: raw parse
            priority=float(raw("admission.priority") or 0.0),
        )


@dataclass
class Decision:
    """One gate verdict. ``action`` is the ladder rung; queue entries
    also carry everything the pump needs to launch or shed later."""

    action: str  # "admit" | "queue" | "shed"
    job_id: str
    session_id: str
    reason: str = ""
    retry_after_secs: float = 0.0
    config: AdmissionConfig = field(default_factory=AdmissionConfig)
    deadline_ts: Optional[float] = None
    enqueued_at: float = 0.0
    args: Optional[tuple] = None  # held planning args for queued jobs
    # rebuilt from the control-plane journal by a restarted scheduler's
    # recover() pass (GetJobStatus surfaces it as QueuedJob.recovered)
    recovered: bool = False

    def error(self) -> AdmissionRejected:
        return AdmissionRejected(self.reason, self.retry_after_secs,
                                 job_id=self.job_id)


class AdmissionController:
    """The scheduler's admission gate + bounded submission queue.

    Thread-safety: one RLock guards the queue/active maps; every state
    transition that re-enters the scheduler (save_job_status fires the
    terminal hook, which calls back into :meth:`on_terminal`) happens
    OUTSIDE the lock — the pump collects its actions under the lock and
    executes them after releasing it."""

    DECISION_RING = 256
    PUMP_INTERVAL_SECS = 0.2

    def __init__(self, state=None,
                 launch_fn: Optional[Callable[[tuple], None]] = None,
                 shed_fn: Optional[Callable[[Decision], None]] = None):
        self._state = state
        self.launch_fn = launch_fn
        # shed_fn(decision): move an already-accepted (queued) job to
        # its terminal shed state — wired to the scheduler service
        self.shed_fn = shed_fn
        # queue_wait_fn(job_id, wait_secs): stamp the admitted job's
        # queue wait into its latency ledger (observability/ledger.py)
        # — wired by the scheduler, best-effort
        self.queue_wait_fn: Optional[Callable[[str, float], None]] = None
        self._lock = threading.RLock()
        self._queue: List[Decision] = []
        self._active_session: Dict[str, str] = {}  # job_id -> session
        self._session_jobs: Dict[str, int] = {}
        self._last_pump = 0.0
        self.draining = False
        self.admitted_total = 0
        self.queued_total = 0
        self.sheds_total = 0
        self._decisions: deque = deque(maxlen=self.DECISION_RING)

    # -- load + metering signals --------------------------------------------

    def _cluster_load(self) -> int:
        """Tasks the cluster already owes work for: ready-queue depth
        plus the in-flight counts every executor heartbeat reports."""
        st = self._state
        if st is None:
            return 0
        load = 0
        try:
            load += st.ready_queue_depth()
        except Exception:  # noqa: BLE001 - advisory signal
            pass
        try:
            for m in st.get_executors_metadata():
                res = getattr(m, "resources", None) or {}
                load += int(res.get("inflight_tasks") or 0)
        except Exception:  # noqa: BLE001 - advisory signal
            pass
        return load

    @staticmethod
    def _session_meter_row(session_id: str) -> dict:
        """The session's cumulative metering record (system.sessions)."""
        from ..observability.progress import process_session_meter

        for rec in process_session_meter().rows():
            if rec.get("session_id") == session_id:
                return rec
        return {}

    # -- the gate ------------------------------------------------------------

    def gate(self, job_id: str, settings: Dict[str, str],
             deadline_secs: float = 0.0) -> Decision:
        """Evaluate one submission. A malformed ``admission.*`` value
        raises ValueError to the submitter (a configured-but-broken
        quota must fail LOUDLY, not silently stop being enforced —
        same posture as a bad ``job.deadline``). Beyond that the gate
        never raises into ExecuteQuery: a triggered ``scheduler.admit``
        fault (IoError-shaped, transient) degrades to a retryable shed;
        any OTHER internal error fails OPEN (admit, logged loudly) — an
        admission bug must not take a serving cluster's front door
        down."""
        from ..observability.progress import SESSION_SETTING

        session_id = str((settings or {}).get(SESSION_SETTING)
                         or "anonymous")
        # user config errors are not "internal": parse OUTSIDE the
        # fail-open guard so they surface to the submitter
        cfg = AdmissionConfig.from_settings(settings)
        try:
            fault_point("scheduler.admit", job=job_id,
                        session=session_id[:12])
            decision = self._decide(job_id, session_id, cfg,
                                    deadline_secs)
        except FaultInjected as e:
            decision = Decision("shed", job_id, session_id,
                                reason="admission-fault",
                                retry_after_secs=1.0)
            log.warning("admission gate fault for job %s: %s", job_id, e)
        except Exception:  # noqa: BLE001 - fail OPEN
            log.exception("admission gate failed for job %s; admitting",
                          job_id)
            decision = self._reserve(Decision("admit", job_id,
                                              session_id,
                                              reason="gate-error"))
        self._record(decision)
        return decision

    def _decide(self, job_id: str, session_id: str,
                cfg: AdmissionConfig, deadline_secs: float) -> Decision:
        def shed(reason: str) -> Decision:
            return Decision("shed", job_id, session_id, reason=reason,
                            retry_after_secs=cfg.retry_after_secs,
                            config=cfg)

        def queued(reason: str) -> Decision:
            # caller holds self._lock: the depth check and the queue
            # RESERVATION are one critical section (racing gates must
            # not grow the queue past the bound), and the queue-full
            # backstop only applies to work that would actually queue —
            # an admissible submission never pays for other tenants'
            # backlog. The entry enters the queue NOW with args pending
            # (the pump skips args-less entries until enqueue() lands).
            if cfg.max_queue_depth and \
                    len(self._queue) >= cfg.max_queue_depth:
                return shed("queue-full")
            d = Decision(
                "queue", job_id, session_id, reason=reason,
                retry_after_secs=cfg.retry_after_secs, config=cfg,
                deadline_ts=(time.time() + deadline_secs
                             if deadline_secs > 0 else None),
                enqueued_at=time.time(),
            )
            self._queue.append(d)
            self._sort_locked()
            return d

        if not cfg.enabled:
            return self._reserve(Decision(
                "admit", job_id, session_id, reason="disabled",
                config=cfg))
        if self.draining:
            return shed("draining")
        # cumulative session budgets: non-transient — queueing would
        # never clear them, so over-budget submissions SHED
        if (cfg.session_task_seconds or cfg.session_shuffle_bytes
                or cfg.session_host_bytes):
            meter = self._session_meter_row(session_id)
            if cfg.session_task_seconds and float(
                    meter.get("task_seconds") or 0.0) >= \
                    cfg.session_task_seconds:
                return shed("session-task-seconds")
            if cfg.session_shuffle_bytes and int(
                    meter.get("bytes_shuffled") or 0) >= \
                    cfg.session_shuffle_bytes:
                return shed("session-shuffle-bytes")
            if cfg.session_host_bytes and int(
                    meter.get("peak_host_bytes") or 0) >= \
                    cfg.session_host_bytes:
                return shed("session-host-bytes")
        # LOCK ORDER: the cluster-load probe takes the STATE lock, so it
        # runs before the controller lock (the terminal hook holds the
        # state lock while calling into the controller — nesting the
        # other way would deadlock); load is advisory, staleness is fine
        load = self._cluster_load() if cfg.saturation_tasks else 0
        with self._lock:
            if cfg.max_session_jobs and \
                    self._session_jobs.get(session_id, 0) >= \
                    cfg.max_session_jobs:
                return queued("session-concurrency")
            if cfg.max_running_jobs and \
                    len(self._active_session) >= cfg.max_running_jobs:
                return queued("cluster-concurrency")
            if cfg.saturation_tasks and load >= cfg.saturation_tasks:
                return queued("saturated")
            # check-and-reserve is ONE critical section: two racing
            # gates must not both admit past the same quota
            return self._reserve(Decision("admit", job_id, session_id,
                                          config=cfg))

    def _reserve(self, d: Decision) -> Decision:
        """Take the admitted job's concurrency slot (re-entrant lock:
        callers may already hold it)."""
        with self._lock:
            self.admitted_total += 1
            self._active_session[d.job_id] = d.session_id
            self._session_jobs[d.session_id] = \
                self._session_jobs.get(d.session_id, 0) + 1
        return d

    # -- bookkeeping ---------------------------------------------------------

    def _record(self, d: Decision) -> None:
        from ..observability.tracing import trace_event

        # admits reserved their slot inside the decision's critical
        # section (_reserve); only the counters remain here
        if d.action == "queue":
            with self._lock:
                self.queued_total += 1
        elif d.action == "shed":
            with self._lock:
                self.sheds_total += 1
        row = {
            "job_id": d.job_id, "session_id": d.session_id,
            "decision": d.action, "reason": d.reason or None,
            "priority": d.config.priority,
            "cluster_load": None, "queue_wait_seconds": None,
            "retry_after_seconds": d.retry_after_secs or None,
            "decided_at": time.time(),
        }
        if d.action != "admit":
            # only pressure decisions pay for the load snapshot
            row["cluster_load"] = self._cluster_load()
        with self._lock:
            self._decisions.append(row)
        try:
            trace_event(f"admission.{d.action}", job=d.job_id,
                        session=d.session_id[:12], reason=d.reason)
        except Exception:  # noqa: BLE001 - observability only
            pass
        if d.action != "admit":
            log.warning("admission %s job %s (session %s): %s",
                        d.action.upper(), d.job_id, d.session_id[:12],
                        d.reason)

    def enqueue(self, decision: Decision, args: tuple) -> None:
        """Attach an accepted-but-queued submission's planning args.
        The queue SLOT was already reserved inside the gate's critical
        section (the depth bound must be atomic with the decision);
        direct-constructed decisions (tests, tools) are inserted here."""
        with self._lock:
            decision.args = args
            # dedup by job_id, not identity: a repeated recovery pass
            # rebuilds fresh Decision objects for jobs already waiting
            if not any(d.job_id == decision.job_id for d in self._queue):
                self._queue.append(decision)
                self._sort_locked()

    def restore_admitted(self, job_id: str, session_id: str) -> None:
        """Restart recovery: re-occupy the concurrency slot of a job
        that was ADMITTED before the previous scheduler died (in-flight
        or replayed planning), so post-restart pumping still honors
        ``max_running_jobs``/``max_session_jobs`` and the job's terminal
        transition releases a slot that actually exists."""
        with self._lock:
            if job_id in self._active_session:
                return
            self._active_session[job_id] = session_id
            self._session_jobs[session_id] = \
                self._session_jobs.get(session_id, 0) + 1

    def _sort_locked(self) -> None:
        # priority (higher first), then server-side deadline (sooner
        # first — a job with less time left must not rot behind
        # deadline-less work), then arrival order
        self._queue.sort(key=lambda d: (
            -d.config.priority,
            d.deadline_ts if d.deadline_ts is not None else float("inf"),
            d.enqueued_at,
        ))

    def on_terminal(self, job_id: str) -> None:
        """Terminal-transition hook (every admitted OR queued job):
        release the session's concurrency slot and drop any queue entry
        (a cancelled/deadline-reaped queued job must leave the queue)."""
        with self._lock:
            session = self._active_session.pop(job_id, None)
            if session is not None:
                n = self._session_jobs.get(session, 0) - 1
                if n > 0:
                    self._session_jobs[session] = n
                else:
                    self._session_jobs.pop(session, None)
            self._queue = [d for d in self._queue if d.job_id != job_id]

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def admittable_queue_depth(self) -> int:
        """Queued entries that added cluster capacity could actually
        admit. A job queued behind its OWN session's concurrency quota
        (``admission.max_session_jobs``) stays queued no matter how
        many executors join — counting it would make the autoscaler
        buy machines a single tenant's quota forbids it from using.
        Walked in pop order with virtual slots: once a session's
        running + admittable-queued jobs reach its quota, the rest of
        that session's backlog is invisible to scaling."""
        with self._lock:
            virtual = dict(self._session_jobs)
            n = 0
            for d in self._queue:
                cfg = d.config
                cap = cfg.max_session_jobs if cfg is not None else 0
                if cap and virtual.get(d.session_id, 0) >= cap:
                    continue
                virtual[d.session_id] = virtual.get(d.session_id, 0) + 1
                n += 1
            return n

    def queue_info(self, job_id: str) -> Optional[dict]:
        """Queue position (1-based, in pop order) + reason + wait so
        far, or None when the job is not admission-queued."""
        now = time.time()
        with self._lock:
            for i, d in enumerate(self._queue):
                if d.job_id == job_id:
                    return {
                        "queue_position": i + 1,
                        "reason": d.reason,
                        "queued_seconds": round(now - d.enqueued_at, 3),
                        "recovered": d.recovered,
                    }
        return None

    def decision_rows(self) -> List[dict]:
        """``system.admission``: recent decisions, oldest first."""
        with self._lock:
            return [dict(r) for r in self._decisions]

    def begin_drain(self) -> None:
        """Overload/shutdown degradation: reject every NEW submission
        (shed, reason=draining) while admitted work — including already
        queued submissions — finishes normally."""
        with self._lock:
            self.draining = True
        log.warning("admission plane draining: new submissions are shed")

    # -- the pump ------------------------------------------------------------

    def pump(self, force: bool = False) -> None:
        """Advance the queue: shed expired entries, launch entries the
        current load/concurrency allows. Called from PollWork and
        GetJobStatus (throttled) and from every terminal transition
        (forced) — the same piggyback cadence the reap pass rides, so
        the queue drains even with zero executors polling."""
        now = time.time()
        with self._lock:
            if not self._queue:
                return
            if not force and now - self._last_pump < \
                    self.PUMP_INTERVAL_SECS:
                return
            self._last_pump = now
        try:
            fault_point("scheduler.admission_queue",
                        depth=self.queue_depth())
        except FaultInjected:
            # transient by contract: the queue entry is untouched and
            # the next pump retries — a queue fault may DELAY dispatch,
            # never lose or hang a submission
            log.warning("admission queue pump fault injected; will "
                        "retry on the next pump")
            return
        to_shed: List[Decision] = []
        to_launch: List[Decision] = []
        # LOCK ORDER: the load probe takes the state lock — before the
        # controller lock (see gate); one snapshot serves the round
        load = self._cluster_load()
        with self._lock:
            keep: List[Decision] = []
            for d in self._queue:
                timeout = d.config.queue_timeout_secs
                if timeout and now - d.enqueued_at >= timeout:
                    to_shed.append(d)
                else:
                    keep.append(d)
            self._queue = keep
            self._sort_locked()
            # admission scan in pop order: entries whose own limits
            # (the submitting client's knobs govern, like adaptive.*)
            # still block are SKIPPED, not waited behind — a session at
            # its quota must not convoy other sessions' ready work
            remaining: List[Decision] = []
            for d in self._queue:
                cfg = d.config
                if d.args is None:
                    # slot reserved by the gate but ExecuteQuery hasn't
                    # attached the planning args yet: not launchable
                    # for a few microseconds — leave it
                    remaining.append(d)
                    continue
                blocked = (
                    (cfg.max_session_jobs and
                     self._session_jobs.get(d.session_id, 0) >=
                     cfg.max_session_jobs)
                    or (cfg.max_running_jobs and
                        len(self._active_session) >=
                        cfg.max_running_jobs)
                    or (cfg.saturation_tasks and
                        load >= cfg.saturation_tasks)
                )
                if blocked:
                    remaining.append(d)
                    continue
                self._active_session[d.job_id] = d.session_id
                self._session_jobs[d.session_id] = \
                    self._session_jobs.get(d.session_id, 0) + 1
                self.admitted_total += 1
                to_launch.append(d)
            self._queue = remaining
        # state transitions OUTSIDE the lock: both paths re-enter the
        # scheduler (shed saves a terminal status whose hook calls
        # on_terminal; launch spawns the planning thread)
        for d in to_shed:
            self._shed_queued(d, now)
        for d in to_launch:
            self._launch_queued(d, now)

    def _observe_wait(self, d: Decision, now: float, outcome: str) -> None:
        from ..observability.registry import observe_histogram
        from ..observability.tracing import trace_event

        wait = max(now - d.enqueued_at, 0.0)
        try:
            observe_histogram("ballista_admission_queue_wait_seconds",
                              {"outcome": outcome}, wait)
        except Exception:  # noqa: BLE001 - observability only
            pass
        with self._lock:
            self._decisions.append({
                "job_id": d.job_id, "session_id": d.session_id,
                "decision": outcome, "reason": d.reason or None,
                "priority": d.config.priority, "cluster_load": None,
                "queue_wait_seconds": round(wait, 3),
                "retry_after_seconds": d.retry_after_secs or None,
                "decided_at": now,
            })
        try:
            trace_event(f"admission.{outcome}", job=d.job_id,
                        session=d.session_id[:12],
                        queue_wait_seconds=round(wait, 3))
        except Exception:  # noqa: BLE001 - observability only
            pass

    def _shed_queued(self, d: Decision, now: float) -> None:
        with self._lock:
            self.sheds_total += 1
        d.reason = "queue-timeout"
        self._observe_wait(d, now, "shed")
        log.warning("admission queue timeout: shedding job %s after "
                    "%.1fs", d.job_id, now - d.enqueued_at)
        if self.shed_fn is not None:
            try:
                self.shed_fn(d)
            except Exception:  # noqa: BLE001 - must not kill the pump
                log.exception("queued-job shed failed for %s", d.job_id)

    def _job_is_terminal(self, job_id: str) -> bool:
        st = self._state
        if st is None:
            return False
        try:
            js = st.get_job_status(job_id)
        except Exception:  # noqa: BLE001 - advisory
            return False
        return js is not None and js.state in ("completed", "failed",
                                               "cancelled")

    def _launch_queued(self, d: Decision, now: float) -> None:
        if self._job_is_terminal(d.job_id):
            # a cancel/deadline raced the enqueue (its terminal hook
            # found no queue entry yet): the job must not launch, and
            # the slot just reserved for it must be released — a leaked
            # slot would deny the session forever
            log.info("queued job %s went terminal before admission; "
                     "dropping", d.job_id)
            self.on_terminal(d.job_id)
            return
        self._observe_wait(d, now, "admitted")
        if self.queue_wait_fn is not None:
            try:
                self.queue_wait_fn(d.job_id,
                                   max(now - d.enqueued_at, 0.0))
            except Exception:  # noqa: BLE001 - ledger is advisory
                pass
        log.info("admitting queued job %s after %.1fs (reason was %s)",
                 d.job_id, now - d.enqueued_at, d.reason)
        if self.launch_fn is not None:
            try:
                self.launch_fn(d.args)
            except Exception:  # noqa: BLE001 - surface as job failure
                # the job would otherwise sit status=queued forever
                # with its slot held: release the slot and shed it as
                # a retryable failure the waiting client sees
                log.exception("queued-job launch failed for %s",
                              d.job_id)
                self.on_terminal(d.job_id)
                d.reason = "launch-error"
                if self.shed_fn is not None:
                    try:
                        self.shed_fn(d)
                    except Exception:  # noqa: BLE001 - best-effort
                        log.exception("launch-failure shed failed for "
                                      "%s", d.job_id)
