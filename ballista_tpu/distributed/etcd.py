"""etcd v3 HA state backend + an in-process fake etcd for tests.

The reference's HA story is an etcd backend with get/prefix/
put-with-lease and a distributed lock at /ballista_global_lock
(reference: rust/scheduler/src/state/etcd.rs:29-113). ``EtcdBackend``
speaks the same etcd v3 gRPC wire protocol (etcdserverpb.KV/Lease +
v3lockpb.Lock — see proto/etcd.proto, field numbers match etcd's).

HA model: ONE active scheduler + warm standbys. All durable state
(jobs, stages, tasks, executor metadata) lives in etcd, so a standby
started against the same namespace rehydrates and takes over after the
active dies. Active-ACTIVE scheduling is NOT supported: the event-driven
ready-queue is per-process (the reference achieves active-active only by
re-scanning every task under the global etcd lock on each poll —
state/mod.rs:182-260 — the very pattern this engine replaced for
scalability). The distributed lock below serves takeover/maintenance
sections; while held, a background LeaseKeepAlive stream renews the
lease so critical sections may exceed the TTL, and a keepalive failure
fails the section loudly instead of silently losing mutual exclusion.

No etcd binary ships in this environment, so tests run against
``FakeEtcdServer`` — an in-process implementation of the same four
services on the same wire protocol (the pattern the reference uses for
its scheduler tests: real service objects, direct or localhost calls).
"""

from __future__ import annotations

import threading
import time
from concurrent import futures
from typing import Dict, List, Optional, Tuple

import grpc

from ..proto import etcd_pb2 as epb
from .state import KvBackend

LOCK_NAME = b"/ballista_global_lock"  # reference: etcd.rs:93
_KV = "etcdserverpb.KV"
_LEASE = "etcdserverpb.Lease"
_LOCK = "v3lockpb.Lock"


def prefix_range_end(prefix: bytes) -> bytes:
    """etcd prefix convention: end = prefix with its last byte + 1."""
    b = bytearray(prefix)
    for i in reversed(range(len(b))):
        if b[i] < 0xFF:
            b[i] += 1
            return bytes(b[: i + 1])
    return b"\0"  # all-0xff prefix: scan to the end of keyspace


class EtcdBackend(KvBackend):
    """KvBackend over the etcd v3 API (first URL of ``urls`` is used)."""

    def __init__(self, urls: str, lock_ttl_secs: int = 15):
        target = urls.split(",")[0].strip()
        if "://" in target:
            target = target.split("://", 1)[1]
        self.channel = grpc.insecure_channel(target)
        self._lock_ttl = lock_ttl_secs
        # key -> lease id of the previous leased put, revoked on renewal
        # so heartbeat writes don't accrue orphan leases until TTL.
        # Leased puts serialize PER KEY (the race is per-key; a global
        # lock would convoy every executor's heartbeat behind ~3 etcd
        # RPCs of whichever arrived first); _key_leases_mu only guards
        # the lock-table itself
        self._key_leases: Dict[str, int] = {}
        self._key_locks: Dict[str, threading.Lock] = {}
        self._key_leases_mu = threading.Lock()

        def stub(service, method, resp_t):
            return self.channel.unary_unary(
                f"/{service}/{method}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_t.FromString,
            )

        self._range = stub(_KV, "Range", epb.RangeResponse)
        self._put = stub(_KV, "Put", epb.PutResponse)
        self._delete = stub(_KV, "DeleteRange", epb.DeleteRangeResponse)
        self._grant = stub(_LEASE, "LeaseGrant", epb.LeaseGrantResponse)
        self._revoke = stub(_LEASE, "LeaseRevoke", epb.LeaseRevokeResponse)
        self._keepalive = self.channel.stream_stream(
            f"/{_LEASE}/LeaseKeepAlive",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=epb.LeaseKeepAliveResponse.FromString,
        )
        self._lock = stub(_LOCK, "Lock", epb.LockResponse)
        self._unlock = stub(_LOCK, "Unlock", epb.UnlockResponse)

    # -- KvBackend -----------------------------------------------------------

    def get(self, key: str) -> Optional[bytes]:
        resp = self._range(epb.RangeRequest(key=key.encode()))
        return resp.kvs[0].value if resp.kvs else None

    def get_from_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        p = prefix.encode()
        resp = self._range(
            epb.RangeRequest(key=p, range_end=prefix_range_end(p))
        )
        return [(kv.key.decode(), kv.value) for kv in resp.kvs]

    def put(self, key: str, value: bytes, lease_secs: Optional[int] = None):
        if not lease_secs:
            self._put(epb.PutRequest(key=key.encode(), value=value))
            return
        # etcd lease TTLs are fixed at grant time (extending needs the
        # streaming KeepAlive RPC), so each leased write re-grants and
        # revokes the key's PREVIOUS lease to avoid accumulation. The
        # whole grant+put+record+revoke sequence is serialized per key:
        # two interleaved puts of the SAME key could otherwise record
        # the live lease as "old" and revoke it, deleting the key and
        # making the executor look dead until its next heartbeat.
        with self._key_leases_mu:
            klock = self._key_locks.setdefault(key, threading.Lock())
        with klock:
            lease_id = self._grant(
                epb.LeaseGrantRequest(TTL=lease_secs)
            ).ID
            self._put(epb.PutRequest(key=key.encode(), value=value,
                                     lease=lease_id))
            old = self._key_leases.get(key)
            self._key_leases[key] = lease_id
            if old:
                self._revoke(epb.LeaseRevokeRequest(ID=old))

    def delete(self, key: str):
        self._delete(epb.DeleteRangeRequest(key=key.encode()))

    def lock(self):
        """Distributed lock whose lease is KEPT ALIVE while held: a
        background thread runs the LeaseKeepAlive stream so critical
        sections longer than the TTL don't silently lose mutual
        exclusion. If the keepalive cannot reach etcd (or etcd reports
        the lease gone), mutual exclusion is no longer guaranteed — the
        section fails LOUDLY: ``held()`` flips False and ``__exit__``
        raises ClusterError instead of pretending the work was safe."""
        backend = self

        class _DistributedLock:
            def held(self_inner) -> bool:
                """True while mutual exclusion is still guaranteed. A
                keepalive ACK older than the TTL counts as lost even if
                the stream hasn't errored — a black-holed connection
                blocks in the read for TCP-retransmit timescales while
                the lease expires server-side."""
                if self_inner._lost.is_set():
                    return False
                if time.time() - self_inner._last_ack[0] > backend._lock_ttl:
                    self_inner._lost.set()
                    return False
                return True

            def __enter__(self_inner):
                lease = backend._grant(
                    epb.LeaseGrantRequest(TTL=backend._lock_ttl)
                ).ID
                self_inner._lease = lease
                self_inner._stop = threading.Event()
                self_inner._lost = threading.Event()
                self_inner._last_ack = [time.time()]
                interval = max(backend._lock_ttl / 3.0, 0.5)

                def keepalive():
                    stop = self_inner._stop

                    def requests():
                        while not stop.is_set():
                            yield epb.LeaseKeepAliveRequest(ID=lease)
                            stop.wait(interval)

                    try:
                        for resp in backend._keepalive(requests()):
                            if stop.is_set():
                                return
                            if resp.TTL <= 0:  # etcd: lease is gone
                                self_inner._lost.set()
                                return
                            self_inner._last_ack[0] = time.time()
                    except Exception:  # noqa: BLE001 - stream died
                        if not stop.is_set():
                            self_inner._lost.set()

                # keepalive starts BEFORE the Lock RPC: a contended
                # acquisition can wait behind the current holder for
                # longer than the TTL, and the lease must survive the
                # wait or etcd fails/poisons the acquisition
                self_inner._ka = threading.Thread(
                    target=keepalive, daemon=True, name="etcd-lock-keepalive"
                )
                self_inner._ka.start()
                try:
                    self_inner._key = backend._lock(
                        epb.LockRequest(name=LOCK_NAME, lease=lease)
                    ).key
                except Exception:
                    self_inner._stop.set()
                    backend._revoke(epb.LeaseRevokeRequest(ID=lease))
                    raise
                self_inner._last_ack[0] = time.time()
                return self_inner

            def __exit__(self_inner, *exc):
                still_held = self_inner.held()  # evaluate BEFORE teardown
                self_inner._stop.set()
                try:
                    backend._unlock(epb.UnlockRequest(key=self_inner._key),
                                    timeout=5.0)
                    backend._revoke(
                        epb.LeaseRevokeRequest(ID=self_inner._lease),
                        timeout=5.0)
                except Exception:  # noqa: BLE001 - etcd may be gone
                    pass
                self_inner._ka.join(timeout=2.0)
                if not still_held and exc == (None, None, None):
                    from ..errors import ClusterError

                    raise ClusterError(
                        "etcd lock lease was lost while held (keepalive "
                        "failed or TTL expired): the critical section ran "
                        "WITHOUT mutual exclusion and must not be trusted"
                    )
                return False

        return _DistributedLock()

    def close(self):
        self.channel.close()


# ---------------------------------------------------------------------------
# In-process fake etcd (tests / single-host development)
# ---------------------------------------------------------------------------


class _FakeEtcdState:
    def __init__(self):
        self.kv: Dict[bytes, Tuple[bytes, int]] = {}  # key -> (value, lease)
        self.leases: Dict[int, float] = {}  # id -> expiry
        self.lease_ttls: Dict[int, int] = {}  # id -> granted TTL (keepalive)
        self.next_lease = 1
        self.mu = threading.Lock()
        self.lock_mu = threading.Lock()  # the global lock itself

    def alive(self, lease_id: int) -> bool:
        if lease_id == 0:
            return True
        exp = self.leases.get(lease_id)
        return exp is not None and time.time() <= exp


class FakeEtcdServer:
    """Implements the KV/Lease/Lock subset on the real wire protocol."""

    def __init__(self, host: str = "localhost", port: int = 0):
        st = self._st = _FakeEtcdState()

        def Range(req: epb.RangeRequest, ctx=None):
            resp = epb.RangeResponse()
            with st.mu:
                if req.range_end == b"\0":
                    # etcd convention: range_end "\0" = to keyspace end
                    keys = sorted(k for k in st.kv if k >= req.key)
                elif req.range_end:
                    keys = sorted(
                        k for k in st.kv
                        if req.key <= k < req.range_end
                    )
                else:
                    keys = [req.key] if req.key in st.kv else []
                for k in keys:
                    v, lease = st.kv[k]
                    if not st.alive(lease):
                        continue
                    resp.kvs.add(key=k, value=v, lease=lease)
            resp.count = len(resp.kvs)
            return resp

        def Put(req: epb.PutRequest, ctx=None):
            with st.mu:
                st.kv[req.key] = (req.value, req.lease)
            return epb.PutResponse()

        def DeleteRange(req: epb.DeleteRangeRequest, ctx=None):
            resp = epb.DeleteRangeResponse()
            with st.mu:
                if req.range_end:
                    doomed = [k for k in st.kv
                              if req.key <= k < req.range_end]
                else:
                    doomed = [req.key] if req.key in st.kv else []
                for k in doomed:
                    del st.kv[k]
                resp.deleted = len(doomed)
            return resp

        def LeaseGrant(req: epb.LeaseGrantRequest, ctx=None):
            with st.mu:
                lid = req.ID or st.next_lease
                st.next_lease = max(st.next_lease, lid) + 1
                st.leases[lid] = time.time() + req.TTL
                st.lease_ttls[lid] = req.TTL
            return epb.LeaseGrantResponse(ID=lid, TTL=req.TTL)

        def LeaseRevoke(req: epb.LeaseRevokeRequest, ctx=None):
            with st.mu:
                st.leases.pop(req.ID, None)
                doomed = [k for k, (_, l) in st.kv.items() if l == req.ID]
                for k in doomed:
                    del st.kv[k]
            return epb.LeaseRevokeResponse()

        def LeaseKeepAlive(request_iterator, ctx=None):
            # etcd semantics: each ping extends an ALIVE lease to its
            # original TTL; a dead/unknown lease answers TTL=0.
            # (yield OUTSIDE the lock: a stalled stream consumer must
            # not pin st.mu across generator suspension)
            for req in request_iterator:
                with st.mu:
                    ttl = st.lease_ttls.get(req.ID)
                    if ttl is not None and st.alive(req.ID):
                        st.leases[req.ID] = time.time() + ttl
                        resp = epb.LeaseKeepAliveResponse(ID=req.ID, TTL=ttl)
                    else:
                        resp = epb.LeaseKeepAliveResponse(ID=req.ID, TTL=0)
                yield resp

        def Lock(req: epb.LockRequest, ctx=None):
            st.lock_mu.acquire()
            return epb.LockResponse(key=req.name + b"/held")

        def Unlock(req: epb.UnlockRequest, ctx=None):
            try:
                st.lock_mu.release()
            except RuntimeError:
                pass
            return epb.UnlockResponse()

        services = {
            _KV: {"Range": (Range, epb.RangeRequest),
                  "Put": (Put, epb.PutRequest),
                  "DeleteRange": (DeleteRange, epb.DeleteRangeRequest)},
            _LEASE: {"LeaseGrant": (LeaseGrant, epb.LeaseGrantRequest),
                     "LeaseRevoke": (LeaseRevoke, epb.LeaseRevokeRequest)},
            _LOCK: {"Lock": (Lock, epb.LockRequest),
                    "Unlock": (Unlock, epb.UnlockRequest)},
        }
        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        for service, methods in services.items():
            handlers = {
                name: grpc.unary_unary_rpc_method_handler(
                    fn,
                    request_deserializer=req_t.FromString,
                    response_serializer=lambda m: m.SerializeToString(),
                )
                for name, (fn, req_t) in methods.items()
            }
            if service == _LEASE:
                handlers["LeaseKeepAlive"] = grpc.stream_stream_rpc_method_handler(
                    LeaseKeepAlive,
                    request_deserializer=epb.LeaseKeepAliveRequest.FromString,
                    response_serializer=lambda m: m.SerializeToString(),
                )
            self.server.add_generic_rpc_handlers(
                (grpc.method_handlers_generic_handler(service, handlers),)
            )
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        self.server.start()

    def stop(self):
        self.server.stop(grace=None)
