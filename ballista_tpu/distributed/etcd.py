"""etcd v3 HA state backend + an in-process fake etcd for tests.

The reference's HA story is an etcd backend with get/prefix/
put-with-lease and a distributed lock at /ballista_global_lock
(reference: rust/scheduler/src/state/etcd.rs:29-113). ``EtcdBackend``
speaks the same etcd v3 gRPC wire protocol (etcdserverpb.KV/Lease +
v3lockpb.Lock — see proto/etcd.proto, field numbers match etcd's).

HA model: ONE active scheduler + warm standbys. All durable state
(jobs, stages, tasks, executor metadata) lives in etcd, so a standby
started against the same namespace rehydrates and takes over after the
active dies. Active-ACTIVE scheduling is NOT supported: the event-driven
ready-queue is per-process (the reference achieves active-active only by
re-scanning every task under the global etcd lock on each poll —
state/mod.rs:182-260 — the very pattern this engine replaced for
scalability). The distributed lock below serves takeover/maintenance
sections, and critical sections must stay under the lock lease TTL
(no keepalive stream is implemented).

No etcd binary ships in this environment, so tests run against
``FakeEtcdServer`` — an in-process implementation of the same four
services on the same wire protocol (the pattern the reference uses for
its scheduler tests: real service objects, direct or localhost calls).
"""

from __future__ import annotations

import threading
import time
from concurrent import futures
from typing import Dict, List, Optional, Tuple

import grpc

from ..proto import etcd_pb2 as epb
from .state import KvBackend

LOCK_NAME = b"/ballista_global_lock"  # reference: etcd.rs:93
_KV = "etcdserverpb.KV"
_LEASE = "etcdserverpb.Lease"
_LOCK = "v3lockpb.Lock"


def prefix_range_end(prefix: bytes) -> bytes:
    """etcd prefix convention: end = prefix with its last byte + 1."""
    b = bytearray(prefix)
    for i in reversed(range(len(b))):
        if b[i] < 0xFF:
            b[i] += 1
            return bytes(b[: i + 1])
    return b"\0"  # all-0xff prefix: scan to the end of keyspace


class EtcdBackend(KvBackend):
    """KvBackend over the etcd v3 API (first URL of ``urls`` is used)."""

    def __init__(self, urls: str, lock_ttl_secs: int = 15):
        target = urls.split(",")[0].strip()
        if "://" in target:
            target = target.split("://", 1)[1]
        self.channel = grpc.insecure_channel(target)
        self._lock_ttl = lock_ttl_secs
        # key -> lease id of the previous leased put, revoked on renewal
        # so heartbeat writes don't accrue orphan leases until TTL.
        # Leased puts serialize PER KEY (the race is per-key; a global
        # lock would convoy every executor's heartbeat behind ~3 etcd
        # RPCs of whichever arrived first); _key_leases_mu only guards
        # the lock-table itself
        self._key_leases: Dict[str, int] = {}
        self._key_locks: Dict[str, threading.Lock] = {}
        self._key_leases_mu = threading.Lock()

        def stub(service, method, resp_t):
            return self.channel.unary_unary(
                f"/{service}/{method}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_t.FromString,
            )

        self._range = stub(_KV, "Range", epb.RangeResponse)
        self._put = stub(_KV, "Put", epb.PutResponse)
        self._delete = stub(_KV, "DeleteRange", epb.DeleteRangeResponse)
        self._grant = stub(_LEASE, "LeaseGrant", epb.LeaseGrantResponse)
        self._revoke = stub(_LEASE, "LeaseRevoke", epb.LeaseRevokeResponse)
        self._lock = stub(_LOCK, "Lock", epb.LockResponse)
        self._unlock = stub(_LOCK, "Unlock", epb.UnlockResponse)

    # -- KvBackend -----------------------------------------------------------

    def get(self, key: str) -> Optional[bytes]:
        resp = self._range(epb.RangeRequest(key=key.encode()))
        return resp.kvs[0].value if resp.kvs else None

    def get_from_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        p = prefix.encode()
        resp = self._range(
            epb.RangeRequest(key=p, range_end=prefix_range_end(p))
        )
        return [(kv.key.decode(), kv.value) for kv in resp.kvs]

    def put(self, key: str, value: bytes, lease_secs: Optional[int] = None):
        if not lease_secs:
            self._put(epb.PutRequest(key=key.encode(), value=value))
            return
        # etcd lease TTLs are fixed at grant time (extending needs the
        # streaming KeepAlive RPC), so each leased write re-grants and
        # revokes the key's PREVIOUS lease to avoid accumulation. The
        # whole grant+put+record+revoke sequence is serialized per key:
        # two interleaved puts of the SAME key could otherwise record
        # the live lease as "old" and revoke it, deleting the key and
        # making the executor look dead until its next heartbeat.
        with self._key_leases_mu:
            klock = self._key_locks.setdefault(key, threading.Lock())
        with klock:
            lease_id = self._grant(
                epb.LeaseGrantRequest(TTL=lease_secs)
            ).ID
            self._put(epb.PutRequest(key=key.encode(), value=value,
                                     lease=lease_id))
            old = self._key_leases.get(key)
            self._key_leases[key] = lease_id
            if old:
                self._revoke(epb.LeaseRevokeRequest(ID=old))

    def delete(self, key: str):
        self._delete(epb.DeleteRangeRequest(key=key.encode()))

    def lock(self):
        backend = self

        class _DistributedLock:
            def __enter__(self_inner):
                lease = backend._grant(
                    epb.LeaseGrantRequest(TTL=backend._lock_ttl)
                ).ID
                self_inner._lease = lease
                try:
                    self_inner._key = backend._lock(
                        epb.LockRequest(name=LOCK_NAME, lease=lease)
                    ).key
                except Exception:
                    backend._revoke(epb.LeaseRevokeRequest(ID=lease))
                    raise
                return self_inner

            def __exit__(self_inner, *exc):
                backend._unlock(epb.UnlockRequest(key=self_inner._key))
                backend._revoke(epb.LeaseRevokeRequest(ID=self_inner._lease))
                return False

        return _DistributedLock()

    def close(self):
        self.channel.close()


# ---------------------------------------------------------------------------
# In-process fake etcd (tests / single-host development)
# ---------------------------------------------------------------------------


class _FakeEtcdState:
    def __init__(self):
        self.kv: Dict[bytes, Tuple[bytes, int]] = {}  # key -> (value, lease)
        self.leases: Dict[int, float] = {}  # id -> expiry
        self.next_lease = 1
        self.mu = threading.Lock()
        self.lock_mu = threading.Lock()  # the global lock itself

    def alive(self, lease_id: int) -> bool:
        if lease_id == 0:
            return True
        exp = self.leases.get(lease_id)
        return exp is not None and time.time() <= exp


class FakeEtcdServer:
    """Implements the KV/Lease/Lock subset on the real wire protocol."""

    def __init__(self, host: str = "localhost", port: int = 0):
        st = self._st = _FakeEtcdState()

        def Range(req: epb.RangeRequest, ctx=None):
            resp = epb.RangeResponse()
            with st.mu:
                if req.range_end == b"\0":
                    # etcd convention: range_end "\0" = to keyspace end
                    keys = sorted(k for k in st.kv if k >= req.key)
                elif req.range_end:
                    keys = sorted(
                        k for k in st.kv
                        if req.key <= k < req.range_end
                    )
                else:
                    keys = [req.key] if req.key in st.kv else []
                for k in keys:
                    v, lease = st.kv[k]
                    if not st.alive(lease):
                        continue
                    resp.kvs.add(key=k, value=v, lease=lease)
            resp.count = len(resp.kvs)
            return resp

        def Put(req: epb.PutRequest, ctx=None):
            with st.mu:
                st.kv[req.key] = (req.value, req.lease)
            return epb.PutResponse()

        def DeleteRange(req: epb.DeleteRangeRequest, ctx=None):
            resp = epb.DeleteRangeResponse()
            with st.mu:
                if req.range_end:
                    doomed = [k for k in st.kv
                              if req.key <= k < req.range_end]
                else:
                    doomed = [req.key] if req.key in st.kv else []
                for k in doomed:
                    del st.kv[k]
                resp.deleted = len(doomed)
            return resp

        def LeaseGrant(req: epb.LeaseGrantRequest, ctx=None):
            with st.mu:
                lid = req.ID or st.next_lease
                st.next_lease = max(st.next_lease, lid) + 1
                st.leases[lid] = time.time() + req.TTL
            return epb.LeaseGrantResponse(ID=lid, TTL=req.TTL)

        def LeaseRevoke(req: epb.LeaseRevokeRequest, ctx=None):
            with st.mu:
                st.leases.pop(req.ID, None)
                doomed = [k for k, (_, l) in st.kv.items() if l == req.ID]
                for k in doomed:
                    del st.kv[k]
            return epb.LeaseRevokeResponse()

        def Lock(req: epb.LockRequest, ctx=None):
            st.lock_mu.acquire()
            return epb.LockResponse(key=req.name + b"/held")

        def Unlock(req: epb.UnlockRequest, ctx=None):
            try:
                st.lock_mu.release()
            except RuntimeError:
                pass
            return epb.UnlockResponse()

        services = {
            _KV: {"Range": (Range, epb.RangeRequest),
                  "Put": (Put, epb.PutRequest),
                  "DeleteRange": (DeleteRange, epb.DeleteRangeRequest)},
            _LEASE: {"LeaseGrant": (LeaseGrant, epb.LeaseGrantRequest),
                     "LeaseRevoke": (LeaseRevoke, epb.LeaseRevokeRequest)},
            _LOCK: {"Lock": (Lock, epb.LockRequest),
                    "Unlock": (Unlock, epb.UnlockRequest)},
        }
        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        for service, methods in services.items():
            handlers = {
                name: grpc.unary_unary_rpc_method_handler(
                    fn,
                    request_deserializer=req_t.FromString,
                    response_serializer=lambda m: m.SerializeToString(),
                )
                for name, (fn, req_t) in methods.items()
            }
            self.server.add_generic_rpc_handlers(
                (grpc.method_handlers_generic_handler(service, handlers),)
            )
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        self.server.start()

    def stop(self):
        self.server.stop(grace=None)
