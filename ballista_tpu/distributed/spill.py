"""Shuffle memory governor + disk spill for the streaming data plane.

The shuffle data plane moves partition bytes as bounded Arrow-IPC
chunks (``BALLISTA_SHUFFLE_CHUNK_BYTES``) instead of whole-partition
blobs. This module makes the memory those chunks occupy a *governed*
resource, the way ``compile/governor.py`` made compilation one:

- :class:`ShuffleMemoryGovernor` — one per-process accountant. Every
  in-flight shuffle buffer byte (fetched-but-not-yet-decoded wire
  chunks, writer-side Arrow conversion buffers) is charged against
  ``BALLISTA_SHUFFLE_MEM_BUDGET``; ``try_charge`` refuses past the
  ``BALLISTA_SHUFFLE_SPILL_WATERMARK`` fraction of the budget.
- :class:`SpillPool` — size-rotated append-only spill files
  (``BALLISTA_SHUFFLE_SPILL_FILE_MB`` per segment) under
  ``BALLISTA_SHUFFLE_SPILL_DIR``. Segments are reference-counted and
  unlinked once rotated out and fully released.
- :class:`ChunkBuffer` — one in-flight shuffle part's chunk queue.
  ``put`` keeps chunks in RAM while the governor grants budget and
  diverts to the spill pool past the watermark (the ingest pool's
  cancel-or-inline philosophy: a saturated budget degrades to
  streaming-from-disk, it never blocks); ``chunks`` replays them in
  arrival order with transparent re-read, releasing as it goes.

Failure semantics: a truncated or short spill segment read raises an
IoError-shaped :class:`SpillCorrupt`; shuffle readers tag it into the
existing ``ShuffleFetchError`` so ``recover_fetch_failure`` re-queues
the producer exactly like a dead peer. Fault point
``shuffle.spill.write`` covers the spill write (``drop`` = torn write:
only half the payload reaches disk, simulating a crash mid-append).

Knob reads are dynamic (per part, not per chunk) so tests and bench
can re-point the budget without process restarts.
"""

from __future__ import annotations

import os
import threading
from typing import Iterator, Optional

from ..errors import IoError
from ..observability import memory as obs_memory
from ..observability.tracing import trace_span
from ..testing.faults import fault_point


class SpillCorrupt(IoError):
    """A spill segment read came back short or misaligned (torn write,
    external truncation, disk fault). IoError-shaped: shuffle readers
    wrap it into the tagged ShuffleFetchError recovery path."""


def _env_int(name: str, default: int, floor: int = 1) -> int:
    try:
        return max(int(os.environ.get(name, "") or default), floor)
    except ValueError:
        return default


def shuffle_chunk_bytes() -> int:
    """``BALLISTA_SHUFFLE_CHUNK_BYTES``: max Arrow-IPC record-batch /
    wire-frame size on the shuffle path (default 4 MiB)."""
    return _env_int("BALLISTA_SHUFFLE_CHUNK_BYTES", 4 << 20, floor=1 << 10)


def shuffle_mem_budget() -> int:
    """``BALLISTA_SHUFFLE_MEM_BUDGET``: per-process cap on in-flight
    shuffle buffer bytes (default 256 MiB)."""
    return _env_int("BALLISTA_SHUFFLE_MEM_BUDGET", 256 << 20, floor=1 << 12)


def spill_watermark() -> float:
    """``BALLISTA_SHUFFLE_SPILL_WATERMARK``: fraction of the budget past
    which new chunk buffers divert to disk (default 0.8)."""
    try:
        v = float(os.environ.get("BALLISTA_SHUFFLE_SPILL_WATERMARK",
                                 "") or 0.8)
    except ValueError:
        return 0.8
    return min(max(v, 0.01), 1.0)


def spill_file_bytes() -> int:
    """``BALLISTA_SHUFFLE_SPILL_FILE_MB``: spill segment rotation size
    (default 64 MiB)."""
    return _env_int("BALLISTA_SHUFFLE_SPILL_FILE_MB", 64, floor=1) << 20


def spill_dir() -> str:
    """``BALLISTA_SHUFFLE_SPILL_DIR``: where spill segments land
    (default: a per-process dir under the system tempdir)."""
    d = os.environ.get("BALLISTA_SHUFFLE_SPILL_DIR", "").strip()
    if d:
        return d
    import tempfile

    return os.path.join(tempfile.gettempdir(),
                        f"ballista-spill-{os.getpid()}")


def stream_window_bytes() -> int:
    """``BALLISTA_SHUFFLE_WINDOW_BYTES``: flow-control window a chunk
    stream reader advertises — the server suspends past this many
    unacked in-flight bytes per peer (default 4 chunks)."""
    return _env_int("BALLISTA_SHUFFLE_WINDOW_BYTES",
                    4 * shuffle_chunk_bytes(), floor=1 << 12)


class ShuffleMemoryGovernor:
    """Process-wide accountant for in-flight shuffle buffer bytes.

    Counters follow the engine's benign-race policy for *gauges* but the
    charge/release pair is locked — a lost update here would leak budget
    forever. The budget/watermark are read from the environment at call
    time, so one governor instance serves any knob configuration."""

    def __init__(self):
        self._lock = threading.Lock()
        self.inflight_bytes = 0
        self.peak_inflight_bytes = 0
        self.spilled_bytes_total = 0
        self.spill_chunks_total = 0
        self.denials = 0

    def try_charge(self, nbytes: int) -> bool:
        """Charge ``nbytes`` against the budget unless doing so would
        cross the spill watermark; returns whether the charge landed.
        Never blocks — a refused charge means the caller spills."""
        n = int(nbytes)
        if n <= 0:
            return True
        limit = int(shuffle_mem_budget() * spill_watermark())
        with self._lock:
            if self.inflight_bytes + n > limit:
                self.denials += 1
                return False
            self.inflight_bytes += n
            if self.inflight_bytes > self.peak_inflight_bytes:
                self.peak_inflight_bytes = self.inflight_bytes
        obs_memory.record_host_bytes("shuffle_stream", n)
        return True

    def charge(self, nbytes: int) -> None:
        """Unconditional charge (writer-side transient buffers: they are
        on their way to disk already, spilling them is meaningless)."""
        n = int(nbytes)
        if n <= 0:
            return
        with self._lock:
            self.inflight_bytes += n
            if self.inflight_bytes > self.peak_inflight_bytes:
                self.peak_inflight_bytes = self.inflight_bytes
        obs_memory.record_host_bytes("shuffle_stream", n)

    def release(self, nbytes: int) -> None:
        n = int(nbytes)
        if n <= 0:
            return
        with self._lock:
            self.inflight_bytes = max(0, self.inflight_bytes - n)
        obs_memory.release_host_bytes("shuffle_stream", n)

    def note_spill(self, nbytes: int) -> None:
        with self._lock:
            self.spilled_bytes_total += int(nbytes)
            self.spill_chunks_total += 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "inflight_bytes": self.inflight_bytes,
                "peak_inflight_bytes": self.peak_inflight_bytes,
                "spilled_bytes_total": self.spilled_bytes_total,
                "spill_chunks_total": self.spill_chunks_total,
                "denials": self.denials,
                "budget_bytes": shuffle_mem_budget(),
            }

    def reset_stats(self) -> None:
        """Re-baseline the cumulative counters (bench phases, tests).
        ``inflight_bytes`` is live accounting and is NOT reset."""
        with self._lock:
            self.peak_inflight_bytes = self.inflight_bytes
            self.spilled_bytes_total = 0
            self.spill_chunks_total = 0
            self.denials = 0


_governor = ShuffleMemoryGovernor()


def governor() -> ShuffleMemoryGovernor:
    return _governor


class _Segment:
    """One size-rotated spill file: append-only while current, unlinked
    once rotated out and every referencing chunk is released."""

    __slots__ = ("path", "size", "refs", "rotated")

    def __init__(self, path: str):
        self.path = path
        self.size = 0
        self.refs = 0
        self.rotated = False


class SpillRef:
    """Handle to one spilled chunk: (segment, offset, length).

    ``written`` records how many bytes actually reached the file at
    append time — a torn write (crash or injected fault mid-append) can
    persist fewer than ``length``, and because later chunks append at
    the file's REAL end, the torn chunk's window would otherwise read
    back the neighbor's bytes without any short read at all."""

    __slots__ = ("_pool", "_seg", "offset", "length", "written")

    def __init__(self, pool: "SpillPool", seg: _Segment, offset: int,
                 length: int, written: int):
        self._pool = pool
        self._seg = seg
        self.offset = offset
        self.length = length
        self.written = written

    def read(self) -> bytes:
        """Transparent re-read; torn writes and truncation surface as
        :class:`SpillCorrupt`, never as silently misaligned bytes."""
        if self.written != self.length:
            raise SpillCorrupt(
                f"spill segment torn: {self._seg.path} "
                f"offset={self.offset} want={self.length} "
                f"wrote={self.written}"
            )
        try:
            with open(self._seg.path, "rb") as fh:
                fh.seek(self.offset)
                data = fh.read(self.length)
        except OSError as e:
            raise SpillCorrupt(
                f"spill segment unreadable: {self._seg.path}: {e}"
            ) from e
        if len(data) != self.length:
            raise SpillCorrupt(
                f"spill segment truncated: {self._seg.path} "
                f"offset={self.offset} want={self.length} got={len(data)}"
            )
        return data

    def release(self) -> None:
        self._pool._release(self._seg)


class SpillPool:
    """Append-only spill storage in size-rotated segments.

    One process-wide instance (lazily created) serves every spilling
    ChunkBuffer; appends serialize under one lock (chunks are at most
    ``shuffle_chunk_bytes`` so the hold time is one buffered write)."""

    def __init__(self, base_dir: Optional[str] = None,
                 max_file_bytes: Optional[int] = None):
        self._dir = base_dir
        self._max = max_file_bytes
        self._lock = threading.Lock()
        self._current: Optional[_Segment] = None
        self._fh = None
        self._seq = 0
        self.segments_created = 0

    def _roll(self) -> _Segment:
        base = self._dir or spill_dir()
        os.makedirs(base, exist_ok=True)
        path = os.path.join(
            base, f"spill-{os.getpid()}-{self._seq:06d}.bin")
        self._seq += 1
        self.segments_created += 1
        if self._fh is not None:
            self._fh.close()
        if self._current is not None:
            self._current.rotated = True
            self._maybe_unlink(self._current)
        self._current = _Segment(path)
        self._fh = open(path, "wb")
        return self._current

    def append(self, data: bytes) -> SpillRef:
        """Write one chunk; returns its re-read handle. The offset is
        taken from the file's REAL position so a previous torn write
        cannot misalign later chunks."""
        action = fault_point("shuffle.spill.write", nbytes=len(data))
        if action == "drop":
            # torn write: half the payload reaches disk — the re-read
            # detects the short segment as SpillCorrupt
            data_to_write = data[: len(data) // 2]
        else:
            data_to_write = data
        with self._lock, trace_span("shuffle.spill", op="write",
                                    nbytes=len(data)):
            seg = self._current
            if seg is None or seg.size >= (self._max or spill_file_bytes()):
                seg = self._roll()
            offset = self._fh.tell()
            self._fh.write(data_to_write)
            self._fh.flush()
            seg.size = self._fh.tell()
            seg.refs += 1
            # written = real bytes on disk; a mismatch with len(data)
            # marks the ref torn so read() raises SpillCorrupt instead
            # of returning the NEXT chunk's bytes (later appends land
            # at the file's real end)
            return SpillRef(self, seg, offset, len(data),
                            written=seg.size - offset)

    def _release(self, seg: _Segment) -> None:
        with self._lock:
            seg.refs = max(0, seg.refs - 1)
            self._maybe_unlink(seg)

    def _maybe_unlink(self, seg: _Segment) -> None:
        # caller holds the lock (or is single-threaded rollover)
        if seg.rotated and seg.refs == 0:
            try:
                os.unlink(seg.path)
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            if self._current is not None:
                self._current.rotated = True
                self._maybe_unlink(self._current)
                self._current = None


_pool_lock = threading.Lock()
_pool: Optional[SpillPool] = None


def spill_pool() -> SpillPool:
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = SpillPool()
        return _pool


def _reset_pool() -> None:
    """Tests: drop the process pool so a fresh spill dir takes effect."""
    global _pool
    with _pool_lock:
        p, _pool = _pool, None
    if p is not None:
        p.close()


class ChunkBuffer:
    """One in-flight shuffle part's ordered chunk queue.

    ``put`` is called by the fetch loop per received wire chunk; chunks
    stay in RAM while the governor grants budget, and once one chunk
    spills every later chunk of this part spills too (so replay order
    is RAM-prefix then disk-suffix — always arrival order).
    ``chunks()`` is consumed exactly once by the incremental IPC
    decoder; each chunk's budget/segment is released as it is yielded.
    ``close()`` releases whatever was not consumed (error paths)."""

    __slots__ = ("_gov", "_ram", "_refs", "_spilling", "total_bytes",
                 "spilled_bytes", "_closed")

    def __init__(self, gov: Optional[ShuffleMemoryGovernor] = None):
        from collections import deque

        self._gov = gov or governor()
        self._ram: "deque[bytes]" = deque()
        self._refs: "deque[SpillRef]" = deque()
        self._spilling = False
        self.total_bytes = 0
        self.spilled_bytes = 0
        self._closed = False

    def put(self, data: bytes) -> None:
        n = len(data)
        self.total_bytes += n
        if not self._spilling and self._gov.try_charge(n):
            self._ram.append(data)
            return
        self._spilling = True
        self._refs.append(spill_pool().append(data))
        self.spilled_bytes += n
        self._gov.note_spill(n)

    def chunks(self) -> Iterator[bytes]:
        """Replay in arrival order, releasing as consumed."""
        while self._ram:
            data = self._ram.popleft()
            self._gov.release(len(data))
            yield data
        while self._refs:
            ref = self._refs.popleft()
            with trace_span("shuffle.spill", op="read", nbytes=ref.length):
                data = ref.read()
            ref.release()
            yield data

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for data in self._ram:
            self._gov.release(len(data))
        self._ram.clear()
        for ref in self._refs:
            ref.release()
        self._refs.clear()
