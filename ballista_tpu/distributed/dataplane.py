"""Shuffle data plane: socket protocol for partition fetch.

The role Arrow Flight ``do_get`` plays in the reference (reference:
rust/executor/src/flight_service.rs:193-228 FetchPartition;
rust/core/src/client.rs:123-169 fetch side). Wire format (also spoken by
the native C++ server in ballista_tpu/native/shuffle_server.cpp):

  request:  u32_be length | ballista_tpu.Action protobuf
  response: u8 status (0=ok, 1=error) | u64_be length | payload
            payload = Arrow IPC file bytes (ok) or utf-8 error message

Python server threads serve from the executor work_dir; the C++ server is a
drop-in replacement on the same protocol.
"""

from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
from typing import Optional

from ..errors import IoError
from ..proto import ballista_pb2 as pb


def partition_path(work_dir: str, job_id: str, stage_id: int,
                   partition_id: int) -> str:
    # layout mirrors the reference's work_dir/{job}/{stage}/{part}/data.arrow
    # (reference: flight_service.rs:104-126)
    return os.path.join(work_dir, job_id, str(stage_id), str(partition_id),
                        "data.arrow")


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise IoError("data plane connection closed early")
        buf.extend(chunk)
    return bytes(buf)


def fetch_partition_bytes(host: str, port: int, job_id: str, stage_id: int,
                          partition_id: int, timeout: float = 60.0) -> bytes:
    action = pb.Action()
    action.fetch_partition.job_id = job_id
    action.fetch_partition.stage_id = stage_id
    action.fetch_partition.partition_id = partition_id
    payload = action.SerializeToString()
    with socket.create_connection((host, port), timeout=timeout) as sock:
        sock.sendall(struct.pack(">I", len(payload)) + payload)
        status = _recv_exact(sock, 1)[0]
        (length,) = struct.unpack(">Q", _recv_exact(sock, 8))
        body = _recv_exact(sock, length)
    if status != 0:
        raise IoError(f"fetch failed: {body.decode(errors='replace')}")
    return body


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            (length,) = struct.unpack(">I", _recv_exact(self.request, 4))
            action = pb.Action()
            action.ParseFromString(_recv_exact(self.request, length))
            which = action.WhichOneof("action_type")
            if which != "fetch_partition":
                raise IoError(f"unsupported data-plane action {which}")
            f = action.fetch_partition
            path = partition_path(
                self.server.work_dir, f.job_id, f.stage_id, f.partition_id
            )
            if not os.path.exists(path):
                raise IoError(f"no such partition: {path}")
            with open(path, "rb") as fh:
                body = fh.read()
            self.request.sendall(struct.pack(">BQ", 0, len(body)))
            self.request.sendall(body)
        except Exception as e:  # noqa: BLE001 - report to peer
            msg = str(e).encode()
            try:
                self.request.sendall(struct.pack(">BQ", 1, len(msg)) + msg)
            except OSError:
                pass


class DataPlaneServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, host: str, port: int, work_dir: str):
        super().__init__((host, port), _Handler)
        self.work_dir = work_dir

    @property
    def port(self) -> int:
        return self.server_address[1]


def start_data_plane(host: str, port: int, work_dir: str) -> DataPlaneServer:
    server = DataPlaneServer(host, port, work_dir)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="ballista-data-plane")
    t.start()
    return server
