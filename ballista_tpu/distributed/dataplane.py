"""Shuffle data plane: socket protocol for partition fetch.

The role Arrow Flight ``do_get`` plays in the reference (reference:
rust/executor/src/flight_service.rs:193-228 FetchPartition;
rust/core/src/client.rs:123-169 fetch side). Wire format (also spoken by
the native C++ server in ballista_tpu/native/shuffle_server.cpp):

  request:  u32_be length | ballista_tpu.Action protobuf
  response: u8 status (0=ok, 1=error) | u64_be length | payload
            payload = Arrow IPC file bytes (ok) or utf-8 error message

Streaming extension (docs/shuffle.md): a request whose Action carries
``stream_window > 0`` asks for a flow-controlled chunk stream instead
of one whole-partition payload. A server that understands it (the
Python server here) answers with status byte 2 followed by frames

  u32_be n | n chunk bytes        (one bounded chunk)
  u32_be 0                        (clean end of stream)
  u32_be 0xFFFFFFFF | u32_be len | message   (mid-stream error)

and suspends once more than ``stream_window`` bytes are in flight
unacknowledged — the reader acks each consumed chunk with a bare
``u32_be n``. The native C++ daemon predates the field, skips it
(protobuf unknown-field semantics) and answers with the legacy framing;
clients consume that body in bounded chunk reads, so memory stays
bounded on either server.

Python server threads serve from the executor work_dir; the C++ server is a
drop-in replacement on the same protocol.
"""

from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
from collections import deque
from typing import Iterator, Optional

from ..errors import IoError
from ..proto import ballista_pb2 as pb

# job ids whose in-flight chunk streams must abort (the executor marks
# them on a CancelJob broadcast): the server-side stream writer checks
# per chunk, so cancellation propagates INTO mid-flight transfers
# instead of waiting for the file to finish streaming
_cancelled_lock = threading.Lock()
_cancelled_jobs: deque = deque(maxlen=256)


def mark_job_cancelled(job_id: str) -> None:
    with _cancelled_lock:
        if job_id not in _cancelled_jobs:
            _cancelled_jobs.append(job_id)


def job_stream_cancelled(job_id: str) -> bool:
    with _cancelled_lock:
        return job_id in _cancelled_jobs


def path_component_ok(s: str) -> bool:
    """Network-supplied path components must be short alnum/-/_ tokens
    (mirrors shuffle_server.cpp path_component_ok; job ids are 7-char
    alphanumeric). Rejects traversal ('..'), separators, and absolute
    paths (os.path.join would discard work_dir for those)."""
    return (
        0 < len(s) <= 128
        and all((c.isascii() and c.isalnum()) or c in "-_" for c in s)
    )


def partition_path(work_dir: str, job_id: str, stage_id: int,
                   partition_id: int) -> str:
    # layout mirrors the reference's work_dir/{job}/{stage}/{part}/data.arrow
    # (reference: flight_service.rs:104-126)
    return os.path.join(work_dir, job_id, str(stage_id), str(partition_id),
                        "data.arrow")


def shuffle_file_name(output_partition: int) -> str:
    # single source of truth for the shuffle file naming scheme (the C++
    # server mirrors it; see shuffle_server.cpp)
    return f"shuffle-{output_partition}.arrow"


def shuffle_path(work_dir: str, job_id: str, stage_id: int,
                 producer_partition: int, output_partition: int) -> str:
    # hash-shuffled stages write one file per consumer partition
    return os.path.join(work_dir, job_id, str(stage_id),
                        str(producer_partition),
                        shuffle_file_name(output_partition))


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    from ..lifecycle import check_cancel

    buf = bytearray()
    while len(buf) < n:
        # a cancelled query stops pulling between recvs even mid-frame
        # (no-op for server handler threads, which bind no token)
        check_cancel()
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise IoError("data plane connection closed early")
        buf.extend(chunk)
    return bytes(buf)


def _fetch_action(job_id: str, stage_id: int, partition_id: int,
                  shuffle_output: "int | None") -> pb.Action:
    action = pb.Action()
    if shuffle_output is not None:
        action.fetch_shuffle.producer.job_id = job_id
        action.fetch_shuffle.producer.stage_id = stage_id
        action.fetch_shuffle.producer.partition_id = partition_id
        action.fetch_shuffle.output_partition = shuffle_output
    else:
        action.fetch_partition.job_id = job_id
        action.fetch_partition.stage_id = stage_id
        action.fetch_partition.partition_id = partition_id
    return action


def fetch_partition_bytes(host: str, port: int, job_id: str, stage_id: int,
                          partition_id: int, timeout: float = 60.0,
                          shuffle_output: "int | None" = None) -> bytes:
    action = _fetch_action(job_id, stage_id, partition_id, shuffle_output)
    payload = action.SerializeToString()
    with socket.create_connection((host, port), timeout=timeout) as sock:
        sock.sendall(struct.pack(">I", len(payload)) + payload)
        status = _recv_exact(sock, 1)[0]
        (length,) = struct.unpack(">Q", _recv_exact(sock, 8))
        body = _recv_exact(sock, length)
    if status != 0:
        raise IoError(f"fetch failed: {body.decode(errors='replace')}")
    return body


_STREAM_ERROR_FRAME = 0xFFFFFFFF


def fetch_partition_chunks(host: str, port: int, job_id: str,
                           stage_id: int, partition_id: int,
                           timeout: float = 60.0,
                           shuffle_output: "int | None" = None,
                           window_bytes: "int | None" = None,
                           chunk_bytes: "int | None" = None,
                           ) -> Iterator[bytes]:
    """Streaming fetch: yields the partition's bytes in bounded chunks.

    Negotiates the chunk-stream framing via ``Action.stream_window``; a
    legacy peer (the native C++ daemon) ignores the field and answers
    with the whole-payload framing, which is then consumed in
    ``chunk_bytes`` reads — either way no whole-partition buffer ever
    exists on this side, and the caller controls the pace (it pulls the
    generator), which IS the flow control: acks are sent only after the
    previous chunk was consumed, so a slow consumer idles the wire at
    ``window_bytes`` in flight, not at the partition size."""
    from ..lifecycle import check_cancel
    from .spill import shuffle_chunk_bytes, stream_window_bytes

    window = int(window_bytes or stream_window_bytes())
    piece = int(chunk_bytes or shuffle_chunk_bytes())
    action = _fetch_action(job_id, stage_id, partition_id, shuffle_output)
    action.stream_window = window
    action.stream_chunk = piece
    payload = action.SerializeToString()
    sock = socket.create_connection((host, port), timeout=timeout)
    try:
        sock.sendall(struct.pack(">I", len(payload)) + payload)
        status = _recv_exact(sock, 1)[0]
        if status == 1:
            (length,) = struct.unpack(">Q", _recv_exact(sock, 8))
            body = _recv_exact(sock, length)
            raise IoError(f"fetch failed: {body.decode(errors='replace')}")
        if status == 0:
            # legacy whole-payload framing (native server): the length
            # is known up front; consume the body in bounded reads
            (length,) = struct.unpack(">Q", _recv_exact(sock, 8))
            remaining = length
            while remaining > 0:
                # chunk-level cancellation: a fired token aborts the
                # fetch of a multi-GB legacy-framed body mid-transfer
                # even when the consumer forgets to check
                check_cancel()
                chunk = _recv_exact(sock, min(piece, remaining))
                remaining -= len(chunk)
                yield chunk
            return
        if status != 2:
            raise IoError(f"bad data-plane status byte {status}")
        while True:
            check_cancel()  # per-frame: cancel aborts mid-stream fetches
            (n,) = struct.unpack(">I", _recv_exact(sock, 4))
            if n == 0:
                return
            if n == _STREAM_ERROR_FRAME:
                (mlen,) = struct.unpack(">I", _recv_exact(sock, 4))
                msg = _recv_exact(sock, mlen)
                raise IoError(
                    f"stream failed: {msg.decode(errors='replace')}")
            chunk = _recv_exact(sock, n)
            yield chunk
            # ack AFTER the consumer resumed us: in-flight unacked
            # bytes measure what the reader has genuinely not absorbed.
            # A send failure is NOT a stream failure — a server that
            # already sent its end marker closes without draining the
            # trailing acks; the next frame read is the source of truth
            try:
                sock.sendall(struct.pack(">I", n))
            except OSError:
                pass
    finally:
        try:
            sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        from ..testing.faults import fault_point

        try:
            # "drop" = close without a response (the peer sees a dead
            # connection, exactly like a mid-transfer crash); "fail"
            # raises and is reported as an error response below. Only
            # the Python server has this point — the native C++ daemon
            # is out of fault-injection reach (tests arm it with
            # BALLISTA_NATIVE_DATAPLANE=off).
            if fault_point("dataplane.serve") == "drop":
                return
            (length,) = struct.unpack(">I", _recv_exact(self.request, 4))
            action = pb.Action()
            action.ParseFromString(_recv_exact(self.request, length))
            which = action.WhichOneof("action_type")
            if which == "fetch_partition":
                f = action.fetch_partition
                job_id = f.job_id
                path = partition_path(
                    self.server.work_dir, f.job_id, f.stage_id, f.partition_id
                )
            elif which == "fetch_shuffle":
                fs = action.fetch_shuffle
                job_id = fs.producer.job_id
                path = shuffle_path(
                    self.server.work_dir, fs.producer.job_id,
                    fs.producer.stage_id, fs.producer.partition_id,
                    fs.output_partition,
                )
            else:
                raise IoError(f"unsupported data-plane action {which}")
            if not path_component_ok(job_id):
                raise IoError("bad job id")
            if not os.path.exists(path):
                raise IoError(f"no such partition: {path}")
            if action.stream_window > 0 and self.server.stream_serve:
                self._serve_stream(path, job_id,
                                   int(action.stream_window),
                                   int(action.stream_chunk))
                return
            with open(path, "rb") as fh:
                body = fh.read()
            self.request.sendall(struct.pack(">BQ", 0, len(body)))
            self.request.sendall(body)
        except Exception as e:  # noqa: BLE001 - report to peer
            msg = str(e).encode()
            try:
                self.request.sendall(struct.pack(">BQ", 1, len(msg)) + msg)
            except OSError:
                pass

    def _serve_stream(self, path: str, job_id: str, window: int,
                      req_chunk: int = 0) -> None:
        """Flow-controlled chunk stream (status byte 2; framing in the
        module docstring). The writer suspends on the peer's acks once
        ``window`` bytes are unacknowledged, checks the cancelled-job
        registry per chunk (a CancelJob aborts mid-flight transfers, not
        just future ones) and exposes the ``dataplane.flow`` fault point
        (drop = close mid-stream like a crashed peer; fail = tagged
        error frame). Transport errors just end the handler — the peer
        sees a dead connection and takes its retry/recovery path."""
        from ..testing.faults import fault_point
        from .spill import shuffle_chunk_bytes

        sock = self.request
        # the reader's requested frame size, capped by this server's own
        # chunk bound (a peer must not force huge frames on us)
        piece = shuffle_chunk_bytes()
        if req_chunk > 0:
            piece = min(piece, req_chunk)
        sock.settimeout(60.0)  # ack reads must not wedge a dead peer
        sock.sendall(b"\x02")
        unacked = 0
        try:
            with open(path, "rb") as fh:
                while True:
                    if job_stream_cancelled(job_id):
                        self._stream_error(f"job {job_id} cancelled")
                        return
                    # "fail" raises out to the error frame below;
                    # "drop" = close mid-stream like a crashed peer
                    if fault_point("dataplane.flow", path=path) == "drop":
                        return
                    chunk = fh.read(piece)
                    if not chunk:
                        break
                    # window-bounded ack drain; the enclosing per-chunk
                    # loop re-checks the cancelled-job registry
                    # ballista: ignore[cancel-coverage]
                    while unacked + len(chunk) > window and unacked > 0:
                        (acked,) = struct.unpack(
                            ">I", _recv_exact(sock, 4))
                        unacked -= acked
                    sock.sendall(struct.pack(">I", len(chunk)) + chunk)
                    unacked += len(chunk)
            sock.sendall(struct.pack(">I", 0))
        except (OSError, IoError):
            return  # peer vanished mid-stream; nothing to report to
        except Exception as e:  # noqa: BLE001 - report mid-stream
            self._stream_error(f"{type(e).__name__}: {e}")

    def _stream_error(self, msg: str) -> None:
        data = msg.encode()
        try:
            self.request.sendall(
                struct.pack(">II", _STREAM_ERROR_FRAME, len(data)) + data)
        except OSError:
            pass


class DataPlaneServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    # tests flip this off to pin the legacy whole-payload framing (the
    # same path a native C++ peer answers with)
    stream_serve = True

    def __init__(self, host: str, port: int, work_dir: str):
        super().__init__((host, port), _Handler)
        self.work_dir = work_dir

    @property
    def port(self) -> int:
        return self.server_address[1]

    def close(self):
        """Stop serving AND close the listening socket — shutdown() alone
        leaves the OS accepting (and never answering) connections, so
        peers hang until their recv timeout instead of getting refused."""
        self.shutdown()
        self.server_close()


class NativeDataPlane:
    """The C++ shuffle server (native/shuffle_server.cpp) as the
    production data plane: a thread-per-connection daemon with zero GIL
    involvement, so partition serving never contends with task execution
    in the executor process (the reference's equivalent is the tokio
    Flight service, rust/executor/src/flight_service.rs:193-228). Same
    wire protocol and path layout as ``DataPlaneServer``."""

    def __init__(self, port: int, work_dir: str, bind_host: str = ""):
        import subprocess

        bin_path = _native_server_bin()
        if bin_path is None:
            raise IoError("native shuffle server not built")
        cmd = [bin_path, str(port), work_dir]
        if bind_host:
            cmd.append(bind_host)
        # The binary ties its lifetime to THIS process (PDEATHSIG +
        # getppid watch against SHUFFLE_SERVER_PARENT_PID), so a
        # SIGKILLed executor can't orphan a daemon wedging the
        # configured port — and no preexec_fn is needed here (fork
        # hooks deadlock under multithreaded jax).
        env = dict(os.environ)
        env["SHUFFLE_SERVER_PARENT_PID"] = str(os.getpid())
        self._proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        line = self._read_banner(timeout_s=10.0)
        try:
            self.port = int(line.split("port")[1].split()[0])
        except (IndexError, ValueError):
            self._proc.terminate()
            self._proc.wait(timeout=5)
            raise IoError(
                f"native shuffle server failed to start: {line!r}")
        self.work_dir = work_dir

    def _read_banner(self, timeout_s: float) -> str:
        """First stdout line with a deadline: a child that binds but
        never prints must fall back to the Python server, not hang the
        executor constructor."""
        import select

        fd = self._proc.stdout.fileno()
        ready, _, _ = select.select([fd], [], [], timeout_s)
        if not ready:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except Exception:  # noqa: BLE001 - escalate
                self._proc.kill()
            raise IoError(
                f"native shuffle server silent for {timeout_s:.0f}s")
        return self._proc.stdout.readline()

    def close(self):
        self._proc.terminate()
        try:
            self._proc.wait(timeout=5)
        except Exception:  # noqa: BLE001 - escalate to SIGKILL
            self._proc.kill()
            self._proc.wait(timeout=5)


def _native_server_bin() -> Optional[str]:
    """Path to the built shuffle_server binary (built on demand alongside
    the native scanner; both come from `make -C ballista_tpu/native`)."""
    native_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "native",
    )
    bin_path = os.path.join(native_dir, "shuffle_server")
    if os.path.exists(bin_path):
        return bin_path
    from ..io import native as native_scan

    if native_scan._try_build() and os.path.exists(bin_path):
        return bin_path
    return None


def native_dataplane_enabled(value: Optional[str] = None) -> bool:
    """Single parse rule for the data-plane selector (env or config):
    'off'/'0'/'false' (any case) disables the native daemon."""
    if value is None:
        value = os.environ.get("BALLISTA_NATIVE_DATAPLANE", "on")
    return str(value).lower() not in ("off", "0", "false")


def start_data_plane(host: str, port: int, work_dir: str,
                     native: Optional[bool] = None):
    """Start the shuffle data plane; returns an object with .port/.close().

    The native C++ daemon is the default; ``BALLISTA_NATIVE_DATAPLANE=off``
    (or native=False) selects the in-process Python server, which also
    remains the automatic fallback when the binary can't be built."""
    if native is None:
        native = native_dataplane_enabled()
    if native:
        try:
            return NativeDataPlane(port, work_dir, bind_host=host)
        except Exception as e:  # noqa: BLE001 - fall back to Python server
            import logging

            logging.getLogger(__name__).warning(
                "native data plane unavailable (%s); using Python server", e)
    server = DataPlaneServer(host, port, work_dir)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="ballista-data-plane")
    t.start()
    return server
