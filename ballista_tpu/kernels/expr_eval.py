"""Traced evaluation of logical expressions against a ColumnBatch.

This is the TPU-native analogue of DataFusion's physical expression layer
that the reference engine serializes in rust/core/src/serde/physical_plan
(reference: to_proto.rs:67-331). Instead of a virtual-dispatch interpreter
over Arrow arrays, expressions are *traced* into the enclosing jit, so a
whole filter/project pipeline compiles to one fused XLA kernel.

Conventions:
- decimals are scaled int64; arithmetic tracks scales exactly (see
  datatypes.py);
- float64 results are computed/stored as f32 on device (TPU has no fast
  f64) — exactness-critical reductions stay in int64;
- utf8 columns are dictionary codes; string predicates (equality, ordering,
  LIKE, substr...) are evaluated *on the host dictionary once* and become
  cheap gathers/compares over the codes on device;
- SQL NULL: validity masks propagate through; predicates treat NULL as
  False at filter boundaries.
"""

from __future__ import annotations

import fnmatch
import re
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, ColumnBatch, Dictionary
from ..datatypes import (
    Boolean,
    DataType,
    Date32,
    Decimal,
    Field,
    Float64,
    Int32,
    Int64,
    Schema,
    Utf8,
)
from ..errors import ExecutionError, NotImplementedError_, PlanError
from .. import expr as ex
from . import dates as date_kernels


@dataclass
class Evaluated:
    """Result of evaluating one expression: traced values + metadata."""

    values: jax.Array  # scalar or [capacity]
    dtype: DataType
    validity: Optional[jax.Array] = None  # bool, None = all valid
    dictionary: Optional[Dictionary] = None
    # set when this is a literal: the exact Python value, enabling exact
    # decimal-vs-float-literal comparisons (no f32 boundary drift)
    literal_value: object = None

    def valid_or(self, cap: int) -> jax.Array:
        if self.validity is None:
            return jnp.ones((cap,), dtype=jnp.bool_)
        return jnp.broadcast_to(self.validity, (cap,))


def _and_validity(*vs: Optional[jax.Array]) -> Optional[jax.Array]:
    present = [v for v in vs if v is not None]
    if not present:
        return None
    out = present[0]
    for v in present[1:]:
        out = jnp.logical_and(out, v)
    return out


def _f32(x):
    return x.astype(jnp.float32)


class Evaluator:
    """Evaluates logical Exprs against batches of a fixed input schema."""

    def __init__(self, schema: Schema):
        self.schema = schema

    # ------------------------------------------------------------------ API

    def evaluate(self, e: ex.Expr, batch: ColumnBatch) -> Evaluated:
        method = getattr(self, "_eval_" + type(e).__name__, None)
        if method is None:
            raise NotImplementedError_(f"cannot evaluate {type(e).__name__}")
        return method(e, batch)

    def evaluate_predicate(self, e: ex.Expr, batch: ColumnBatch) -> jax.Array:
        """Boolean mask [capacity]; NULL -> False."""
        r = self.evaluate(e, batch)
        if r.dtype != Boolean:
            raise PlanError(f"predicate has type {r.dtype!r}, expected boolean")
        mask = jnp.broadcast_to(r.values, (batch.capacity,))
        if r.validity is not None:
            mask = jnp.logical_and(mask, r.validity)
        return mask

    def to_column(self, e: ex.Expr, batch: ColumnBatch) -> Column:
        r = self.evaluate(e, batch)
        # scalar/1-D values broadcast to (capacity,); fixed-size-list
        # values keep their trailing element axis: (capacity, length)
        trailing = tuple(getattr(r.values, "shape", ()))[1:]
        vals = jnp.broadcast_to(r.values, (batch.capacity,) + trailing)
        return Column(vals, r.dtype, r.validity, r.dictionary)

    # ----------------------------------------------------------- leaf nodes

    def _eval_ColumnRef(self, e: ex.ColumnRef, batch: ColumnBatch) -> Evaluated:
        idx = batch.schema.index_of(e.column)
        col = batch.columns[idx]
        return Evaluated(col.values, col.dtype, col.validity, col.dictionary)

    def _eval_Literal(self, e: ex.Literal, batch: ColumnBatch) -> Evaluated:
        if e.value is None:
            cap = batch.capacity
            return Evaluated(
                jnp.zeros((), dtype=e.dtype.device_dtype()),
                e.dtype,
                jnp.zeros((cap,), dtype=jnp.bool_),
            )
        if e.dtype.kind == "utf8":
            # bare utf8 literal (e.g. in projection): 1-entry dictionary
            d = Dictionary([e.value])
            return Evaluated(jnp.zeros((), jnp.int32), Utf8, None, d)
        v = e.value
        if e.dtype.kind == "decimal":
            v = int(round(float(v) * 10 ** e.dtype.scale))
        return Evaluated(
            jnp.asarray(v, dtype=e.dtype.device_dtype()), e.dtype,
            literal_value=e.value,
        )

    # ------------------------------------------------------------- wrappers

    def _eval_Alias(self, e: ex.Alias, batch: ColumnBatch) -> Evaluated:
        return self.evaluate(e.expr, batch)

    def _eval_SortExpr(self, e: ex.SortExpr, batch: ColumnBatch) -> Evaluated:
        return self.evaluate(e.expr, batch)

    def _eval_Not(self, e: ex.Not, batch: ColumnBatch) -> Evaluated:
        r = self.evaluate(e.expr, batch)
        return Evaluated(jnp.logical_not(r.values), Boolean, r.validity)

    def _eval_IsNull(self, e: ex.IsNull, batch: ColumnBatch) -> Evaluated:
        r = self.evaluate(e.expr, batch)
        if r.validity is None:
            return Evaluated(jnp.zeros((batch.capacity,), jnp.bool_), Boolean)
        return Evaluated(jnp.logical_not(r.validity), Boolean)

    def _eval_IsNotNull(self, e: ex.IsNotNull, batch: ColumnBatch) -> Evaluated:
        r = self.evaluate(e.expr, batch)
        if r.validity is None:
            return Evaluated(jnp.ones((batch.capacity,), jnp.bool_), Boolean)
        return Evaluated(r.validity, Boolean)

    def _eval_Cast(self, e: ex.Cast, batch: ColumnBatch) -> Evaluated:
        r = self.evaluate(e.expr, batch)
        return self._cast(r, e.dtype)

    def _cast(self, r: Evaluated, to: DataType) -> Evaluated:
        if r.dtype == to:
            return r
        src, dst = r.dtype, to
        v = r.values
        if dst.kind == "decimal":
            if src.kind == "decimal":
                shift = dst.scale - src.scale
                if shift >= 0:
                    out = v.astype(jnp.int64) * (10 ** shift)
                else:
                    out = v.astype(jnp.int64) // (10 ** (-shift))
            elif src.is_integer:
                out = v.astype(jnp.int64) * (10 ** dst.scale)
            elif src.is_floating:
                out = jnp.round(_f32(v) * (10.0 ** dst.scale)).astype(jnp.int64)
            else:
                raise PlanError(f"cast {src!r} -> {dst!r} unsupported")
            return Evaluated(out, dst, r.validity)
        if dst.is_floating:
            if src.kind == "decimal":
                out = _f32(v) / (10.0 ** src.scale)
            else:
                out = _f32(v)
            return Evaluated(out, dst, r.validity)
        if dst.is_integer:
            if src.kind == "decimal":
                out = (v // (10 ** src.scale)).astype(dst.device_dtype())
            else:
                out = v.astype(dst.device_dtype())
            return Evaluated(out, dst, r.validity)
        if dst.kind == "date32" and src.is_integer:
            return Evaluated(v.astype(jnp.int32), dst, r.validity)
        if dst.kind == "boolean":
            return Evaluated(v.astype(jnp.bool_), dst, r.validity)
        raise PlanError(f"cast {src!r} -> {dst!r} unsupported")

    # --------------------------------------------------------------- binary

    def _eval_BinaryExpr(self, e: ex.BinaryExpr, batch: ColumnBatch) -> Evaluated:
        op = e.op
        l = self.evaluate(e.left, batch)
        r = self.evaluate(e.right, batch)
        validity = _and_validity(l.validity, r.validity)

        if op in ex.BOOL_OPS:
            # NULL-as-False at boolean combinators (adequate for TPC-H)
            lv = l.values if l.validity is None else jnp.logical_and(l.values, l.validity)
            rv = r.values if r.validity is None else jnp.logical_and(r.values, r.validity)
            fn = jnp.logical_and if op == "and" else jnp.logical_or
            return Evaluated(fn(lv, rv), Boolean, None)

        if op in ex.CMP_OPS:
            return self._compare(op, l, r, validity)

        # arithmetic
        return self._arith(op, l, r, validity)

    # comparison ----------------------------------------------------------

    _CMP = {
        "=": jnp.equal,
        "!=": jnp.not_equal,
        "<": jnp.less,
        "<=": jnp.less_equal,
        ">": jnp.greater,
        ">=": jnp.greater_equal,
    }

    def _compare(self, op, l: Evaluated, r: Evaluated, validity) -> Evaluated:
        # utf8 handling
        if l.dtype.kind == "utf8" or r.dtype.kind == "utf8":
            return self._compare_utf8(op, l, r, validity)
        # exact decimal column vs numeric literal: integer threshold compare
        if l.dtype.kind == "decimal" and r.literal_value is not None \
                and r.dtype.is_numeric and r.dtype.kind != "decimal":
            res = self._compare_decimal_literal(op, l, r.literal_value, validity)
            if res is not None:
                return res
        if r.dtype.kind == "decimal" and l.literal_value is not None \
                and l.dtype.is_numeric and l.dtype.kind != "decimal":
            flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                    "=": "=", "!=": "!="}
            res = self._compare_decimal_literal(
                flip[op], r, l.literal_value, validity
            )
            if res is not None:
                return res
        lv, rv = self._coerce_pair(l, r)
        return Evaluated(self._CMP[op](lv, rv), Boolean, validity)

    _I64_MAX = (1 << 63) - 1
    _I64_MIN = -(1 << 63)

    def _compare_decimal_literal(self, op, col: Evaluated, lit_val,
                                 validity) -> Optional[Evaluated]:
        """decimal(s) column vs float/int literal without f32 drift: the
        literal scales to c*10^s in host float64, then integer thresholds
        (floor/ceil) make every comparison exact. Returns None for
        non-finite literals (caller falls back to the generic float path,
        where NaN compares all-false)."""
        import math

        n = col.values.shape
        c = float(lit_val) * (10 ** col.dtype.scale)
        if not math.isfinite(c):
            return None
        v = col.values.astype(jnp.int64)
        # literals beyond int64 range: every value is on one side
        if c > self._I64_MAX:
            true_ops = ("<", "<=", "!=")
        elif c < self._I64_MIN:
            true_ops = (">", ">=", "!=")
        else:
            true_ops = None
        if true_ops is not None:
            fill = jnp.full(n, op in true_ops, dtype=jnp.bool_)
            return Evaluated(fill, Boolean, validity)
        # relative tolerance: double rounding error grows with |c|
        is_int = abs(c - round(c)) <= max(1e-9, abs(c) * 1e-12)
        ci = int(round(c))
        if op == "=":
            out = (v == ci) if is_int else jnp.zeros(n, jnp.bool_)
        elif op == "!=":
            out = (v != ci) if is_int else jnp.ones(n, jnp.bool_)
        elif op == "<":
            out = v < (ci if is_int else math.ceil(c))
        elif op == "<=":
            out = v <= (ci if is_int else math.floor(c))
        elif op == ">":
            out = v > (ci if is_int else math.floor(c))
        else:  # >=
            out = v >= (ci if is_int else math.ceil(c))
        return Evaluated(out, Boolean, validity)

    def _compare_utf8(self, op, l: Evaluated, r: Evaluated, validity) -> Evaluated:
        # date column vs string literal
        if l.dtype.kind == "date32" and r.dtype.kind == "utf8":
            days = ex.parse_date_literal(self._literal_str(r))
            return Evaluated(
                self._CMP[op](l.values, jnp.int32(days)), Boolean, validity
            )
        if r.dtype.kind == "date32" and l.dtype.kind == "utf8":
            days = ex.parse_date_literal(self._literal_str(l))
            return Evaluated(
                self._CMP[op](jnp.int32(days), r.values), Boolean, validity
            )
        # dict-coded column vs string literal
        if l.dictionary is not None and r.dictionary is not None:
            if len(r.dictionary) == 1:  # literal on the right
                return self._compare_codes_literal(
                    op, l, r.dictionary.values[0], validity
                )
            if len(l.dictionary) == 1:  # literal on the left (flip op)
                flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}
                return self._compare_codes_literal(
                    flip[op], r, l.dictionary.values[0], validity
                )
            if l.dictionary is r.dictionary:
                return Evaluated(self._CMP[op](l.values, r.values), Boolean, validity)
            raise NotImplementedError_(
                "comparison between differently-encoded utf8 columns"
            )
        raise PlanError("utf8 comparison requires dictionary-encoded operands")

    def _compare_codes_literal(self, op, col: Evaluated, s: str, validity) -> Evaluated:
        d = col.dictionary
        codes = col.values
        if op in ("=", "!="):
            code = d.code_of(s)
            if code < 0:
                out = jnp.zeros(codes.shape, jnp.bool_)
            else:
                out = jnp.equal(codes, jnp.int32(code))
            if op == "!=":
                out = jnp.logical_not(out)
            return Evaluated(out, Boolean, validity)
        # ordering against a sorted dictionary: code-space boundary compare
        lo, hi = d.code_range(s)
        if op == "<":
            out = codes < lo
        elif op == "<=":
            out = codes < hi
        elif op == ">":
            out = codes >= hi
        else:  # >=
            out = codes >= lo
        return Evaluated(out, Boolean, validity)

    def _literal_str(self, r: Evaluated) -> str:
        if r.dictionary is None or len(r.dictionary) != 1:
            raise PlanError("expected a string literal")
        return str(r.dictionary.values[0])

    def _coerce_pair(self, l: Evaluated, r: Evaluated):
        """Coerce two numeric/temporal operands to a directly comparable repr."""
        a, b = l.dtype, r.dtype
        if a.kind == "decimal" or b.kind == "decimal":
            if a.is_floating or b.is_floating:
                lv = _f32(l.values) / (10.0 ** a.scale) if a.kind == "decimal" else _f32(l.values)
                rv = _f32(r.values) / (10.0 ** b.scale) if b.kind == "decimal" else _f32(r.values)
                return lv, rv
            sa = a.scale if a.kind == "decimal" else 0
            sb = b.scale if b.kind == "decimal" else 0
            s = max(sa, sb)
            lv = l.values.astype(jnp.int64) * (10 ** (s - sa))
            rv = r.values.astype(jnp.int64) * (10 ** (s - sb))
            return lv, rv
        if a.is_floating or b.is_floating:
            return _f32(l.values), _f32(r.values)
        if a.kind == "date32" or b.kind == "date32":
            return l.values.astype(jnp.int32), r.values.astype(jnp.int32)
        if a.kind == "int64" or b.kind == "int64":
            return l.values.astype(jnp.int64), r.values.astype(jnp.int64)
        return l.values, r.values

    # arithmetic -----------------------------------------------------------

    def _arith(self, op, l: Evaluated, r: Evaluated, validity) -> Evaluated:
        a, b = l.dtype, r.dtype
        # dates
        if a.kind == "date32" or b.kind == "date32":
            lv = l.values.astype(jnp.int32)
            rv = r.values.astype(jnp.int32)
            if op == "+":
                return Evaluated(lv + rv, Date32, validity)
            if op == "-":
                out_t = Int32 if (a.kind == b.kind == "date32") else Date32
                return Evaluated(lv - rv, out_t, validity)
            raise PlanError(f"op {op} invalid for dates")
        # decimal exact paths
        if (a.kind == "decimal" or b.kind == "decimal") and not (
            a.is_floating or b.is_floating
        ):
            sa = a.scale if a.kind == "decimal" else 0
            sb = b.scale if b.kind == "decimal" else 0
            lv = l.values.astype(jnp.int64)
            rv = r.values.astype(jnp.int64)
            if op in ("+", "-"):
                s = max(sa, sb)
                lv = lv * (10 ** (s - sa))
                rv = rv * (10 ** (s - sb))
                out = lv + rv if op == "+" else lv - rv
                return Evaluated(out, Decimal(s), validity)
            if op == "*":
                return Evaluated(lv * rv, Decimal(sa + sb), validity)
            if op == "/":
                out = (_f32(lv) / (10.0 ** sa)) / (_f32(rv) / (10.0 ** sb))
                return Evaluated(out, Float64, validity)
            raise PlanError(f"op {op} unsupported on decimal")
        # float path (int/int division stays integer, matching the planner's
        # _arith_result_type: SQL integer division truncates toward zero)
        int_int = a.is_integer and b.is_integer
        if a.is_floating or b.is_floating or (op == "/" and not int_int):
            lv = _f32(l.values) / (10.0 ** a.scale) if a.kind == "decimal" else _f32(l.values)
            rv = _f32(r.values) / (10.0 ** b.scale) if b.kind == "decimal" else _f32(r.values)
            out = {"+": jnp.add, "-": jnp.subtract, "*": jnp.multiply,
                   "/": jnp.divide, "%": jnp.mod}[op](lv, rv)
            return Evaluated(out, Float64, validity)
        # integer path
        out_t = Int64 if (a.kind == "int64" or b.kind == "int64") else Int32
        lv = l.values.astype(out_t.device_dtype())
        rv = r.values.astype(out_t.device_dtype())
        if op == "/":
            out = jax.lax.div(lv, rv)  # truncating integer division
        else:
            out = {"+": jnp.add, "-": jnp.subtract, "*": jnp.multiply,
                   "%": jnp.mod}[op](lv, rv)
        return Evaluated(out, out_t, validity)

    # ------------------------------------------------------------ compound

    def _eval_InList(self, e: ex.InList, batch: ColumnBatch) -> Evaluated:
        base = self.evaluate(e.expr, batch)
        acc = None
        for item in e.list:
            cmp = self._compare("=", base, self.evaluate(item, batch), None)
            acc = cmp.values if acc is None else jnp.logical_or(acc, cmp.values)
        if acc is None:
            acc = jnp.zeros((batch.capacity,), jnp.bool_)
        if e.negated:
            acc = jnp.logical_not(acc)
        return Evaluated(acc, Boolean, base.validity)

    def _eval_Like(self, e: ex.Like, batch: ColumnBatch) -> Evaluated:
        base = self.evaluate(e.expr, batch)
        if base.dictionary is None:
            raise NotImplementedError_("LIKE on non-dictionary column")
        # SQL LIKE -> regex on the host dictionary, gather match by code
        pat = re.escape(str(e.pattern)).replace("%", ".*").replace("_", ".")
        rx = re.compile("^" + pat + "$", re.S)
        host = np.asarray(
            [bool(rx.match(str(v))) for v in base.dictionary.values], dtype=np.bool_
        )
        out = jnp.take(jnp.asarray(host), base.values.astype(jnp.int32), mode="clip")
        if e.negated:
            out = jnp.logical_not(out)
        return Evaluated(out, Boolean, base.validity)

    def _eval_Case(self, e: ex.Case, batch: ColumnBatch) -> Evaluated:
        # Evaluate all branches; select with jnp.where chains (traced, fused).
        conds = []
        thens = []
        for w, t in e.branches:
            if e.base is not None:
                c = self._eval_BinaryExpr(ex.BinaryExpr(e.base, "=", w), batch)
            else:
                c = self.evaluate(w, batch)
            conds.append(c)
            thens.append(self.evaluate(t, batch))
        if e.otherwise is not None:
            other = self.evaluate(e.otherwise, batch)
        else:
            other = Evaluated(
                jnp.zeros((), thens[0].values.dtype),
                thens[0].dtype,
                jnp.zeros((batch.capacity,), jnp.bool_),
            )
        out_dtype = thens[0].dtype
        # normalize all THEN/ELSE branches to out_dtype
        norm = [self._cast(t, out_dtype) for t in thens]
        other = self._cast(other, out_dtype)
        vals = jnp.broadcast_to(other.values, (batch.capacity,))
        validity = other.validity
        for c, t in zip(reversed(conds), reversed(norm)):
            cm = jnp.broadcast_to(c.values, (batch.capacity,))
            if c.validity is not None:
                cm = jnp.logical_and(cm, c.validity)
            vals = jnp.where(cm, jnp.broadcast_to(t.values, (batch.capacity,)), vals)
            tv = t.valid_or(batch.capacity)
            ov = validity if validity is not None else jnp.ones(
                (batch.capacity,), jnp.bool_
            )
            validity = jnp.where(cm, tv, ov)
        return Evaluated(vals, out_dtype, validity)

    # ------------------------------------------------------ scalar functions

    def _eval_ScalarFunction(self, e: ex.ScalarFunction, batch: ColumnBatch) -> Evaluated:
        fn = e.fn
        # string functions -> host dictionary transforms
        if fn in ("upper", "lower", "trim", "ltrim", "rtrim", "substr", "length",
                  "character_length", "octet_length", "concat", "md5",
                  "sha224", "sha256", "sha384", "sha512", "to_timestamp"):
            return self._eval_string_fn(e, batch)
        if fn in ("extract_year", "extract_month", "extract_day", "date_part",
                  "date_trunc"):
            return self._eval_date_fn(e, batch)
        args = [self.evaluate(a, batch) for a in e.args]
        validity = _and_validity(*[a.validity for a in args])
        if fn == "array":
            # rectangular (capacity, n) stack; a NULL element NULLs the row
            # (documented restriction — no per-element validity planes)
            out_f = e.to_field(batch.schema)
            elem = out_f.dtype.element
            cap = batch.capacity
            norm = [self._cast(a, elem) for a in args]
            stacked = jnp.stack(
                [jnp.broadcast_to(a.values, (cap,)) for a in norm], axis=1)
            return Evaluated(stacked, out_f.dtype, validity)
        if fn == "nullif":
            eqr = self._compare("=", args[0], args[1], None)
            base_valid = args[0].valid_or(batch.capacity)
            new_valid = jnp.logical_and(base_valid, jnp.logical_not(eqr.values))
            return Evaluated(args[0].values, args[0].dtype, new_valid)
        if fn == "coalesce":
            out_dtype = args[0].dtype
            norm = [self._cast(a, out_dtype) for a in args]
            out = jnp.broadcast_to(norm[-1].values, (batch.capacity,))
            validity = norm[-1].validity
            for a in reversed(norm[:-1]):
                av = a.valid_or(batch.capacity)
                out = jnp.where(av, jnp.broadcast_to(a.values, (batch.capacity,)), out)
                validity = jnp.logical_or(av, validity) if validity is not None else av
            return Evaluated(out, out_dtype, validity)
        x = args[0]
        if fn == "abs":
            return Evaluated(jnp.abs(x.values), x.dtype, validity)
        if fn == "signum":
            return Evaluated(jnp.sign(x.values), x.dtype, validity)
        # float math
        xv = _f32(x.values)
        if x.dtype.kind == "decimal":
            xv = xv / (10.0 ** x.dtype.scale)
        jfn = {
            "sqrt": jnp.sqrt, "exp": jnp.exp, "ln": jnp.log, "log": jnp.log,
            "log2": jnp.log2, "log10": jnp.log10, "floor": jnp.floor,
            "ceil": jnp.ceil, "round": jnp.round, "trunc": jnp.trunc,
            "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "asin": jnp.arcsin,
            "acos": jnp.arccos, "atan": jnp.arctan,
        }.get(fn)
        if jfn is None:
            raise NotImplementedError_(f"scalar function {fn}")
        return Evaluated(jfn(xv), Float64, validity)

    @staticmethod
    def _literal_part(e: ex.ScalarFunction, arg_index: int = 0) -> str:
        part = e.args[arg_index]
        name = part.value if isinstance(part, ex.Literal) else None
        if name is None:
            raise PlanError(f"{e.fn} requires a literal part name")
        return str(name).lower()

    _NS_PER_DAY = 86_400_000_000_000

    def _as_epoch_days(self, x: Evaluated):
        """Temporal value -> days-since-epoch int32 (timestamps floor to
        their calendar day)."""
        if x.dtype.kind == "timestamp_ns":
            return jnp.floor_divide(
                x.values, jnp.int64(self._NS_PER_DAY)).astype(jnp.int32)
        return x.values

    _NS_PER = {"hour": 3_600_000_000_000, "minute": 60_000_000_000,
               "second": 1_000_000_000}

    def _eval_date_fn(self, e: ex.ScalarFunction, batch: ColumnBatch) -> Evaluated:
        if e.fn == "date_trunc":
            part_name = self._literal_part(e)
            x = self.evaluate(e.args[1], batch)
            if part_name in self._NS_PER or part_name == "day":
                if x.dtype.kind != "timestamp_ns":  # dates: day- no-ops
                    if part_name == "day":
                        return x
                    raise PlanError(
                        f"date_trunc({part_name!r}) needs a timestamp, "
                        f"got {x.dtype}")
                unit = jnp.int64(self._NS_PER.get(part_name,
                                                  self._NS_PER_DAY))
                return Evaluated(
                    jnp.floor_divide(x.values, unit) * unit, x.dtype,
                    x.validity)
            if part_name not in ("year", "quarter", "month", "week"):
                raise PlanError(f"date_trunc part {part_name!r}")
            days = self._as_epoch_days(x)
            td = date_kernels.date_trunc(part_name, days)
            if x.dtype.kind == "timestamp_ns":
                td = td.astype(jnp.int64) * jnp.int64(self._NS_PER_DAY)
            return Evaluated(td, x.dtype, x.validity)
        if e.fn == "date_part":
            part_name = self._literal_part(e)
            x = self.evaluate(e.args[1], batch)
            return self._extract_part(part_name, x)
        x = self.evaluate(e.args[0], batch)
        return self._extract_part(e.fn.removeprefix("extract_"), x)

    def _extract_part(self, part_name: str, x: Evaluated) -> Evaluated:
        if part_name in self._NS_PER:
            if x.dtype.kind != "timestamp_ns":
                raise PlanError(
                    f"date_part({part_name!r}) needs a timestamp, "
                    f"got {x.dtype}")
            unit = jnp.int64(self._NS_PER[part_name])
            mod = jnp.int64(self._NS_PER_DAY if part_name == "hour"
                            else self._NS_PER["hour"] if part_name == "minute"
                            else self._NS_PER["minute"])
            v = jnp.floor_divide(jnp.mod(x.values, mod), unit)
            return Evaluated(v.astype(jnp.int32), Int32, x.validity)
        fn = {"year": date_kernels.extract_year,
              "month": date_kernels.extract_month,
              "day": date_kernels.extract_day}.get(part_name)
        if fn is None:
            raise PlanError(f"date_part part {part_name!r}")
        return Evaluated(fn(self._as_epoch_days(x)), Int32, x.validity)

    def _eval_string_fn(self, e: ex.ScalarFunction, batch: ColumnBatch) -> Evaluated:
        fn = e.fn
        if fn == "concat":
            raise NotImplementedError_("concat over columns (host-side only)")
        base = self.evaluate(e.args[0], batch)
        if base.dictionary is None:
            raise NotImplementedError_(f"{fn} on non-dictionary column")
        d = base.dictionary
        if fn in ("length", "character_length", "octet_length"):
            if fn == "octet_length":  # bytes, not codepoints
                host = np.asarray(
                    [len(str(v).encode("utf-8")) for v in d.values],
                    dtype=np.int32)
            else:
                host = np.asarray([len(str(v)) for v in d.values],
                                  dtype=np.int32)
            out = jnp.take(jnp.asarray(host), base.values.astype(jnp.int32), mode="clip")
            return Evaluated(out, Int32, base.validity)
        if fn in ("md5", "sha224", "sha256", "sha384", "sha512"):
            # dictionary transform: hash each distinct string once
            import hashlib

            h = getattr(hashlib, fn)
            return self._remapped_dict(
                base, [h(str(v).encode("utf-8")).hexdigest() for v in d.values]
            )
        if fn == "to_timestamp":
            # parse each distinct string once -> epoch-ns lookup table
            from ..datatypes import TimestampNs

            # ns-representable range; np.datetime64(s, "ns") silently
            # WRAPS int64 outside it instead of raising
            lo = np.datetime64("1677-09-22", "s")
            hi = np.datetime64("2262-04-11", "s")

            def parse_one(v):
                try:
                    d = np.datetime64(str(v))  # native unit, no wrap
                except ValueError:
                    return np.datetime64("NaT", "ns")
                if np.isnat(d) or not (lo <= d.astype("datetime64[s]") <= hi):
                    return np.datetime64("NaT", "ns")
                return d.astype("datetime64[ns]")

            parsed = np.asarray([parse_one(v) for v in d.values],
                                dtype="datetime64[ns]")
            host = parsed.astype(np.int64)
            bad = np.isnat(parsed)
            out = jnp.take(jnp.asarray(host), base.values.astype(jnp.int32),
                           mode="clip")
            validity = base.validity
            if bad.any():
                ok = jnp.take(jnp.asarray(~bad),
                              base.values.astype(jnp.int32), mode="clip")
                validity = ok if validity is None else jnp.logical_and(
                    validity, ok)
            return Evaluated(out, TimestampNs, validity)
        if fn == "substr":
            start = e.args[1]
            length = e.args[2]
            if not (isinstance(start, ex.Literal) and isinstance(length, ex.Literal)):
                raise NotImplementedError_("substr with non-literal bounds")
            s0 = int(start.value) - 1  # SQL 1-based
            ln = int(length.value)
            return self._remapped_dict(base, [str(v)[s0 : s0 + ln] for v in d.values])
        tf = {"upper": str.upper, "lower": str.lower, "trim": str.strip,
              "ltrim": str.lstrip, "rtrim": str.rstrip}[fn]
        return self._remapped_dict(base, [tf(str(v)) for v in d.values])

    def _remapped_dict(self, base: Evaluated, new_values) -> Evaluated:
        # derived dictionaries must stay sorted + duplicate-free for the
        # comparison kernels; canonicalize and remap the codes
        newd, remap = Dictionary.canonicalize(new_values)
        codes = jnp.take(
            jnp.asarray(remap), base.values.astype(jnp.int32), mode="clip"
        )
        return Evaluated(codes, Utf8, base.validity, newd)
