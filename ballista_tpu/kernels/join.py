"""Join kernels.

TPU-native replacement for the reference's ``HashJoinExec`` (reference:
rust/core/proto/ballista.proto:399-407, HashJoinExecNode with on-keys and
join type). A CPU-style linked hash table doesn't map to the MXU/VPU, so the
build side is *sorted* and the probe side does a vectorized binary search
(XLA lowers searchsorted to a fused gather loop):

- ``build_lookup`` sorts the build keys once;
- ``probe_unique`` handles the FK->PK joins that dominate TPC-H (build keys
  unique): one searchsorted + one gather, no row expansion;
- ``probe_expand`` (general many-to-many) computes per-probe match counts
  and materializes matches up to a static output capacity.

Keys are single int64 columns (dict codes / ints / dates cast to int64).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp

INT64_SENTINEL = jnp.iinfo(jnp.int64).max


@dataclass
class BuildTable:
    """Sorted build side of a join."""

    sorted_keys: jax.Array  # int64 [Nb] (dead rows = sentinel, at end)
    order: jax.Array  # int32 [Nb] original row index per sorted slot
    num_live: jax.Array  # int32 scalar


jax.tree_util.register_dataclass(
    BuildTable, data_fields=["sorted_keys", "order", "num_live"], meta_fields=[]
)


def build_lookup(keys: jax.Array, live: jax.Array) -> BuildTable:
    keyed = jnp.where(live, keys, INT64_SENTINEL)
    order = jnp.argsort(keyed, stable=True).astype(jnp.int32)
    return BuildTable(keyed[order], order, jnp.sum(live.astype(jnp.int32)))


def probe_unique(
    table: BuildTable, probe_keys: jax.Array, probe_live: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Probe assuming unique build keys (FK->PK join).

    Returns (build_row_indices int32 [Np], matched bool [Np]). Unmatched
    probes get index 0 with matched=False; the caller masks them out
    (inner join) or null-fills (left join).
    """
    nb = table.sorted_keys.shape[0]
    idx = jnp.searchsorted(table.sorted_keys, probe_keys, side="left")
    idx = jnp.minimum(idx, nb - 1).astype(jnp.int32)
    hit = jnp.equal(table.sorted_keys[idx], probe_keys)
    hit = jnp.logical_and(hit, probe_keys != INT64_SENTINEL)
    matched = jnp.logical_and(hit, probe_live)
    build_rows = jnp.where(matched, table.order[idx], 0)
    return build_rows, matched


def probe_semi(
    table: BuildTable, probe_keys: jax.Array, probe_live: jax.Array
) -> jax.Array:
    """Semi-join mask: probe rows whose key exists in the build side."""
    _, matched = probe_unique(table, probe_keys, probe_live)
    return matched


def probe_counts(table: BuildTable, probe_keys: jax.Array) -> jax.Array:
    """Number of build matches per probe key (for many-to-many planning)."""
    lo = jnp.searchsorted(table.sorted_keys, probe_keys, side="left")
    hi = jnp.searchsorted(table.sorted_keys, probe_keys, side="right")
    return (hi - lo).astype(jnp.int32)


def probe_expand(
    table: BuildTable,
    probe_keys: jax.Array,
    probe_live: jax.Array,
    out_capacity: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """General inner join with row expansion to a static output capacity.

    Returns (probe_row_idx [C], build_row_idx [C], out_live [C],
    total_matches scalar). If total_matches > out_capacity the result is
    truncated; callers detect via the returned total and re-run with a
    bigger capacity (host-side fallback policy).
    """
    keyed = jnp.where(probe_live, probe_keys, INT64_SENTINEL - 1)
    lo = jnp.searchsorted(table.sorted_keys, keyed, side="left")
    hi = jnp.searchsorted(table.sorted_keys, keyed, side="right")
    counts = (hi - lo).astype(jnp.int32)
    counts = jnp.where(probe_live, counts, 0)
    offsets = jnp.cumsum(counts) - counts  # exclusive prefix sum
    total = jnp.sum(counts)

    C = out_capacity
    out_slot = jnp.arange(C, dtype=jnp.int32)
    # For each output slot, find its probe row: the row whose [offset,
    # offset+count) window contains the slot.
    probe_of_slot = (
        jnp.searchsorted(offsets + counts, out_slot, side="right")
    ).astype(jnp.int32)
    np_rows = probe_keys.shape[0]
    probe_of_slot = jnp.minimum(probe_of_slot, np_rows - 1)
    within = out_slot - offsets[probe_of_slot]
    build_slot = lo[probe_of_slot] + within
    nb = table.sorted_keys.shape[0]
    build_slot = jnp.minimum(build_slot, nb - 1)
    out_live = out_slot < jnp.minimum(total, C)
    build_rows = jnp.where(out_live, table.order[build_slot], 0)
    probe_rows = jnp.where(out_live, probe_of_slot, 0)
    return probe_rows, build_rows, out_live, total
