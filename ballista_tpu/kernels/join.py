"""Join kernels.

TPU-native replacement for the reference's ``HashJoinExec`` (reference:
rust/core/proto/ballista.proto:399-407, HashJoinExecNode with on-keys and
join type). A CPU-style linked hash table doesn't map to the MXU/VPU, so the
build side is *sorted* and the probe side does a vectorized binary search
(XLA lowers searchsorted to a fused gather loop):

- ``build_lookup`` sorts the build keys once;
- ``probe_unique`` handles the FK->PK joins that dominate TPC-H (build keys
  unique): one searchsorted + one gather, no row expansion;
- ``probe_expand`` (general many-to-many) computes per-probe match counts
  and materializes matches up to a static output capacity.

Keys are single int64 columns (dict codes / ints / dates cast to int64).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

INT64_SENTINEL = jnp.iinfo(jnp.int64).max


@dataclass
class BuildTable:
    """Build side of a join: always carries the sorted representation;
    near-dense integer keys additionally carry a direct-index table
    (``dense_rows``/``dense_base``) so probes are ONE gather instead of
    a ~log2(Nb)-step binary search — the decisive difference on TPU,
    where each searchsorted step is a dependent gather."""

    sorted_keys: jax.Array  # int64 [Nb] (dead rows = sentinel, at end)
    order: jax.Array  # int32 [Nb] original row index per sorted slot
    num_live: jax.Array  # int32 scalar
    dense_rows: Optional[jax.Array] = None  # int32 [R]: key-base -> row | -1
    dense_base: Optional[jax.Array] = None  # int64 scalar


jax.tree_util.register_dataclass(
    BuildTable,
    data_fields=["sorted_keys", "order", "num_live", "dense_rows",
                 "dense_base"],
    meta_fields=[],
)


def build_lookup(keys: jax.Array, live: jax.Array) -> BuildTable:
    keyed = jnp.where(live, keys, INT64_SENTINEL)
    order = jnp.argsort(keyed, stable=True).astype(jnp.int32)
    return BuildTable(keyed[order], order, jnp.sum(live.astype(jnp.int32)))


def build_dense(keys: jax.Array, live: jax.Array, base: jax.Array,
                size: int) -> Tuple[jax.Array, jax.Array]:
    """Direct-index build: scatter live rows into a [size] table keyed by
    ``key - base``. Returns (dense_rows int32 [size] with -1 = empty,
    has_duplicates bool scalar). ``size`` is static (shape)."""
    n = keys.shape[0]
    idx = (keys - base).astype(jnp.int64)
    # dead rows scatter out of bounds -> dropped
    slot = jnp.where(live, idx, jnp.int64(size)).astype(jnp.int32)
    counts = jnp.zeros((size,), jnp.int32).at[slot].add(
        1, mode="drop")
    rows = jnp.full((size,), -1, jnp.int32).at[slot].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop")
    return rows, jnp.any(counts > 1)


def build_sorted_with_unique(
    keys: jax.Array, live: jax.Array
) -> Tuple[BuildTable, jax.Array]:
    """Sorted build table + a uniqueness flag computed ON DEVICE, so the
    caller fetches one scalar instead of the whole sorted key array."""
    table = build_lookup(keys, live)
    sk = table.sorted_keys
    n = sk.shape[0]
    pos = jnp.arange(1, n, dtype=jnp.int32)
    dup = jnp.any(jnp.logical_and(sk[1:] == sk[:-1], pos < table.num_live))
    return table, jnp.logical_not(dup)


def probe_unique(
    table: BuildTable, probe_keys: jax.Array, probe_live: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Probe assuming unique build keys (FK->PK join).

    Returns (build_row_indices int32 [Np], matched bool [Np]). Unmatched
    probes get index 0 with matched=False; the caller masks them out
    (inner join) or null-fills (left join).
    """
    if table.dense_rows is not None:
        size = table.dense_rows.shape[0]
        idx = probe_keys - table.dense_base
        in_range = jnp.logical_and(idx >= 0, idx < size)
        slot = jnp.clip(idx, 0, size - 1).astype(jnp.int32)
        row = jnp.take(table.dense_rows, slot)
        matched = jnp.logical_and(
            jnp.logical_and(in_range, row >= 0), probe_live)
        return jnp.where(matched, row, 0), matched
    nb = table.sorted_keys.shape[0]
    idx = jnp.searchsorted(table.sorted_keys, probe_keys, side="left")
    idx = jnp.minimum(idx, nb - 1).astype(jnp.int32)
    hit = jnp.equal(table.sorted_keys[idx], probe_keys)
    hit = jnp.logical_and(hit, probe_keys != INT64_SENTINEL)
    matched = jnp.logical_and(hit, probe_live)
    build_rows = jnp.where(matched, table.order[idx], 0)
    return build_rows, matched


def probe_semi(
    table: BuildTable, probe_keys: jax.Array, probe_live: jax.Array
) -> jax.Array:
    """Semi-join mask: probe rows whose key exists in the build side."""
    _, matched = probe_unique(table, probe_keys, probe_live)
    return matched


def probe_counts(table: BuildTable, probe_keys: jax.Array) -> jax.Array:
    """Number of build matches per probe key (for many-to-many planning)."""
    lo = jnp.searchsorted(table.sorted_keys, probe_keys, side="left")
    hi = jnp.searchsorted(table.sorted_keys, probe_keys, side="right")
    return (hi - lo).astype(jnp.int32)


def probe_expand(
    table: BuildTable,
    probe_keys: jax.Array,
    probe_live: jax.Array,
    out_capacity: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """General inner join with row expansion to a static output capacity.

    Returns (probe_row_idx [C], build_row_idx [C], out_live [C],
    total_matches scalar). If total_matches > out_capacity the result is
    truncated; callers detect via the returned total and re-run with a
    bigger capacity (host-side fallback policy).
    """
    keyed = jnp.where(probe_live, probe_keys, INT64_SENTINEL - 1)
    lo = jnp.searchsorted(table.sorted_keys, keyed, side="left")
    hi = jnp.searchsorted(table.sorted_keys, keyed, side="right")
    counts = (hi - lo).astype(jnp.int32)
    counts = jnp.where(probe_live, counts, 0)
    offsets = jnp.cumsum(counts) - counts  # exclusive prefix sum
    total = jnp.sum(counts)

    C = out_capacity
    out_slot = jnp.arange(C, dtype=jnp.int32)
    # For each output slot, find its probe row: the row whose [offset,
    # offset+count) window contains the slot.
    probe_of_slot = (
        jnp.searchsorted(offsets + counts, out_slot, side="right")
    ).astype(jnp.int32)
    np_rows = probe_keys.shape[0]
    probe_of_slot = jnp.minimum(probe_of_slot, np_rows - 1)
    within = out_slot - offsets[probe_of_slot]
    build_slot = lo[probe_of_slot] + within
    nb = table.sorted_keys.shape[0]
    build_slot = jnp.minimum(build_slot, nb - 1)
    out_live = out_slot < jnp.minimum(total, C)
    build_rows = jnp.where(out_live, table.order[build_slot], 0)
    probe_rows = jnp.where(out_live, probe_of_slot, 0)
    return probe_rows, build_rows, out_live, total
