"""Multi-key sort kernel.

TPU-native replacement for the reference's ``SortExec`` physical operator
(reference: rust/core/proto/ballista.proto:424-431, SortExecNode). Uses
chained stable argsorts (least-significant key first), which XLA lowers to
its native sort; dead (filtered) rows sink to the end so downstream
operators can keep static shapes.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def sort_permutation(
    keys: Sequence[Tuple[jax.Array, bool]],  # (values, ascending), major key first
    live: jax.Array,
) -> jax.Array:
    """Return int32 permutation ordering live rows by keys, dead rows last."""
    n = live.shape[0]
    perm = jnp.arange(n, dtype=jnp.int32)
    # least-significant key first; each pass is stable so earlier keys win
    for values, ascending in reversed(list(keys)):
        k = values[perm]
        k = _orderable(k, ascending)
        perm = perm[jnp.argsort(k, stable=True)]
    # final pass: dead rows last (stable keeps the key order among live rows)
    dead = jnp.logical_not(live)[perm]
    perm = perm[jnp.argsort(dead, stable=True)]
    return perm


def _orderable(v: jax.Array, ascending: bool) -> jax.Array:
    if v.dtype == jnp.bool_:
        v = v.astype(jnp.int32)
    if jnp.issubdtype(v.dtype, jnp.floating):
        return v if ascending else -v
    if ascending:
        return v
    # descending integers: flip via bitwise-not to avoid negation overflow
    return ~v
