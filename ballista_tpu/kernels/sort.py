"""Multi-key sort kernel.

TPU-native replacement for the reference's ``SortExec`` physical operator
(reference: rust/core/proto/ballista.proto:424-431, SortExecNode). Uses a
single multi-operand lexicographic ``lax.sort``, XLA's native sort form;
dead (filtered) rows sink to the end so downstream operators can keep
static shapes.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def sort_permutation(
    keys: Sequence[Tuple[jax.Array, bool]],  # (values, ascending), major key first
    live: jax.Array,
) -> jax.Array:
    """Return int32 permutation ordering live rows by keys, dead rows last.

    One multi-operand lexicographic ``lax.sort`` (dead flag, then keys in
    major-to-minor order, row index as payload) instead of chained stable
    argsorts: cheaper to trace, and the single-sort form is what XLA
    lowers best on TPU."""
    n = live.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    dead = jnp.logical_not(live)
    ops = [dead] + [_orderable(v, asc) for v, asc in keys] + [idx]
    return jax.lax.sort(tuple(ops), num_keys=1 + len(keys),
                        is_stable=True)[-1]


def _orderable(v: jax.Array, ascending: bool) -> jax.Array:
    if v.dtype == jnp.bool_:
        v = v.astype(jnp.int32)
    if jnp.issubdtype(v.dtype, jnp.floating):
        return v if ascending else -v
    if ascending:
        return v
    # descending integers: flip via bitwise-not to avoid negation overflow
    return ~v
