"""JAX/XLA compute kernels for ballista-tpu physical operators.

These are the TPU-native replacement for DataFusion's Rust compute kernels
used by the reference's physical operators (reference:
rust/core/proto/ballista.proto:294-312 lists the 15 operators they power).
Everything in this package is traceable and composes into one XLA program
per query stage.
"""
