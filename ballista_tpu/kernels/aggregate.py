"""Grouped and ungrouped aggregation kernels.

TPU-native replacement for the reference's ``HashAggregateExec`` (reference:
rust/core/proto/ballista.proto:370-384; planner splits it into
Partial->shuffle->Final at rust/scheduler/src/planner.rs:149-171 — our
physical operators follow the same two-phase decomposition).

A CPU hash table is hostile to XLA, so grouping is *sort-based*:

1. rows are ordered by ONE multi-operand ``lax.sort`` (lexicographic over
   [dead-flag, key columns..., row-index payload]; no bit-packing, so any
   number/width of key columns works), sinking dead rows to the end;
2. run-boundary detection (ANY key differs from the predecessor) + a prefix
   sum assigns dense group ids;
3. ``segment_sum/min/max`` with ``indices_are_sorted=True`` reduces each
   aggregate in one pass.

SQL semantics carried through:
- NULL group keys form their own group (each key column contributes its
  validity as an implicit sort/boundary key);
- NULL inputs are excluded from aggregates, and each aggregate reports a
  per-group validity ("any non-NULL input seen"), so all-NULL groups yield
  NULL rather than the reduction identity.

Everything is static-shaped: the caller supplies ``group_capacity`` and gets
fixed-size outputs plus a ``group_valid`` mask; ``num_groups`` reports the
TRUE group count so callers can detect overflow and retry with a larger
capacity. Sums over decimals stay in int64, so results are exact (TPU f64
is avoided entirely).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..errors import ExecutionError


# ---------------------------------------------------------------------------
# Grouped aggregation
# ---------------------------------------------------------------------------


def _run_boundaries(cols: Sequence[jax.Array]) -> jax.Array:
    """bool [N]: row i starts a new run of the (sorted) key columns —
    ANY column differs from its predecessor (row 0 always starts one).
    Shared by the sort-based grouping and the distinct-count kernel so
    their byte-identical ordering contract stays in lockstep."""
    first = None
    for ks in cols:
        diff = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), ks[1:] != ks[:-1]])
        first = diff if first is None else jnp.logical_or(first, diff)
    return first


@dataclass
class AggInput:
    """One aggregate to compute: op in {sum, count, min, max}."""

    op: str
    values: Optional[jax.Array]  # None for count(*)
    validity: Optional[jax.Array]  # None = all valid


@dataclass
class GroupedResult:
    rep_indices: jax.Array  # int32 [G] original row index of each group's first row
    group_valid: jax.Array  # bool [G]
    num_groups: jax.Array  # int32 scalar (TRUE count; may exceed capacity G)
    aggregates: List[jax.Array]  # each [G]
    agg_valid: List[jax.Array]  # bool [G] per aggregate ("any input seen")


jax.tree_util.register_dataclass(
    GroupedResult,
    data_fields=["rep_indices", "group_valid", "num_groups", "aggregates",
                 "agg_valid"],
    meta_fields=[],
)


def grouped_aggregate(
    keys: Sequence[jax.Array],  # one or more [N] key columns (ints/codes)
    live: jax.Array,  # bool [N] live-row mask
    aggs: Sequence[AggInput],
    group_capacity: int,
    key_validities: Optional[Sequence[Optional[jax.Array]]] = None,
) -> GroupedResult:
    keys = list(keys)
    if not keys:
        raise ExecutionError("grouped_aggregate requires at least one key")
    if key_validities is None:
        key_validities = [None] * len(keys)
    # NULL keys group together: each nullable key contributes (validity,
    # value-or-0) as the effective sort/boundary pair
    eff_keys: List[jax.Array] = []
    for k, kv in zip(keys, key_validities):
        if kv is not None:
            eff_keys.append(kv.astype(jnp.int32))
            eff_keys.append(jnp.where(kv, k, jnp.zeros((), k.dtype)))
        else:
            eff_keys.append(k)

    n = live.shape[0]
    # ONE multi-operand lexicographic sort (dead flag first, then keys,
    # then the row index as payload) replaces K chained stable argsorts +
    # per-key gathers: a single lax.sort is both cheaper to trace and the
    # form XLA lowers best on TPU. Sorted keys fall out as byproducts, so
    # boundary detection needs no extra gathers either.
    dead = jnp.logical_not(live)
    idx = jnp.arange(n, dtype=jnp.int32)
    if len(eff_keys) == 1:
        # PRESORTED fast path (runtime-branched, no host sync): group-by
        # over a clustered key (TPC-H q18's l_orderkey — file order) can
        # skip the O(N log N) sort entirely when the key is already
        # non-decreasing over a contiguous live prefix. lax.cond executes
        # only the taken branch, so unsorted inputs pay one O(N) check.
        k0 = eff_keys[0]
        live_prefix = jnp.all(live[1:] <= live[:-1])  # no live after dead
        nondecreasing = jnp.all(
            jnp.logical_or(k0[1:] >= k0[:-1], jnp.logical_not(live[1:]))
        )
        presorted = jnp.logical_and(live_prefix, nondecreasing)

        def _fast(_):
            return idx, (k0,), live

        def _slow(_):
            ops = jax.lax.sort((dead, k0, idx), num_keys=2, is_stable=True)
            return ops[-1], (ops[1],), jnp.logical_not(ops[0])

        order, sorted_keys, live_sorted = jax.lax.cond(
            presorted, _fast, _slow, None)
    else:
        sorted_ops = jax.lax.sort(
            (dead, *eff_keys, idx), num_keys=1 + len(eff_keys),
            is_stable=True
        )
        order = sorted_ops[-1]
        sorted_keys = sorted_ops[1:-1]
        live_sorted = jnp.logical_not(sorted_ops[0])

    # a row starts a new group if live and ANY key differs from predecessor
    starts = jnp.logical_and(_run_boundaries(sorted_keys), live_sorted)
    gid = jnp.cumsum(starts.astype(jnp.int32)) - 1  # [-1..G-1]
    num_groups = jnp.sum(starts.astype(jnp.int32))
    # dead rows / overflow go to the trash segment group_capacity
    seg = jnp.where(live_sorted, jnp.minimum(gid, group_capacity), group_capacity)

    G = group_capacity

    # representative original-row index per group (first member in sort order)
    pos = jnp.arange(n, dtype=jnp.int32)
    first_pos = jax.ops.segment_min(
        jnp.where(live_sorted, pos, n), seg, num_segments=G + 1,
        indices_are_sorted=True,
    )[:G]
    safe_first = jnp.minimum(first_pos, n - 1)
    rep_indices = order[safe_first].astype(jnp.int32)

    group_valid = jnp.arange(G, dtype=jnp.int32) < num_groups

    results: List[jax.Array] = []
    valid_results: List[jax.Array] = []
    for a in aggs:
        valid = a.validity[order] if a.validity is not None else None
        if a.op == "count":
            v = jnp.ones((n,), jnp.int64)
            if valid is not None:
                v = jnp.where(valid, v, 0)
            r = jax.ops.segment_sum(v, seg, num_segments=G + 1,
                                    indices_are_sorted=True)[:G]
            va = group_valid
        else:
            if a.values is None:
                raise ExecutionError(f"{a.op} requires input values")
            v = a.values[order]
            if a.op == "sum":
                if valid is not None:
                    v = jnp.where(valid, v, jnp.zeros((), v.dtype))
                r = jax.ops.segment_sum(v, seg, num_segments=G + 1,
                                        indices_are_sorted=True)[:G]
            elif a.op == "min":
                if valid is not None:
                    v = jnp.where(valid, v, _max_ident(v.dtype))
                r = jax.ops.segment_min(v, seg, num_segments=G + 1,
                                        indices_are_sorted=True)[:G]
            elif a.op == "max":
                if valid is not None:
                    v = jnp.where(valid, v, _min_ident(v.dtype))
                r = jax.ops.segment_max(v, seg, num_segments=G + 1,
                                        indices_are_sorted=True)[:G]
            else:
                raise ExecutionError(f"unknown aggregate op {a.op}")
            if valid is not None:
                seen = jax.ops.segment_max(
                    valid.astype(jnp.int32), seg, num_segments=G + 1,
                    indices_are_sorted=True,
                )[:G]
                va = jnp.logical_and(group_valid, seen > 0)
            else:
                va = group_valid
        results.append(jnp.where(va, r, jnp.zeros((), r.dtype)))
        valid_results.append(va)

    return GroupedResult(rep_indices, group_valid, num_groups, results,
                         valid_results)


def grouped_distinct_count(
    group_keys: Sequence[jax.Array],  # [N] key columns (ints/codes)
    live: jax.Array,  # bool [N] live-row mask
    distinct_key: jax.Array,  # [N] the COUNT(DISTINCT x) column
    group_capacity: int,
    group_validities: Optional[Sequence[Optional[jax.Array]]] = None,
    distinct_validity: Optional[jax.Array] = None,
) -> GroupedResult:
    """Single-pass COUNT(DISTINCT x) GROUP BY g1..gk.

    The SQL planner rewrites COUNT(DISTINCT) into a two-level aggregate
    (dedup on (g, x), then count per g) — three sort-based groupings over
    the same rows. This kernel needs ONE lexicographic sort over
    [dead, g.., x, idx]: a row opens a *group* when any g-key differs
    from its predecessor, and opens a *distinct pair* when additionally x
    differs — the per-group pair-start count IS the distinct count.

    SQL semantics match the two-level rewrite exactly: NULL group keys
    form their own group (validity rides the sort key), NULL x values
    are never counted (but a group whose every x is NULL still appears,
    with count 0). Input duplicates are fine — only pair boundaries
    count. Output group order equals ``grouped_aggregate``'s (sorted by
    the effective key encoding), so swapping the rewrite for this kernel
    is byte-identical. Result carries one aggregate: the int64 counts.
    """
    group_keys = list(group_keys)
    if not group_keys:
        raise ExecutionError("grouped_distinct_count requires a group key")
    if group_validities is None:
        group_validities = [None] * len(group_keys)
    eff_g: List[jax.Array] = []
    for k, kv in zip(group_keys, group_validities):
        if kv is not None:
            eff_g.append(kv.astype(jnp.int32))
            eff_g.append(jnp.where(kv, k, jnp.zeros((), k.dtype)))
        else:
            eff_g.append(k)
    eff_d: List[jax.Array] = []
    if distinct_validity is not None:
        eff_d.append(distinct_validity.astype(jnp.int32))
        eff_d.append(jnp.where(distinct_validity, distinct_key,
                               jnp.zeros((), distinct_key.dtype)))
    else:
        eff_d.append(distinct_key)

    n = live.shape[0]
    dead = jnp.logical_not(live)
    idx = jnp.arange(n, dtype=jnp.int32)
    ops = jax.lax.sort((dead, *eff_g, *eff_d, idx),
                       num_keys=1 + len(eff_g) + len(eff_d),
                       is_stable=True)
    order = ops[-1]
    live_sorted = jnp.logical_not(ops[0])
    sg = ops[1:1 + len(eff_g)]
    sd = ops[1 + len(eff_g):-1]

    g_first = _run_boundaries(sg)
    pair_first = jnp.logical_or(g_first, _run_boundaries(sd))
    starts = jnp.logical_and(g_first, live_sorted)
    gid = jnp.cumsum(starts.astype(jnp.int32)) - 1
    num_groups = jnp.sum(starts.astype(jnp.int32))
    G = group_capacity
    seg = jnp.where(live_sorted, jnp.minimum(gid, G), G)

    # pairs whose x is NULL exist as groups' rows but never count
    counted = jnp.logical_and(pair_first, live_sorted)
    if distinct_validity is not None:
        counted = jnp.logical_and(counted, distinct_validity[order])
    counts = jax.ops.segment_sum(
        counted.astype(jnp.int64), seg, num_segments=G + 1,
        indices_are_sorted=True)[:G]

    pos = jnp.arange(n, dtype=jnp.int32)
    first_pos = jax.ops.segment_min(
        jnp.where(live_sorted, pos, n), seg, num_segments=G + 1,
        indices_are_sorted=True,
    )[:G]
    rep_indices = order[jnp.minimum(first_pos, n - 1)].astype(jnp.int32)
    group_valid = jnp.arange(G, dtype=jnp.int32) < num_groups
    counts = jnp.where(group_valid, counts, jnp.zeros((), counts.dtype))
    return GroupedResult(rep_indices, group_valid, num_groups, [counts],
                         [group_valid])


def _max_ident(dt):
    if jnp.issubdtype(dt, jnp.integer):
        return jnp.iinfo(dt).max
    return jnp.asarray(jnp.inf, dt)


def _min_ident(dt):
    if jnp.issubdtype(dt, jnp.integer):
        return jnp.iinfo(dt).min
    return jnp.asarray(-jnp.inf, dt)


# ---------------------------------------------------------------------------
# Dense grouping fast path: group ids already small dense ints (dictionary
# codes / booleans with known cardinality). No sort — one fused masked
# reduction per aggregate, which is the MXU/VPU-friendly shape for TPC-H
# q1-style tiny-cardinality GROUP BYs.
# ---------------------------------------------------------------------------


# On CPU the kernel runs in (slow, python-looped) interpret mode, so the
# automatic gate only admits batches small enough for CI validation.
_PALLAS_INTERPRET_MAX_ROWS = 4096


def _pallas_mode() -> str:
    """'' (off) | 'on' (compiled kernel) | 'interpret' | 'auto'.

    Default (no env): OFF everywhere in production — the round-3
    on-chip A/B (recorded in bench.py's JSON every run) measured the XLA
    dense path at ~4x the Pallas one-hot matmul for q1's tiny group
    counts (G<=8 leaves the MXU idle and the limb split adds ~7x HBM
    traffic), and interpret mode is a python loop nobody should pay
    outside tests. Under pytest, small CPU batches auto-route through
    interpret mode so the kernel stays exactness-tested in every run.
    Explicit ``BALLISTA_PALLAS`` (off/on/interpret) always wins; an
    unrecognized value warns once and means off.
    """
    import os

    env = os.environ.get("BALLISTA_PALLAS", "").lower()
    if not env:
        return "auto"
    if env in ("off", "0", "no", "false"):
        return ""
    if env in ("on", "1", "yes", "true"):
        return "on"
    if env == "interpret":
        return "interpret"
    if env not in _warned_env:
        import logging

        logging.getLogger("ballista.kernels").warning(
            "unrecognized BALLISTA_PALLAS=%r: treating as off "
            "(expected off/on/interpret)", env)
        _warned_env.append(env)
    return ""


_warned_env: list = []


def _pallas_additive(a: AggInput) -> bool:
    """True for aggregates the Pallas kernel computes (integer sums and
    counts, validity-masked or not); min/max and float sums stay on the
    XLA dense path (split per aggregate, same program)."""
    if a.op == "count":
        return True
    return (a.op == "sum" and a.values is not None
            and jnp.issubdtype(a.values.dtype, jnp.integer))


def dense_grouped_aggregate(
    gids: jax.Array,  # int32 [N] in [0, num_groups)
    live: jax.Array,  # bool [N]
    aggs: Sequence[AggInput],
    num_groups: int,
) -> GroupedResult:
    mode = _pallas_mode()
    if mode == "auto":
        import os

        if "PYTEST_CURRENT_TEST" in os.environ and \
                jax.default_backend() == "cpu" and \
                gids.shape[0] <= _PALLAS_INTERPRET_MAX_ROWS:
            mode = "interpret"  # CI: keep the kernel exactness-tested
        else:
            mode = ""  # production default is XLA: measured faster
    if mode in ("on", "interpret"):
        additive = [a for a in aggs if _pallas_additive(a)]
        rest = [a for a in aggs if not _pallas_additive(a)]
        if any(a.op == "sum" for a in additive):
            res_p = _dense_grouped_pallas(
                gids, live, additive, num_groups,
                interpret=(mode == "interpret"),
            )
            if not rest:
                return res_p
            res_x = _dense_grouped_xla(gids, live, rest, num_groups)
            results, valids = [], []
            ip = ix = 0
            for a in aggs:
                if _pallas_additive(a):
                    results.append(res_p.aggregates[ip])
                    valids.append(res_p.agg_valid[ip])
                    ip += 1
                else:
                    results.append(res_x.aggregates[ix])
                    valids.append(res_x.agg_valid[ix])
                    ix += 1
            return GroupedResult(res_p.rep_indices, res_p.group_valid,
                                 res_p.num_groups, results, valids)
    return _dense_grouped_xla(gids, live, aggs, num_groups)


def _dense_grouped_xla(
    gids: jax.Array,
    live: jax.Array,
    aggs: Sequence[AggInput],
    num_groups: int,
) -> GroupedResult:
    n = gids.shape[0]
    groups = jnp.arange(num_groups, dtype=jnp.int32)
    # [N, G] membership mask, fused into each reduction (never materialized
    # at full width for small G)
    member = jnp.logical_and(live[:, None], gids[:, None] == groups[None, :])

    group_valid = jnp.any(member, axis=0)
    # argmax returns the FIRST True row per group
    rep_indices = jnp.argmax(member, axis=0).astype(jnp.int32)
    num_present = jnp.sum(group_valid.astype(jnp.int32))

    results: List[jax.Array] = []
    valid_results: List[jax.Array] = []
    for a in aggs:
        m = member
        if a.validity is not None:
            m = jnp.logical_and(m, a.validity[:, None])
        if a.op == "count":
            r = jnp.sum(m.astype(jnp.int64), axis=0)
            va = group_valid
        else:
            if a.values is None:
                raise ExecutionError(f"{a.op} requires input values")
            v = a.values[:, None]
            if a.op == "sum":
                r = jnp.sum(jnp.where(m, v, jnp.zeros((), v.dtype)), axis=0)
            elif a.op == "min":
                r = jnp.min(jnp.where(m, v, _max_ident(v.dtype)), axis=0)
            elif a.op == "max":
                r = jnp.max(jnp.where(m, v, _min_ident(v.dtype)), axis=0)
            else:
                raise ExecutionError(f"unknown aggregate op {a.op}")
            va = jnp.any(m, axis=0)
        results.append(jnp.where(va, r, jnp.zeros((), r.dtype)))
        valid_results.append(va)

    return GroupedResult(rep_indices, group_valid, num_present, results,
                         valid_results)


def dense_grouped_scatter(
    gids: jax.Array,  # int32 [N] in [0, num_groups)
    live: jax.Array,  # bool [N]
    aggs: Sequence[AggInput],
    num_groups: int,
) -> GroupedResult:
    """O(N) scatter-based dense grouping for group counts where
    ``_dense_grouped_xla``'s [N, G] membership product is prohibitive
    (ranged-integer keys: thousands to millions of groups). Same
    semantics: non-compact groups, ``group_valid`` marks occupancy,
    per-aggregate validity is "any non-NULL input seen"."""
    n = gids.shape[0]
    G = num_groups
    slot = jnp.where(live, gids, G).astype(jnp.int32)  # dead -> dropped
    rows = jnp.arange(n, dtype=jnp.int32)
    first = jnp.full((G,), n, jnp.int32).at[slot].min(rows, mode="drop")
    group_valid = first < n
    rep_indices = jnp.minimum(first, n - 1)
    num_present = jnp.sum(group_valid.astype(jnp.int32))

    results: List[jax.Array] = []
    valid_results: List[jax.Array] = []
    for a in aggs:
        valid = a.validity
        if a.op == "count":
            v = jnp.ones((n,), jnp.int64)
            if valid is not None:
                v = jnp.where(valid, v, 0)
            r = jnp.zeros((G,), jnp.int64).at[slot].add(v, mode="drop")
            va = group_valid
        else:
            if a.values is None:
                raise ExecutionError(f"{a.op} requires input values")
            v = a.values
            if a.op == "sum":
                if valid is not None:
                    v = jnp.where(valid, v, jnp.zeros((), v.dtype))
                r = jnp.zeros((G,), v.dtype).at[slot].add(v, mode="drop")
            elif a.op == "min":
                if valid is not None:
                    v = jnp.where(valid, v, _max_ident(v.dtype))
                r = jnp.full((G,), _max_ident(v.dtype), v.dtype) \
                    .at[slot].min(v, mode="drop")
            elif a.op == "max":
                if valid is not None:
                    v = jnp.where(valid, v, _min_ident(v.dtype))
                r = jnp.full((G,), _min_ident(v.dtype), v.dtype) \
                    .at[slot].max(v, mode="drop")
            else:
                raise ExecutionError(f"unknown aggregate op {a.op}")
            if valid is not None:
                seen = jnp.zeros((G,), jnp.int32).at[slot].max(
                    valid.astype(jnp.int32), mode="drop")
                va = jnp.logical_and(group_valid, seen > 0)
            else:
                va = group_valid
        results.append(jnp.where(va, r, jnp.zeros((), r.dtype)))
        valid_results.append(va)

    return GroupedResult(rep_indices, group_valid, num_present, results,
                         valid_results)


def _dense_grouped_pallas(gids, live, aggs, num_groups,
                          interpret: bool) -> GroupedResult:
    """Integer sums/counts via the fused Pallas kernel
    (kernels/pallas_agg.py); representatives via cheap XLA ops.

    Validity handling happens BEFORE the kernel: masked-out sum inputs
    are zeroed (sum semantics), and each validity-masked aggregate gets
    one extra 0/1 value column whose per-group sum is its valid-input
    count — so the kernel only ever sums, and per-aggregate NULL
    semantics (all-NULL group -> NULL) survive exactly."""
    from .pallas_agg import dense_grouped_sums

    values: List[jax.Array] = []
    # per agg: ("count", None) | ("countv", vcol) | ("sum", col, vcol|None)
    plan = []
    vmask_col: dict = {}  # id(validity) -> value-column index of its mask

    def mask_col(validity) -> int:
        key = id(validity)
        if key not in vmask_col:
            vmask_col[key] = len(values)
            values.append(validity.astype(jnp.int64))
        return vmask_col[key]

    for a in aggs:
        if a.op == "count":
            if a.validity is None:
                plan.append(("count", None, None))
            else:
                plan.append(("countv", mask_col(a.validity), None))
        else:  # integer sum
            v = a.values.astype(jnp.int64)
            vcol = None
            if a.validity is not None:
                v = jnp.where(a.validity, v, jnp.int64(0))
                vcol = mask_col(a.validity)  # may append; BEFORE len()
            plan.append(("sum", len(values), vcol))
            values.append(v)

    sums, counts = dense_grouped_sums(gids, live, values, num_groups,
                                      interpret=interpret)
    n = gids.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    first = jax.ops.segment_min(jnp.where(live, pos, n), gids,
                                num_segments=num_groups)
    rep_indices = jnp.minimum(first, n - 1).astype(jnp.int32)
    group_valid = counts > 0
    num_present = jnp.sum(group_valid.astype(jnp.int32))
    results: List[jax.Array] = []
    valid_results: List[jax.Array] = []
    for a, (kind, col, vcol) in zip(aggs, plan):
        if kind == "count":
            results.append(counts)
            valid_results.append(group_valid)
        elif kind == "countv":
            results.append(sums[col])
            valid_results.append(group_valid)
        else:
            va = group_valid if vcol is None else (sums[vcol] > 0)
            out = sums[col].astype(a.values.dtype)
            results.append(jnp.where(va, out, jnp.zeros((), out.dtype)))
            valid_results.append(va)
    return GroupedResult(rep_indices, group_valid, num_present, results,
                         valid_results)


# ---------------------------------------------------------------------------
# Ungrouped aggregation (whole-batch reductions)
# ---------------------------------------------------------------------------


def scalar_aggregate(
    live: jax.Array, aggs: Sequence[AggInput]
) -> Tuple[List[jax.Array], List[jax.Array]]:
    """Returns (values, validities) — validity False when no valid input."""
    out: List[jax.Array] = []
    valid_out: List[jax.Array] = []
    for a in aggs:
        valid = live
        if a.validity is not None:
            valid = jnp.logical_and(valid, a.validity)
        any_valid = jnp.any(valid)
        if a.op == "count":
            out.append(jnp.sum(valid.astype(jnp.int64)))
            valid_out.append(jnp.ones((), jnp.bool_))
            continue
        v = a.values
        if a.op == "sum":
            r = jnp.sum(jnp.where(valid, v, jnp.zeros((), v.dtype)))
        elif a.op == "min":
            r = jnp.min(jnp.where(valid, v, _max_ident(v.dtype)))
        elif a.op == "max":
            r = jnp.max(jnp.where(valid, v, _min_ident(v.dtype)))
        else:
            raise ExecutionError(f"unknown aggregate op {a.op}")
        out.append(jnp.where(any_valid, r, jnp.zeros((), r.dtype)))
        valid_out.append(any_valid)
    return out, valid_out


# ---------------------------------------------------------------------------
# Exact fixed-point average: sum/count scaled to 10^6 without overflowing
# ---------------------------------------------------------------------------


def avg_fixed(sum_: jax.Array, count: jax.Array, in_scale: int) -> jax.Array:
    """(sum / count) scaled to Decimal(6), overflow-safe.

    Splits the division: A = q*M + (r*M)//count with q=sum//count,
    r=sum%count, M=10^(6-in_scale) — r*M stays < count*M so the only
    overflow left is a logical |avg| >= ~9.2e12, documented out of range.
    """
    s = sum_.astype(jnp.int64)
    if in_scale > 6:
        s = jax.lax.div(s, jnp.int64(10 ** (in_scale - 6)))
        in_scale = 6
    m = jnp.int64(10 ** (6 - in_scale))
    c = jnp.maximum(count.astype(jnp.int64), 1)
    q = jax.lax.div(s, c)
    r = jax.lax.rem(s, c)
    return q * m + jax.lax.div(r * m, c)
