"""Grouped and ungrouped aggregation kernels.

TPU-native replacement for the reference's ``HashAggregateExec`` (reference:
rust/core/proto/ballista.proto:370-384; planner splits it into
Partial->shuffle->Final at rust/scheduler/src/planner.rs:149-171 — our
physical operators follow the same two-phase decomposition).

A CPU hash table is hostile to XLA, so grouping is *sort-based*:

1. pack the group key columns into one int64 composite key;
2. stable-sort rows by key (dead rows get a +inf sentinel and sink to the
   end);
3. run-boundary detection + prefix-sum assigns dense group ids;
4. ``segment_sum/min/max`` with ``indices_are_sorted=True`` reduces each
   aggregate in one pass.

Everything is static-shaped: the caller supplies ``group_capacity`` (the max
number of distinct groups an output batch can carry) and gets fixed-size
outputs plus a ``group_valid`` mask. Sums over decimals stay in int64, so
results are exact (TPU f64 is avoided entirely).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..errors import ExecutionError

INT64_SENTINEL = jnp.iinfo(jnp.int64).max


# ---------------------------------------------------------------------------
# Key packing
# ---------------------------------------------------------------------------


def bits_for(n: int) -> int:
    """Bits needed to represent values in [0, n]."""
    b = 1
    while (1 << b) <= n:
        b += 1
    return b


def pack_keys(columns: Sequence[Tuple[jax.Array, int]]) -> jax.Array:
    """Pack non-negative int columns (value, bit_width) into one int64 key.

    Total width must be <= 62 (sign bit + sentinel headroom). Values are
    assumed normalized to [0, 2^width). The first column is the most
    significant, so packed-key order == lexicographic column order.
    """
    total = sum(w for _, w in columns)
    if total > 62:
        raise ExecutionError(f"composite group key needs {total} bits > 62")
    out = None
    for values, width in columns:
        v = values.astype(jnp.int64) & ((1 << width) - 1)
        out = v if out is None else (out << width) | v
    return out if out is not None else jnp.zeros((), jnp.int64)


# ---------------------------------------------------------------------------
# Grouped aggregation
# ---------------------------------------------------------------------------


@dataclass
class AggInput:
    """One aggregate to compute: op in {sum, count, min, max}."""

    op: str
    values: Optional[jax.Array]  # None for count(*)
    validity: Optional[jax.Array]  # None = all valid


@dataclass
class GroupedResult:
    rep_indices: jax.Array  # int32 [G] original row index of each group's first row
    group_valid: jax.Array  # bool [G]
    num_groups: jax.Array  # int32 scalar
    aggregates: List[jax.Array]  # each [G]


jax.tree_util.register_dataclass(
    GroupedResult,
    data_fields=["rep_indices", "group_valid", "num_groups", "aggregates"],
    meta_fields=[],
)


def grouped_aggregate(
    keys: jax.Array,  # int64 [N] composite group key
    live: jax.Array,  # bool [N] live-row mask
    aggs: Sequence[AggInput],
    group_capacity: int,
) -> GroupedResult:
    n = keys.shape[0]
    keyed = jnp.where(live, keys, INT64_SENTINEL)
    order = jnp.argsort(keyed, stable=True)  # dead rows sink to the end
    sk = keyed[order]
    live_sorted = live[order]

    # a row starts a new group if live and key differs from predecessor
    first = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sk[1:] != sk[:-1]]
    )
    starts = jnp.logical_and(first, live_sorted)
    gid = jnp.cumsum(starts.astype(jnp.int32)) - 1  # [-1..G-1]
    num_groups = jnp.sum(starts.astype(jnp.int32))
    # dead rows / overflow go to the trash segment group_capacity
    seg = jnp.where(live_sorted, jnp.minimum(gid, group_capacity), group_capacity)

    G = group_capacity

    # representative original-row index per group (first member in sort order)
    pos = jnp.arange(n, dtype=jnp.int32)
    first_pos = jax.ops.segment_min(
        jnp.where(live_sorted, pos, n), seg, num_segments=G + 1,
        indices_are_sorted=True,
    )[:G]
    safe_first = jnp.minimum(first_pos, n - 1)
    rep_indices = order[safe_first].astype(jnp.int32)

    group_valid = jnp.arange(G, dtype=jnp.int32) < num_groups

    results: List[jax.Array] = []
    for a in aggs:
        if a.op == "count":
            v = jnp.ones((n,), jnp.int64)
            valid = a.validity[order] if a.validity is not None else None
            if valid is not None:
                v = jnp.where(valid, v, 0)
            r = jax.ops.segment_sum(v, seg, num_segments=G + 1,
                                    indices_are_sorted=True)[:G]
        else:
            if a.values is None:
                raise ExecutionError(f"{a.op} requires input values")
            v = a.values[order]
            valid = a.validity[order] if a.validity is not None else None
            if a.op == "sum":
                zero = jnp.zeros((), v.dtype)
                if valid is not None:
                    v = jnp.where(valid, v, zero)
                r = jax.ops.segment_sum(v, seg, num_segments=G + 1,
                                        indices_are_sorted=True)[:G]
            elif a.op == "min":
                ident = _max_ident(v.dtype)
                if valid is not None:
                    v = jnp.where(valid, v, ident)
                r = jax.ops.segment_min(v, seg, num_segments=G + 1,
                                        indices_are_sorted=True)[:G]
            elif a.op == "max":
                ident = _min_ident(v.dtype)
                if valid is not None:
                    v = jnp.where(valid, v, ident)
                r = jax.ops.segment_max(v, seg, num_segments=G + 1,
                                        indices_are_sorted=True)[:G]
            else:
                raise ExecutionError(f"unknown aggregate op {a.op}")
        results.append(jnp.where(group_valid, r, jnp.zeros((), r.dtype)))

    return GroupedResult(rep_indices, group_valid, num_groups, results)


def _max_ident(dt):
    if jnp.issubdtype(dt, jnp.integer):
        return jnp.iinfo(dt).max
    return jnp.asarray(jnp.inf, dt)


def _min_ident(dt):
    if jnp.issubdtype(dt, jnp.integer):
        return jnp.iinfo(dt).min
    return jnp.asarray(-jnp.inf, dt)


# ---------------------------------------------------------------------------
# Ungrouped aggregation (whole-batch reductions)
# ---------------------------------------------------------------------------


def scalar_aggregate(live: jax.Array, aggs: Sequence[AggInput]) -> List[jax.Array]:
    out: List[jax.Array] = []
    for a in aggs:
        valid = live
        if a.validity is not None:
            valid = jnp.logical_and(valid, a.validity)
        if a.op == "count":
            out.append(jnp.sum(valid.astype(jnp.int64)))
            continue
        v = a.values
        if a.op == "sum":
            out.append(jnp.sum(jnp.where(valid, v, jnp.zeros((), v.dtype))))
        elif a.op == "min":
            out.append(jnp.min(jnp.where(valid, v, _max_ident(v.dtype))))
        elif a.op == "max":
            out.append(jnp.max(jnp.where(valid, v, _min_ident(v.dtype))))
        else:
            raise ExecutionError(f"unknown aggregate op {a.op}")
    return out
