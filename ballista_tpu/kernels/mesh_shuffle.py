"""ICI all_to_all hash shuffle: the on-device replacement for the host
shuffle (reference moves whole partitions over Arrow Flight,
rust/core/src/execution_plans/shuffle_reader.rs:77-99; within a TPU slice
we exchange rows over ICI instead).

Works inside ``shard_map`` with static shapes:

1. each device computes a destination id per live row (splitmix64 hash of
   the key mod n_devices);
2. rows are grouped by destination with a stable sort and scattered into a
   send buffer [n_dev, dest_capacity] (padded);
3. one ``lax.all_to_all`` exchanges the buffers;
4. per-source row counts travel alongside, so the receiver reconstructs a
   live mask for its [n_dev * dest_capacity] output rows.

``dest_capacity`` bounds rows sent from one device to one destination; the
caller picks it (conservatively = capacity, or tighter with overflow
detection via the returned per-destination counts).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .hashing import hash_partition_ids, splitmix64


def destination_ids(keys: jax.Array, live: jax.Array, n_dev: int) -> jax.Array:
    """int32 destination device per row (dead rows -> 0). Shares the
    partitioning hash with the host shuffle (hashing.hash_partition_ids) so
    both planes always agree on row destinations."""
    return jnp.where(live, hash_partition_ids(keys.astype(jnp.int64), n_dev), 0)


def all_to_all_rows(
    columns: Sequence[jax.Array],  # each [N] per-device rows
    live: jax.Array,  # bool [N]
    dest: jax.Array,  # int32 [N] in [0, n_dev)
    axis_name: str,
    n_dev: int,
    dest_capacity: int,
) -> Tuple[List[jax.Array], jax.Array, jax.Array]:
    """Exchange rows so each lands on its destination device.

    Returns (out_columns each [n_dev*dest_capacity], out_live, send_counts
    [n_dev] — callers check max(send_counts) <= dest_capacity for overflow).
    """
    n = live.shape[0]
    d = jnp.where(live, dest, n_dev)  # dead rows to trash bucket

    # stable sort rows by destination; rank within destination
    order = jnp.argsort(d, stable=True)
    d_sorted = d[order]
    # rank of each sorted row within its destination run
    idx = jnp.arange(n, dtype=jnp.int32)
    first_of_dest = jnp.searchsorted(d_sorted, jnp.arange(n_dev + 1)).astype(
        jnp.int32
    )
    rank = idx - first_of_dest[jnp.minimum(d_sorted, n_dev)]
    counts = jnp.bincount(jnp.minimum(d, n_dev), length=n_dev + 1)[:n_dev]

    # scatter sorted rows into [n_dev, dest_capacity] send buffers; rows
    # with no slot (dead / over capacity) get an out-of-bounds index and
    # are dropped by the scatter
    slot_ok = jnp.logical_and(d_sorted < n_dev, rank < dest_capacity)
    oob = n_dev * dest_capacity
    slot = jnp.where(
        slot_ok, jnp.minimum(d_sorted, n_dev - 1) * dest_capacity + rank, oob
    )

    out_cols = []
    for col in columns:
        src = col[order]
        buf = jnp.zeros((n_dev * dest_capacity,), col.dtype)
        buf = buf.at[slot].set(src, mode="drop")
        # exchange: [n_dev, cap] -> all_to_all over the mesh axis
        got = lax.all_to_all(
            buf.reshape(n_dev, dest_capacity), axis_name, 0, 0, tiled=False
        )
        out_cols.append(got.reshape(n_dev * dest_capacity))

    # counts destined to me, from each source device
    my_counts = lax.all_to_all(
        jnp.minimum(counts, dest_capacity).reshape(n_dev, 1),
        axis_name, 0, 0, tiled=False,
    ).reshape(n_dev)
    rank_out = jnp.arange(n_dev * dest_capacity, dtype=jnp.int32) % dest_capacity
    src_of = jnp.arange(n_dev * dest_capacity, dtype=jnp.int32) // dest_capacity
    out_live = rank_out < my_counts[src_of]
    return out_cols, out_live, counts
