"""Pallas TPU kernel: fused dense grouped accumulation (q1's hot loop).

Replaces the XLA `dense_grouped_aggregate` inner loop — one pass over the
batch computing every group's sums and counts — with a single Pallas
kernel so the row -> group scatter never materializes [N, G] masks in
HBM (the role a hand-written Rust hash-aggregate loop plays in the
reference's DataFusion engine; here it is a TPU kernel, not CPU code).

Formulation: per grid block, rows are one-hot encoded by group id and
the per-group partial sums are ONE matmul on the MXU:

    acc[G, C] = onehot[BLOCK, G]^T @ limbs[BLOCK, C]

(the round-2 kernel statically unrolled a masked VPU reduction per
group — fine for q1's 4 groups, pathological compile time and code size
at G=256; the matmul form is O(1) in G).

Exactness without i64 vectors: Mosaic has no 64-bit vector ops and the
MXU accumulates in float32, so each int64 value is split into FIVE
13-bit limbs (arithmetic shift keeps the sign in the top limb), which
covers the ENTIRE int64 range — no caller-side magnitude precondition.
A block's per-limb group sum is bounded by BLOCK * 2^13 = 2^23 < 2^24,
so every partial is exactly representable in f32; the per-block int32
partials are recombined in int64 by XLA:
sum(v) = sum(l0) + (sum(l1) << 13) + ... + (sum(l4) << 52).

Validity-masked aggregates: the caller pre-zeroes masked-out values
(sum semantics) and passes each COUNT's 0/1 mask as one more value
column, so the kernel itself only ever sums.

Status: exactness-validated in interpret mode on every CI run AND
compiled+verified on a real TPU v5e (round 3). The on-chip A/B measured
the XLA dense path ~4x FASTER for q1-sized group counts (G<=8: the
one-hot matmul leaves the MXU idle and the limb split multiplies HBM
traffic), so kernels/aggregate.py keeps this kernel OPT-IN
(``BALLISTA_PALLAS=on``); bench.py re-records the A/B every run so a
winning shape class shows up in the data.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp

BLOCK = 1024  # rows per grid step; per-limb block sums stay < 2^24 (f32-exact)
LIMB_BITS = 13
N_LIMBS = 5  # 4x13 bits + signed top limb (v>>52): all of int64


def _limbs(v: jax.Array) -> List[jax.Array]:
    """int64 [N] -> N_LIMBS int32 13-bit limbs (sign rides the top limb
    via arithmetic shift)."""
    mask = jnp.int64((1 << LIMB_BITS) - 1)
    out = []
    for i in range(N_LIMBS - 1):
        out.append(((v >> (LIMB_BITS * i)) & mask).astype(jnp.int32))
    out.append((v >> (LIMB_BITS * (N_LIMBS - 1))).astype(jnp.int32))
    return out


def _kernel(gid_ref, limb_ref, out_ref, *, num_groups: int):
    """One grid step: one-hot the block's group ids and matmul the limb
    matrix onto the MXU. Dead rows carry gid == -1 (never one-hot)."""
    gids = gid_ref[...]  # [BLOCK] int32; -1 = dead
    limbs = limb_ref[...].astype(jnp.float32)  # [BLOCK, C], all < 2^13
    groups = jax.lax.broadcasted_iota(jnp.int32, (BLOCK, num_groups), 1)
    oh = (gids[:, None] == groups).astype(jnp.float32)  # [BLOCK, G]
    acc = jax.lax.dot_general(
        oh, limbs, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [G, C] — exact: every partial < 2^24
    out_ref[0] = acc.astype(jnp.int32)


def dense_grouped_sums(
    gids: jax.Array,  # int32 [N] in [0, num_groups)
    live: jax.Array,  # bool [N]
    values: Sequence[jax.Array],  # int64 [N] each (|v| < 2^51), pre-masked
    num_groups: int,
    interpret: bool = False,
):
    """Returns (sums: list of int64 [G], counts: int64 [G]).

    ``values`` are summed per group; ``counts`` counts live rows. Callers
    wanting validity-masked counts pass the mask as a value column.
    """
    from jax.experimental import pallas as pl

    n = gids.shape[0]
    # dead rows -> group -1: never matches the one-hot iota
    gids = jnp.where(live, gids, -1).astype(jnp.int32)
    ones = live.astype(jnp.int64)
    cols: List[jax.Array] = []
    for v in values:
        cols.extend(_limbs(v))
    cols.append(ones)  # count column (exact: 0/1)
    n_cols = len(cols)

    pad = (-n) % BLOCK
    if pad:
        gids = jnp.pad(gids, (0, pad), constant_values=-1)
        cols = [jnp.pad(c, (0, pad)) for c in cols]
        n += pad
    n_blocks = n // BLOCK
    limbs = jnp.stack(cols, axis=1).astype(jnp.int32)

    # index-map constants must be constructed int32 INSIDE the lambda:
    # the engine enables jax_enable_x64 globally, so a bare `0` traces
    # as i64 (Mosaic rejects i64 block indices), and a hoisted Array is
    # rejected as a captured constant
    partials = pl.pallas_call(
        partial(_kernel, num_groups=num_groups),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((BLOCK,), lambda b: (b,)),
            pl.BlockSpec((BLOCK, n_cols), lambda b: (b, jnp.int32(0))),
        ],
        out_specs=pl.BlockSpec(
            (1, num_groups, n_cols),
            lambda b: (b, jnp.int32(0), jnp.int32(0)),
        ),
        out_shape=jax.ShapeDtypeStruct(
            (n_blocks, num_groups, n_cols), jnp.int32
        ),
        interpret=interpret,
    )(gids, limbs)

    totals = jnp.sum(partials.astype(jnp.int64), axis=0)  # [G, C]
    sums = []
    for i in range(len(values)):
        s = jnp.zeros((num_groups,), jnp.int64)
        for j in range(N_LIMBS):
            s = s + (totals[:, N_LIMBS * i + j] << (LIMB_BITS * j))
        sums.append(s)
    counts = totals[:, n_cols - 1]
    return sums, counts
