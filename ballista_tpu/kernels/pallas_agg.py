"""Pallas TPU kernel: fused dense grouped accumulation (q1's hot loop).

Replaces the XLA `dense_grouped_aggregate` inner loop — one pass over the
batch computing every group's sums and counts — with a single Pallas
kernel so the row -> group scatter never materializes [N, G] masks in
HBM (the role a hand-written Rust hash-aggregate loop plays in the
reference's DataFusion engine; here it is a TPU kernel, not CPU code).

Exactness without i64 vectors: Mosaic has no 64-bit vector ops, so each
scaled-decimal int64 value is split into three limbs (16+16+32-bit,
arithmetic shift keeps the sign in the top limb) and accumulated in
int32 per block — safe because a block's limb sum is bounded by
BLOCK * 2^16 < 2^31 — then the per-block partials are recombined in
int64 by XLA: sum(v) = sum(l0) + (sum(l1) << 16) + (sum(l2) << 32).
Values must fit |v| < 2^47 (checked by the caller's decimal scales).

Developed and tested in interpret mode (no TPU in CI); enable on-chip
via BALLISTA_PALLAS=1 once measured (kernels/aggregate.py gates it).
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp

BLOCK = 1024  # rows per grid step; limb sums stay < 2^31


def _limbs(v: jax.Array) -> List[jax.Array]:
    """int64 [N] -> three int32 [N] limbs (16/16/32, sign in the top)."""
    l0 = (v & jnp.int64(0xFFFF)).astype(jnp.int32)
    l1 = ((v >> 16) & jnp.int64(0xFFFF)).astype(jnp.int32)
    l2 = (v >> 32).astype(jnp.int32)  # arithmetic shift: carries the sign
    return [l0, l1, l2]


def _kernel(gid_ref, live_ref, limb_ref, out_ref, *, num_groups: int,
            n_cols: int):
    """One grid step: accumulate this block's rows into per-group
    partial sums. out block: [1, num_groups, n_cols + 1] int32 (the last
    column counts live rows)."""
    gids = gid_ref[...]  # [BLOCK] int32
    live = live_ref[...]  # [BLOCK] int32 (0/1)
    limbs = limb_ref[...]  # [BLOCK, n_cols] int32
    for g in range(num_groups):  # static unroll: VPU masked reductions
        mask = jnp.logical_and(gids == g, live > 0)
        masked = jnp.where(mask[:, None], limbs, 0)
        out_ref[0, g, :n_cols] = jnp.sum(masked, axis=0)
        out_ref[0, g, n_cols] = jnp.sum(mask.astype(jnp.int32))


def dense_grouped_sums(
    gids: jax.Array,  # int32 [N] in [0, num_groups)
    live: jax.Array,  # bool [N]
    values: Sequence[jax.Array],  # int64 [N] each (|v| < 2^47)
    num_groups: int,
    interpret: bool = False,
):
    """Returns (sums: list of int64 [G], counts: int64 [G])."""
    from jax.experimental import pallas as pl

    if not values:
        raise ValueError("dense_grouped_sums needs at least one value column")
    n = gids.shape[0]
    pad = (-n) % BLOCK
    if pad:
        gids = jnp.pad(gids, (0, pad))
        live = jnp.pad(live, (0, pad))
        values = [jnp.pad(v, (0, pad)) for v in values]
        n += pad
    n_blocks = n // BLOCK
    n_cols = 3 * len(values)
    limbs = jnp.stack([l for v in values for l in _limbs(v)], axis=1)

    partials = pl.pallas_call(
        partial(_kernel, num_groups=num_groups, n_cols=n_cols),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((BLOCK,), lambda b: (b,)),
            pl.BlockSpec((BLOCK,), lambda b: (b,)),
            pl.BlockSpec((BLOCK, n_cols), lambda b: (b, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, num_groups, n_cols + 1), lambda b: (b, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (n_blocks, num_groups, n_cols + 1), jnp.int32
        ),
        interpret=interpret,
    )(gids, live.astype(jnp.int32), limbs)

    totals = jnp.sum(partials.astype(jnp.int64), axis=0)  # [G, C+1]
    sums = []
    for i in range(len(values)):
        l0 = totals[:, 3 * i]
        l1 = totals[:, 3 * i + 1]
        l2 = totals[:, 3 * i + 2]
        sums.append(l0 + (l1 << 16) + (l2 << 32))
    counts = totals[:, n_cols]
    return sums, counts
