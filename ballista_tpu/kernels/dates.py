"""Date arithmetic kernels (days-since-epoch int32 representation).

Uses the standard civil-calendar/days bijection (Howard Hinnant's public
domain algorithms) expressed in traced integer ops so they fuse into the
surrounding XLA program.
"""

from __future__ import annotations

import jax.numpy as jnp


def civil_from_days(days):
    """days since 1970-01-01 -> (year, month, day) int32 arrays."""
    z = days.astype(jnp.int32) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097  # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    year = y + (m <= 2)
    return year.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def extract_year(days):
    return civil_from_days(days)[0]


def extract_month(days):
    return civil_from_days(days)[1]


def extract_day(days):
    return civil_from_days(days)[2]
