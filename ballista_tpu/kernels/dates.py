"""Date arithmetic kernels (days-since-epoch int32 representation).

Uses the standard civil-calendar/days bijection (Howard Hinnant's public
domain algorithms) expressed in traced integer ops so they fuse into the
surrounding XLA program.
"""

from __future__ import annotations

import jax.numpy as jnp


def civil_from_days(days):
    """days since 1970-01-01 -> (year, month, day) int32 arrays."""
    z = days.astype(jnp.int32) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097  # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    year = y + (m <= 2)
    return year.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def days_from_civil(y, m, d):
    """(year, month, day) int32 arrays -> days since 1970-01-01 (inverse
    of civil_from_days; same public-domain algorithm family)."""
    y = y.astype(jnp.int32) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400  # [0, 399]
    mp = m + jnp.where(m > 2, -3, 9)  # [0, 11]
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def date_trunc(part: str, days):
    """Truncate days-since-epoch to the start of year/quarter/month/week/day.
    ``part`` is static (baked into the trace). The reference exposes this as
    the DATETRUNC scalar function (reference: rust/core/proto/ballista.proto:107)."""
    if part == "day":
        return days.astype(jnp.int32)
    if part == "week":  # ISO weeks start Monday; 1970-01-01 was a Thursday
        return (days - jnp.mod(days + 3, 7)).astype(jnp.int32)
    y, m, _ = civil_from_days(days)
    one = jnp.ones_like(m)
    if part == "year":
        return days_from_civil(y, one, one)
    if part == "quarter":
        return days_from_civil(y, ((m - 1) // 3) * 3 + 1, one)
    if part == "month":
        return days_from_civil(y, m, one)
    raise ValueError(f"date_trunc part {part!r}")


def extract_year(days):
    return civil_from_days(days)[0]


def extract_month(days):
    return civil_from_days(days)[1]


def extract_day(days):
    return civil_from_days(days)[2]
