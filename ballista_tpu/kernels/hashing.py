"""Hash kernels for repartitioning/shuffle.

TPU-native replacement for the reference's hash repartitioning (reference:
rust/core/proto/ballista.proto:219-230 RepartitionNode, :415-422
RepartitionExecNode). Uses a splitmix64 finalizer over int64 composite keys;
the partition id feeds either the host shuffle writer or the in-mesh
``all_to_all`` fast path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def splitmix64(x: jax.Array) -> jax.Array:
    """splitmix64 finalizer; good avalanche, pure vector ops."""
    z = x.astype(jnp.uint64)
    z = (z + jnp.uint64(0x9E3779B97F4A7C15)) & jnp.uint64(0xFFFFFFFFFFFFFFFF)
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    z = z ^ (z >> jnp.uint64(31))
    return z


def hash_partition_ids(keys: jax.Array, num_partitions: int) -> jax.Array:
    """int64 keys -> int32 partition ids in [0, num_partitions)."""
    h = splitmix64(keys)
    return (h % jnp.uint64(num_partitions)).astype(jnp.int32)
