"""Mesh-fused shuffle aggregation: the ICI fast path for a
Repartition(hash) -> HashAggregate(final) stage pair.

When one executor owns a whole device mesh, materializing N^2 shuffle
files through the host data plane (reference model:
rust/core/src/execution_plans/shuffle_reader.rs:77-99 — whole partitions
over Arrow Flight) wastes the interconnect. This operator runs the pair
as ONE SPMD XLA program instead:

  per device: partial state rows -> hash destination ids
           -> lax.all_to_all row exchange  (kernels.mesh_shuffle)
           -> per-device final aggregation (groups are now co-located)

The row->destination hash is ``compute_partition_ids`` — the same
function the host shuffle uses — so the mesh path and the file path
always agree on row placement (utf8 keys hash their string values via
dictionary stable hashes, never producer-local codes).

The scheduler's fusion pass (distributed/scheduler.py) builds this node
from a shuffle stage + its final-aggregate consumer when the target
executor reports enough devices; ``mesh.devices`` gates it.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..columnar import Column, ColumnBatch, round_capacity
from ..compile import bucket_capacity
from ..datatypes import Schema
from ..errors import ExecutionError
from .. import expr as ex
from ..kernels import mesh_shuffle
from ..kernels.expr_eval import Evaluator
from ..parallel.mesh import make_mesh
from .aggregate import DEFAULT_GROUP_CAPACITY, HashAggregateExec
from .base import PhysicalPlan, Partitioning




def _shuffle_side(b: ColumnBatch, hash_exprs, ev: Evaluator, n_dev: int,
                  in_cap: int, axis: str = "data") -> ColumnBatch:
    """Traced: hash rows by ``hash_exprs`` and exchange them over the
    mesh axis; returns the post-shuffle per-device batch (capacity
    n_dev * in_cap)."""
    dest = _partition_ids(b, hash_exprs, n_dev, ev)
    arrays = [c.values for c in b.columns] + [c.validity for c in b.columns]
    out_arrays, out_live, _counts = mesh_shuffle.all_to_all_rows(
        arrays, b.selection, dest, axis, n_dev, dest_capacity=in_cap,
    )
    nf = len(b.schema.fields)
    cols = [
        Column(v, f.dtype, va, c.dictionary)
        for v, va, f, c in zip(out_arrays[:nf], out_arrays[nf:],
                               b.schema.fields, b.columns)
    ]
    return ColumnBatch(b.schema, cols, out_live,
                       jnp.sum(out_live).astype(jnp.int32))


def _host_visible(stacked, mesh):
    """Make a stacked output sliceable on THIS process: under a
    multi-process (cross-host) mesh, some shards live on other
    processes, so the (small) final output is all_gather-replicated
    first; single-process meshes pass through untouched."""
    from ..parallel.multihost import is_multiprocess, replicate_stacked

    if not is_multiprocess():
        return stacked
    return replicate_stacked(stacked, mesh)


class _SchemaOnly(PhysicalPlan):
    """Placeholder child that only carries a schema (the mesh runner
    feeds batches directly, there is nothing to execute)."""

    def __init__(self, schema: Schema):
        self._schema = schema

    def output_schema(self) -> Schema:
        return self._schema

    def with_new_children(self, children):
        return self


class MeshAggExec(PhysicalPlan):
    """One task that replaces a whole shuffle stage pair.

    ``producer`` is the shuffle stage's child (scan -> ... -> partial
    aggregate, P partitions, executed on host); its output rows are laid
    out over an ``n_devices`` mesh and exchanged over ICI.
    Output: a single partition containing every device's final groups.
    """

    def __init__(self, producer: PhysicalPlan, group_exprs: List[ex.Expr],
                 agg_exprs: List[ex.Expr], hash_exprs: List[ex.Expr],
                 n_devices: int,
                 group_capacity: int = DEFAULT_GROUP_CAPACITY):
        self.producer = producer
        self.group_exprs = list(group_exprs)
        self.agg_exprs = list(agg_exprs)
        self.hash_exprs = list(hash_exprs)
        self.n_devices = n_devices
        self.group_capacity = group_capacity
        self._partial_schema = producer.output_schema()
        self._final = HashAggregateExec(
            "final", self.group_exprs, self.agg_exprs,
            _SchemaOnly(self._partial_schema), group_capacity,
        )
        self._ev = Evaluator(self._partial_schema)

    # -- plan plumbing -------------------------------------------------------

    def output_schema(self) -> Schema:
        return self._final.output_schema()

    def output_partitioning(self) -> Partitioning:
        return Partitioning("unknown", 1)

    def children(self):
        return [self.producer]

    def with_new_children(self, children):
        return MeshAggExec(children[0], self.group_exprs, self.agg_exprs,
                           self.hash_exprs, self.n_devices,
                           self.group_capacity)

    def display(self) -> str:
        g = ", ".join(e.name() for e in self.group_exprs)
        return (f"MeshAggExec: {self.n_devices}-device ICI all_to_all "
                f"shuffle + final agg gby=[{g}]")

    def _signature_parts(self) -> tuple:
        from ..compile import fingerprint

        return (fingerprint(self.group_exprs), fingerprint(self.agg_exprs),
                fingerprint(self.hash_exprs), self.n_devices,
                self._partial_schema)

    def _detach(self) -> None:
        from .base import SchemaLeaf

        # _final's child is already schema-only; only the producer
        # subtree (scans and their caches) must be severed
        self.producer = SchemaLeaf(self._partial_schema)

    # -- execution -----------------------------------------------------------

    def _spmd(self, stacked, mesh, cap: int, in_cap: int):
        """(stacked batch pytree) -> (stacked out batch, num_groups[n])."""
        from functools import partial

        from ..parallel.mesh import shard_map  # version-guarded import

        from ..compile import governed
        from .mesh_input import _MESH_NS_CAP

        n_dev = self.n_devices

        def build():
            tw = self.trace_twin()
            final_fn = self._final._get_grouped_fn(cap, n_dev * in_cap)

            @partial(shard_map, mesh=mesh, in_specs=(P("data"),),
                     out_specs=(P("data"), P("data")), check_vma=False)
            def run(stacked_b):
                b = jax.tree.map(lambda x: x[0], stacked_b)
                b2 = _shuffle_side(b, tw.hash_exprs, tw._ev, n_dev,
                                   in_cap)
                out_batch, num_groups = final_fn(b2)
                return (
                    jax.tree.map(lambda x: x[None], out_batch),
                    num_groups[None],
                )

            return run

        key = ("mesh.agg_spmd", self.compile_signature(), mesh, cap,
               in_cap, jax.tree.structure(stacked))
        return governed(key, build, cap=_MESH_NS_CAP,
                        metrics=self.metrics())(stacked)

    def execute_stacked(self, mesh) -> ColumnBatch:
        """Device-resident execution: stacked [n_dev, cap] output sharded
        over the mesh — consumed directly by a chained fused stage (HBM
        partition cache) or sliced per device by ``execute``."""
        from ..parallel.multihost import host_max
        from .mesh_input import stacked_input

        stacked, in_cap = stacked_input(self.producer, self._partial_schema,
                                        mesh)
        cap = self.group_capacity
        while True:
            out_stacked, num_groups = self._spmd(stacked, mesh, cap, in_cap)
            ng = host_max(num_groups)  # multihost-safe replicated max
            if ng <= cap:
                return out_stacked
            cap = round_capacity(ng)  # overflow: recompile with exact cap

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        if partition != 0:
            raise ExecutionError("MeshAggExec has a single output partition")
        mesh = make_mesh(self.n_devices)
        out_stacked = _host_visible(self.execute_stacked(mesh), mesh)
        for q in range(self.n_devices):
            yield jax.tree.map(lambda x, _q=q: jnp.asarray(x)[_q],
                               out_stacked)


def _partition_ids(batch: ColumnBatch, hash_exprs, n_dev: int,
                   ev: Evaluator):
    from .operators import compute_partition_ids

    return compute_partition_ids(batch, hash_exprs, n_dev, 0, ev)

class MeshJoinExec(PhysicalPlan):
    """Mesh-fused co-partitioned join: BOTH join inputs are exchanged
    over ICI ``lax.all_to_all`` (hashed on the join keys) and joined per
    device in the same SPMD program — BASELINE config 4's shape
    ("q5 shuffle -> ICI all_to_all") with zero shuffle files.

    Built by the scheduler's fusion pass from a partitioned JoinExec
    stage and its two hash-shuffle producer stages. Supports every host
    join type (inner/left/semi/anti/full): co-partitioning makes
    unmatched-row detection local to each device, so outer rows are
    appended after the matched expansion in the same static output
    buffer (host semantics: physical/join.py:292-357). Key
    representation is raw values for one key column, the exact rank
    codec otherwise — decided statically, no host-side range checks.
    Output: a single partition containing every device's joined rows
    (adaptive output capacity with whole-SPMD retry on overflow, like
    MeshAggExec).
    """

    def __init__(self, build_producer: PhysicalPlan,
                 probe_producer: PhysicalPlan, on, how: str,
                 n_devices: int, null_aware: bool = False):
        if how not in ("inner", "left", "semi", "anti", "full"):
            raise ExecutionError(f"MeshJoinExec join type {how}")
        self.null_aware = null_aware
        self.build_producer = build_producer
        self.probe_producer = probe_producer
        self.on = list(on)
        self.how = how
        self.n_devices = n_devices
        from .join import JoinExec

        # schema/key helpers only; never executed
        self._join = JoinExec(
            _SchemaOnly(build_producer.output_schema()),
            _SchemaOnly(probe_producer.output_schema()),
            self.on, how,
        )
        self._build_ev = Evaluator(build_producer.output_schema())
        self._probe_ev = Evaluator(probe_producer.output_schema())

    # -- plan plumbing -------------------------------------------------------

    def output_schema(self) -> Schema:
        return self._join.output_schema()

    def output_partitioning(self) -> Partitioning:
        return Partitioning("unknown", 1)

    def children(self):
        return [self.build_producer, self.probe_producer]

    def with_new_children(self, children):
        return MeshJoinExec(children[0], children[1], self.on, self.how,
                            self.n_devices, self.null_aware)

    def display(self) -> str:
        on = ", ".join(f"{l}={r}" for l, r in self.on)
        return (f"MeshJoinExec: {self.n_devices}-device ICI all_to_all "
                f"join how={self.how} on=[{on}]")

    def _signature_parts(self) -> tuple:
        return (self.how, tuple(self.on), self.null_aware, self.n_devices,
                self.build_producer.output_schema(),
                self.probe_producer.output_schema())

    def _detach(self) -> None:
        from .base import SchemaLeaf

        self.build_producer = SchemaLeaf(self.build_producer.output_schema())
        self.probe_producer = SchemaLeaf(self.probe_producer.output_schema())
        # _join's children are already schema-only, but execute_stacked
        # fills its _remap_cache with per-query dictionaries — take its
        # own (cache-cleared) twin so the governed entry pins none
        self._join = self._join.trace_twin()

    # -- execution -----------------------------------------------------------

    def _spmd(self, stacked_b, stacked_p, mesh, remaps, out_cap: int,
              b_cap: int, p_cap: int):
        from functools import partial as fpartial

        from ..kernels import join as join_k
        from ..parallel.mesh import shard_map

        def build():
            # whole closure construction deferred: on a governed cache hit
            # none of this work (twin, hash exprs, shard_map wrapping) runs
            n_dev = self.n_devices
            bcols = [b for b, _ in self.on]
            pcols = [p for _, p in self.on]
            bhash = [ex.ColumnRef(c) for c in bcols]
            phash = [ex.ColumnRef(c) for c in pcols]
            out_schema = self.output_schema()
            probe_schema = self.probe_producer.output_schema()
            tw = self.trace_twin()

            @fpartial(shard_map, mesh=mesh,
                      in_specs=(P("data"), P("data"), P()),
                      out_specs=(P("data"), P("data")), check_vma=False)
            def run(sb, sp, remaps):
              b = jax.tree.map(lambda x: x[0], sb)
              p = jax.tree.map(lambda x: x[0], sp)
              b2 = _shuffle_side(b, bhash, tw._build_ev, n_dev, b_cap)
              p2 = _shuffle_side(p, phash, tw._probe_ev, n_dev, p_cap)
              # keys: raw for a single column, exact rank codec otherwise
              if len(tw.on) == 1:
                  bk = b2.column(bcols[0]).values.astype(jnp.int64)
                  blive = b2.selection
                  v = b2.column(bcols[0]).validity
                  if v is not None:
                      blive = jnp.logical_and(blive, v)
                  pk, pvalid = tw._join._probe_col_values(
                      p2, pcols[0], remaps[0])
                  plive = p2.selection
                  if pvalid is not None:
                      plive = jnp.logical_and(plive, pvalid)
              else:
                  bk, blive, (tables, nlive) = tw._join._codec_build(
                      b2, bcols)
                  pk, plive = tw._join._probe_keys(p2, "codec",
                                                     (tables, nlive), remaps)
              table = join_k.build_lookup(bk, blive)

              if tw.how in ("semi", "anti"):
                  # membership only: probe-aligned output, no expansion
                  matched = join_k.probe_semi(table, pk, plive)
                  if tw.how == "semi":
                      sel = jnp.logical_and(p2.selection, matched)
                  else:
                      sel = jnp.logical_and(p2.selection,
                                            jnp.logical_not(matched))
                      if tw.null_aware:
                          # SQL NOT IN: a null key ANYWHERE in the build
                          # side (any device) makes the predicate never
                          # true; null-key probe rows are dropped too
                          bnull = jnp.logical_and(b2.selection,
                                                  jnp.logical_not(blive))
                          bnull_any = jax.lax.pmax(
                              jnp.max(bnull.astype(jnp.int32)), "data") > 0
                          for _, pcol in tw.on:
                              vv = p2.column(pcol).validity
                              if vv is not None:
                                  sel = jnp.logical_and(sel, vv)
                          sel = jnp.logical_and(sel,
                                                jnp.logical_not(bnull_any))
                  out = p2.with_selection(sel)
                  need = jnp.zeros((), jnp.int32)
                  return jax.tree.map(lambda x: x[None], out), need[None]

              prows, brows, olive, total = join_k.probe_expand(
                  table, pk, plive, out_cap)
              need = total
              C = out_cap
              # outer rows: co-partitioning makes unmatched detection
              # local; append them after the matched expansion in the same
              # static buffer (overflow rides the same retry as matches)
              sidx_p = sidx_b = None
              n_up = jnp.zeros((), jnp.int32)
              if tw.how in ("left", "full"):
                  counts = join_k.probe_counts(table, pk)
                  un_p = jnp.logical_and(
                      p2.selection,
                      jnp.logical_or(jnp.logical_not(plive), counts == 0))
                  rank_p = jnp.cumsum(un_p.astype(jnp.int32)) - un_p
                  n_up = jnp.sum(un_p.astype(jnp.int32))
                  sidx_p = jnp.where(un_p, total + rank_p, C)  # C drops
                  need = need + n_up
              if tw.how == "full":
                  pt = join_k.build_lookup(pk, plive)
                  _, bmat = join_k.probe_unique(pt, bk, blive)
                  un_b = jnp.logical_and(
                      b2.selection,
                      jnp.logical_not(jnp.logical_and(blive, bmat)))
                  rank_b = jnp.cumsum(un_b.astype(jnp.int32)) - un_b
                  sidx_b = jnp.where(un_b, total + n_up + rank_b, C)
                  need = need + jnp.sum(un_b.astype(jnp.int32))

              live = olive
              if sidx_p is not None:
                  live = live.at[sidx_p].set(True, mode="drop")
              if sidx_b is not None:
                  live = live.at[sidx_b].set(True, mode="drop")

              cols = []
              for f in out_schema.fields:
                  from_probe = probe_schema.has_field(f.name)
                  src = p2 if from_probe else b2
                  rows = prows if from_probe else brows
                  c = src.column(f.name)
                  vals = jnp.take(c.values, rows)
                  validity = (jnp.take(c.validity, rows)
                              if c.validity is not None else None)
                  src_valid = (c.validity if c.validity is not None
                               else True)
                  if from_probe:
                      if sidx_p is not None:
                          vals = vals.at[sidx_p].set(c.values, mode="drop")
                          if validity is not None:
                              validity = validity.at[sidx_p].set(
                                  src_valid, mode="drop")
                      if sidx_b is not None:  # probe cols null on
                          if validity is None:  # build-only rows
                              validity = jnp.ones((C,), jnp.bool_)
                          validity = validity.at[sidx_b].set(
                              False, mode="drop")
                  else:
                      if sidx_p is not None:  # build cols null on
                          if validity is None:  # probe-only rows
                              validity = jnp.ones((C,), jnp.bool_)
                          validity = validity.at[sidx_p].set(
                              False, mode="drop")
                      if sidx_b is not None:
                          vals = vals.at[sidx_b].set(c.values, mode="drop")
                          validity = validity.at[sidx_b].set(
                              src_valid, mode="drop")
                  cols.append(Column(vals, f.dtype, validity, c.dictionary))
              out = ColumnBatch(out_schema, cols, live,
                                jnp.sum(live).astype(jnp.int32))
              return jax.tree.map(lambda x: x[None], out), need[None]

            return run

        from ..compile import MESH_NS_CAP, governed

        key = ("mesh.join_spmd", self.compile_signature(), mesh, out_cap,
               b_cap, p_cap,
               jax.tree.structure((stacked_b, stacked_p, remaps)))
        return governed(key, build, cap=MESH_NS_CAP,
                        metrics=self.metrics())(stacked_b, stacked_p,
                                                remaps)

    def execute_stacked(self, mesh) -> ColumnBatch:
        """Device-resident execution: both inputs laid out over the mesh
        (or taken straight from chained fused producers), joined in one
        SPMD program; stacked [n_dev, out_cap] output stays sharded."""
        from .mesh_input import stacked_input

        sb, b_cap = stacked_input(
            self.build_producer, self.build_producer.output_schema(), mesh)
        sp, p_cap = stacked_input(
            self.probe_producer, self.probe_producer.output_schema(), mesh)
        remaps = self._join._remaps_for(sb, sp)
        from ..parallel.multihost import host_max

        out_cap = self.n_devices * p_cap  # post-shuffle probe rows/device
        if self.how == "full":  # + room for unmatched build rows
            out_cap = bucket_capacity(out_cap + self.n_devices * b_cap)
        while True:
            out_stacked, totals = self._spmd(sb, sp, mesh, remaps, out_cap,
                                             b_cap, p_cap)
            t = host_max(totals)  # multihost-safe replicated max
            if t <= out_cap:
                return out_stacked
            out_cap = bucket_capacity(t)  # duplicate-heavy keys: retry

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        if partition != 0:
            raise ExecutionError("MeshJoinExec has a single output partition")
        from .base import maybe_compact

        mesh = make_mesh(self.n_devices)
        out_stacked = _host_visible(self.execute_stacked(mesh), mesh)
        for q in range(self.n_devices):
            # selective joins (semi/anti especially) leave mostly-dead
            # slices; shrink them like the host join does before handing
            # batches to downstream host operators
            yield maybe_compact(jax.tree.map(
                lambda x, _q=q: jnp.asarray(x)[_q], out_stacked))
