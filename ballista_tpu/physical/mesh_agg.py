"""Mesh-fused shuffle aggregation: the ICI fast path for a
Repartition(hash) -> HashAggregate(final) stage pair.

When one executor owns a whole device mesh, materializing N^2 shuffle
files through the host data plane (reference model:
rust/core/src/execution_plans/shuffle_reader.rs:77-99 — whole partitions
over Arrow Flight) wastes the interconnect. This operator runs the pair
as ONE SPMD XLA program instead:

  per device: partial state rows -> hash destination ids
           -> lax.all_to_all row exchange  (kernels.mesh_shuffle)
           -> per-device final aggregation (groups are now co-located)

The row->destination hash is ``compute_partition_ids`` — the same
function the host shuffle uses — so the mesh path and the file path
always agree on row placement (utf8 keys hash their string values via
dictionary stable hashes, never producer-local codes).

The scheduler's fusion pass (distributed/scheduler.py) builds this node
from a shuffle stage + its final-aggregate consumer when the target
executor reports enough devices; ``mesh.devices`` gates it.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..columnar import Column, ColumnBatch, round_capacity
from ..datatypes import Schema
from ..errors import ExecutionError
from .. import expr as ex
from ..kernels import mesh_shuffle
from ..kernels.expr_eval import Evaluator
from ..parallel.mesh import make_mesh
from .aggregate import DEFAULT_GROUP_CAPACITY, HashAggregateExec
from .base import PhysicalPlan, Partitioning, concat_batches


class _SchemaOnly(PhysicalPlan):
    """Placeholder child that only carries a schema (the mesh runner
    feeds batches directly, there is nothing to execute)."""

    def __init__(self, schema: Schema):
        self._schema = schema

    def output_schema(self) -> Schema:
        return self._schema

    def with_new_children(self, children):
        return self


class MeshAggExec(PhysicalPlan):
    """One task that replaces a whole shuffle stage pair.

    ``producer`` is the shuffle stage's child (scan -> ... -> partial
    aggregate, P partitions, executed on host); its output rows are laid
    out over an ``n_devices`` mesh and exchanged over ICI.
    Output: a single partition containing every device's final groups.
    """

    def __init__(self, producer: PhysicalPlan, group_exprs: List[ex.Expr],
                 agg_exprs: List[ex.Expr], hash_exprs: List[ex.Expr],
                 n_devices: int,
                 group_capacity: int = DEFAULT_GROUP_CAPACITY):
        self.producer = producer
        self.group_exprs = list(group_exprs)
        self.agg_exprs = list(agg_exprs)
        self.hash_exprs = list(hash_exprs)
        self.n_devices = n_devices
        self.group_capacity = group_capacity
        self._partial_schema = producer.output_schema()
        self._final = HashAggregateExec(
            "final", self.group_exprs, self.agg_exprs,
            _SchemaOnly(self._partial_schema), group_capacity,
        )
        self._ev = Evaluator(self._partial_schema)

    # -- plan plumbing -------------------------------------------------------

    def output_schema(self) -> Schema:
        return self._final.output_schema()

    def output_partitioning(self) -> Partitioning:
        return Partitioning("unknown", 1)

    def children(self):
        return [self.producer]

    def with_new_children(self, children):
        return MeshAggExec(children[0], self.group_exprs, self.agg_exprs,
                           self.hash_exprs, self.n_devices,
                           self.group_capacity)

    def display(self) -> str:
        g = ", ".join(e.name() for e in self.group_exprs)
        return (f"MeshAggExec: {self.n_devices}-device ICI all_to_all "
                f"shuffle + final agg gby=[{g}]")

    # -- execution -----------------------------------------------------------

    def _device_batches(self) -> List[ColumnBatch]:
        """Run the producer on host and lay its live rows out round-robin
        over the mesh slots (uniform capacity, materialized validity so
        every slot shares one pytree structure)."""
        batches = []
        for p in range(self.producer.output_partitioning().num_partitions):
            batches.extend(self.producer.execute(p))
        if not batches:
            from ..columnar import empty_batch

            batches = [empty_batch(self._partial_schema)]
        big = concat_batches(self._partial_schema, batches)  # unifies dicts
        sel = np.asarray(big.selection)
        rows = np.flatnonzero(sel)
        chunks = np.array_split(rows, self.n_devices)
        cap = round_capacity(max((len(c) for c in chunks), default=1) or 1)
        out = []
        for c in chunks:
            cols = []
            for col in big.columns:
                vals = np.zeros((cap,), np.asarray(col.values).dtype)
                vals[: len(c)] = np.asarray(col.values)[c]
                if col.validity is not None:
                    valid = np.zeros((cap,), bool)
                    valid[: len(c)] = np.asarray(col.validity)[c]
                else:
                    valid = np.zeros((cap,), bool)
                    valid[: len(c)] = True
                cols.append(Column(jnp.asarray(vals), col.dtype,
                                   jnp.asarray(valid), col.dictionary))
            live = np.zeros((cap,), bool)
            live[: len(c)] = True
            out.append(ColumnBatch(
                self._partial_schema, cols, jnp.asarray(live),
                jnp.asarray(np.int32(len(c))),
            ))
        return out

    def _spmd(self, stacked, mesh, cap: int, in_cap: int):
        """(stacked batch pytree) -> (stacked out batch, num_groups[n])."""
        from functools import partial

        from ..parallel.mesh import shard_map  # version-guarded import

        n_dev = self.n_devices
        fields = self._partial_schema.fields
        final_fn = self._final._get_grouped_fn(cap, n_dev * in_cap)

        @partial(shard_map, mesh=mesh, in_specs=(P("data"),),
                 out_specs=(P("data"), P("data")), check_vma=False)
        def run(stacked_b):
            b = jax.tree.map(lambda x: x[0], stacked_b)
            dest = _partition_ids(b, self.hash_exprs, n_dev, self._ev)
            arrays = [c.values for c in b.columns] + \
                     [c.validity for c in b.columns]
            out_arrays, out_live, _counts = mesh_shuffle.all_to_all_rows(
                arrays, b.selection, dest, "data", n_dev,
                dest_capacity=in_cap,
            )
            vals = out_arrays[: len(fields)]
            valids = out_arrays[len(fields):]
            cols = [
                Column(v, f.dtype, va, c.dictionary)
                for v, va, f, c in zip(vals, valids, fields, b.columns)
            ]
            b2 = ColumnBatch(
                self._partial_schema, cols, out_live,
                jnp.sum(out_live).astype(jnp.int32),
            )
            out_batch, num_groups = final_fn(b2)
            return (
                jax.tree.map(lambda x: x[None], out_batch),
                num_groups[None],
            )

        return run(stacked)

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        if partition != 0:
            raise ExecutionError("MeshAggExec has a single output partition")
        mesh = make_mesh(self.n_devices)
        device_batches = self._device_batches()
        in_cap = device_batches[0].capacity
        stacked = jax.tree.map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
            *device_batches,
        )
        sharding = NamedSharding(mesh, P("data"))
        stacked = jax.device_put(stacked, sharding)
        cap = self.group_capacity
        while True:
            out_stacked, num_groups = self._spmd(stacked, mesh, cap, in_cap)
            ng = int(np.max(np.asarray(num_groups)))
            if ng <= cap:
                break
            cap = round_capacity(ng)  # overflow: recompile with exact cap
        for q in range(self.n_devices):
            yield jax.tree.map(lambda x, _q=q: jnp.asarray(x)[_q],
                               out_stacked)


def _partition_ids(batch: ColumnBatch, hash_exprs, n_dev: int,
                   ev: Evaluator):
    from .operators import compute_partition_ids

    return compute_partition_ids(batch, hash_exprs, n_dev, 0, ev)
