"""Distributed shuffle operators.

TPU-native equivalents of the reference's shuffle trio (reference:
rust/core/src/execution_plans/{query_stage.rs,shuffle_reader.rs,
unresolved_shuffle.rs}):

- ``QueryStageExec`` marks a stage boundary; the executor runs its child for
  one partition and materializes the (hash-partitioned) output;
- ``UnresolvedShuffleExec`` is the planner's placeholder for inputs whose
  producing stages haven't completed; it refuses to execute;
- ``ShuffleReaderExec`` reads completed stage partitions: from the local
  filesystem when the producer shares it, else over the data-plane socket.
"""

from __future__ import annotations

import os
import threading
from typing import Iterator, List, Optional

from ..columnar import ColumnBatch
from ..datatypes import Schema
from ..errors import ExecutionError
from ..distributed.types import PartitionLocation
from .base import PhysicalPlan, Partitioning


class QueryStageExec(PhysicalPlan):
    """Stage boundary marker (reference: query_stage.rs:29-85). Execution
    (materializing output) is driven by the executor task runner, which
    also applies the hash partitioning for the consuming stage when
    ``shuffle_hash_exprs``/``shuffle_output_partitions`` are set."""

    def __init__(self, job_id: str, stage_id: int, child: PhysicalPlan,
                 shuffle_hash_exprs=None, shuffle_output_partitions: int = 0):
        self.job_id = job_id
        self.stage_id = stage_id
        self.child = child
        self.shuffle_hash_exprs = shuffle_hash_exprs
        self.shuffle_output_partitions = shuffle_output_partitions

    def output_schema(self) -> Schema:
        return self.child.output_schema()

    def output_partitioning(self) -> Partitioning:
        return self.child.output_partitioning()

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return QueryStageExec(self.job_id, self.stage_id, children[0],
                              self.shuffle_hash_exprs,
                              self.shuffle_output_partitions)

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        yield from self.child.execute(partition)

    def display(self) -> str:
        return f"QueryStageExec: job={self.job_id} stage={self.stage_id}"


class UnresolvedShuffleExec(PhysicalPlan):
    """Placeholder input (reference: unresolved_shuffle.rs:34-91)."""

    def __init__(self, query_stage_ids: List[int], schema: Schema,
                 partition_count: int):
        self.query_stage_ids = list(query_stage_ids)
        self._schema = schema
        self.partition_count = partition_count

    def output_schema(self) -> Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning("unknown", self.partition_count)

    def with_new_children(self, children):
        return self

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        raise ExecutionError(
            "UnresolvedShuffleExec cannot execute; the scheduler must "
            "resolve it into a ShuffleReaderExec first"
        )

    def display(self) -> str:
        return (
            f"UnresolvedShuffleExec: stages={self.query_stage_ids} "
            f"parts={self.partition_count}"
        )


class ShuffleReaderExec(PhysicalPlan):
    """Reads completed shuffle partitions (reference:
    shuffle_reader.rs:33-100).

    Three layouts:
    - merge-style stages: output partition i maps 1:1 to location i;
    - hash-shuffled stages (locations carry ``shuffle_output``): output
      partition q reads the shuffle-q file of EVERY producer partition;
    - adaptive (``read_partitions``): output partition i reads the file
      ranges the re-planner selected — coalesced spans of whole hash
      buckets and/or producer subranges of a skew-split bucket.
    """

    # tests flip this to exercise the cross-host (socket) path even when
    # producer and consumer share a filesystem
    FORCE_REMOTE = False

    def __init__(self, partition_locations: List[PartitionLocation],
                 schema: Schema, read_partitions=None,
                 hash_columns=(), original_partitions: int = 0):
        self.partition_locations = list(partition_locations)
        self._schema = schema
        self._cache = {}
        # ingest read-ahead: group index -> in-flight Future loading it
        # behind the consumer (see execute()). _group_locks serialize a
        # group's load so a read-ahead racing a direct consumer never
        # fetches the same files twice; _served gates read-ahead to
        # instances that actually iterate multiple partitions (a cluster
        # task deserializes its own plan and executes exactly ONE
        # partition — read-ahead there would fetch a neighbour task's
        # group into a cache that dies with this task)
        from ..ingest import KeyedLocks

        self._inflight = {}
        self._inflight_lock = threading.Lock()
        self._group_locks = KeyedLocks()
        self._served = False
        # read_partitions: List[List[(out_lo, out_hi, prod_lo, prod_hi)]],
        # producer_hi == 0 selecting all producers (adaptive/rules.py)
        self.read_partitions = (
            [[tuple(r) for r in ranges] for ranges in read_partitions]
            if read_partitions else None
        )
        # columns the producing stage hash-partitioned on: lets the
        # in-task planner (and AQE join demotion) trust co-partitioning
        # instead of seeing Partitioning("unknown", n)
        self.hash_columns = tuple(hash_columns or ())
        self.original_partitions = original_partitions
        shuffled = [
            l for l in self.partition_locations if l.shuffle_output is not None
        ]
        if shuffled and self.read_partitions:
            self._groups = [
                [
                    l for l in shuffled
                    if any(
                        olo <= l.shuffle_output < ohi
                        and (phi == 0 or plo <= l.partition_id < phi)
                        for olo, ohi, plo, phi in ranges
                    )
                ]
                for ranges in self.read_partitions
            ]
        elif shuffled:
            n_out = max(l.shuffle_output for l in shuffled) + 1
            self._groups: List[List[PartitionLocation]] = [
                [l for l in shuffled if l.shuffle_output == q]
                for q in range(n_out)
            ]
        else:
            self._groups = [[l] for l in self.partition_locations]

    def _has_splits(self) -> bool:
        from ..adaptive.rules import layout_has_splits

        return bool(self.read_partitions) and \
            layout_has_splits(self.read_partitions)

    def output_schema(self) -> Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        n = max(len(self._groups), 1)
        # coalesced groups are unions of whole hash buckets, so the hash
        # property survives; producer-level skew splits break it
        if self.hash_columns and not self._has_splits():
            return Partitioning("hash", n, self.hash_columns)
        return Partitioning("unknown", n)

    def estimated_rows(self) -> Optional[int]:
        """EXACT row count from the producers' write-time PartitionStats
        (carried in every PartitionLocation) — consumers planning over
        shuffle input (e.g. the partitioned-join threshold) get real
        numbers, not scan-size guesses. Hash-shuffled stages fan each
        producer out into one location PER consumer partition, all
        carrying that producer's TOTAL stats, so counting distinct
        producers once is what is exact."""
        seen = {}
        # metadata walk over location stats, no per-iteration IO
        # ballista: ignore[cancel-coverage]
        for loc in self.partition_locations:
            n = (loc.stats or {}).get("num_rows")
            if n is None:
                return None
            seen[(loc.stage_id, loc.partition_id)] = int(n)
        return sum(seen.values())

    def with_new_children(self, children):
        return self

    def _load_location(self, loc: PartitionLocation):
        """Fetch+decode ONE shuffle file (local filesystem or data-plane
        socket). Runs on ingest pool workers when a group has several
        producers — the fetches overlap instead of serializing one
        network round-trip per producer. Local reads decode the
        memory-mapped stream file incrementally; remote fetches stream
        bounded chunks through the governed ChunkBuffer (disk spill past
        the budget watermark). Metric increments from worker threads
        ride the usual benign-race policy."""
        from ..io import ipc

        m = self.metrics()
        if not self.FORCE_REMOTE and loc.path and os.path.exists(loc.path):
            m.add_counter("bytes_read", os.path.getsize(loc.path))
            m.add_counter("local_reads")
            _, arrays, nulls, dicts, _ = ipc.read_partition_arrays(loc.path)
        else:
            arrays, nulls, dicts = self._fetch_with_retry(loc)
        return arrays, nulls, dicts

    def _load_group(self, q: int) -> List[ColumnBatch]:
        """Fetch only THIS output partition's files (a consumer task reads
        its own group, not the whole shuffle), producers fetched
        concurrently on the ingest pool. Per-group locking: a read-ahead
        racing the direct consumer loads once, the loser serves from the
        cache. utf8 dictionaries are unioned within the group;
        cross-group concat is handled by concat_batches' dictionary
        unification."""
        if q in self._cache:  # fast path once loaded
            return self._cache[q]
        with self._group_locks.get(q):
            if q in self._cache:
                return self._cache[q]
            from ..io import ipc
            from ..ingest import parallel_map

            parts = parallel_map(self._load_location, self._groups[q])
            batches = ipc.batches_from_parts(self._schema, parts)
            self._cache[q] = batches
            return batches

    def _take_group(self, q: int) -> List[ColumnBatch]:
        """Serve group ``q``, joining a read-ahead future if one is in
        flight (its exceptions surface here, on the consumer). The
        cancel-or-inline rule applies: a future the pool never started
        is cancelled and loaded inline — blocking on it from a pool
        worker (readers execute on ingest producers under MergeExec)
        would deadlock an exhausted pool."""
        with self._inflight_lock:
            fut = self._inflight.pop(q, None)
        if fut is not None and not fut.cancel():
            return fut.result()
        return self._load_group(q)

    def _bg_load(self, q: int) -> List[ColumnBatch]:
        """Read-ahead body: load, then drop the inflight registration
        (an unconsumed future must not pin itself forever)."""
        try:
            return self._load_group(q)
        finally:
            with self._inflight_lock:
                self._inflight.pop(q, None)

    def _read_ahead(self, q: int) -> None:
        """Start loading group ``q`` behind the consumer (merge-style
        multi-partition readers: partition N+1 fetches while N's rows
        are being joined/aggregated). Only fires once this INSTANCE has
        demonstrably served more than one partition — a cluster task's
        single-partition reader must not fetch a neighbour task's
        group. Best-effort and bounded by the shared ingest pool."""
        from ..ingest import ingest_pool, prefetch_batches

        if (not self._served or prefetch_batches() <= 0
                or q >= len(self._groups) or q in self._cache):
            return
        with self._inflight_lock:
            if q in self._inflight:
                return
            self._inflight[q] = ingest_pool().submit(self._bg_load, q)

    def _fetch_with_retry(self, loc: PartitionLocation):
        """Streaming fetch+decode of one producer file with one quick
        retry for transient hiccups; a persistent failure (producer
        executor dead mid-stream, data lost, truncated wire or spill
        bytes, or no known address) raises a tagged ShuffleFetchError
        the scheduler can act on by re-queueing the producer partition —
        recovery works from a half-consumed stream because the attempt's
        partial buffers are released and the re-run refetches whole."""
        import time as _time

        from ..errors import QueryCancelled, ShuffleFetchError
        from ..lifecycle import check_cancel
        from ..observability import trace_span
        from ..testing.faults import fault_point

        if not loc.host or not loc.port:
            raise ShuffleFetchError(
                loc.stage_id, [loc.partition_id], loc.executor_id,
                "producer executor address unknown (lease expired?)",
            )
        last = None
        for attempt in range(2):
            # a cancelled task must stop fetching, not ride out retries
            check_cancel()
            try:
                # 10s covers connect and each recv (not the whole
                # transfer); a dead-but-backlogged peer fails fast
                with trace_span("shuffle.fetch", host=loc.host,
                                stage=loc.stage_id,
                                partition=loc.partition_id,
                                attempt=attempt):
                    # per-attempt: an injected failure is retried like a
                    # real transport hiccup, then surfaces as the tagged
                    # ShuffleFetchError the scheduler re-queues on
                    fault_point("shuffle.fetch", stage=loc.stage_id,
                                partition=loc.partition_id,
                                attempt=attempt)
                    return self._fetch_stream_once(loc, attempt)
            except QueryCancelled:
                raise  # chunk-level cancel is terminal, never retried
            except Exception as e:  # noqa: BLE001 - any transport failure
                last = e
                if attempt == 0:
                    _time.sleep(1.0)
        raise ShuffleFetchError(
            loc.stage_id, [loc.partition_id], loc.executor_id,
            f"{type(last).__name__}: {last}",
        )

    def _fetch_stream_once(self, loc: PartitionLocation, attempt: int):
        """One streaming fetch attempt: wire chunks land in a governed
        ChunkBuffer (RAM within the budget, size-rotated spill files
        past the watermark — never a blocking wait), then decode replays
        them incrementally. The cancel token is checked at EVERY chunk
        boundary on both the receive and decode loops, so
        ``ctx.cancel()``/deadlines abort in-flight transfers within one
        chunk."""
        from ..distributed.dataplane import fetch_partition_chunks
        from ..distributed.spill import ChunkBuffer
        from ..io import ipc
        from ..lifecycle import check_cancel
        from ..testing.faults import fault_point

        m = self.metrics()
        buf = ChunkBuffer()
        try:
            for chunk in fetch_partition_chunks(
                    loc.host, loc.port, loc.job_id, loc.stage_id,
                    loc.partition_id, shuffle_output=loc.shuffle_output,
                    timeout=10.0):
                check_cancel()
                fault_point("shuffle.stream.chunk", stage=loc.stage_id,
                            partition=loc.partition_id, attempt=attempt)
                buf.put(chunk)
            _, arrays, nulls, dicts, _ = \
                ipc.read_partition_arrays_from_chunks(buf.chunks())
        finally:
            buf.close()
        m.add_counter("bytes_read", buf.total_bytes)
        m.add_counter("remote_fetches")
        if buf.spilled_bytes:
            m.add_counter("spilled_bytes", buf.spilled_bytes)
        return arrays, nulls, dicts

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        batches = self._take_group(partition)
        self._read_ahead(partition + 1)
        self._served = True
        yield from batches

    def display(self) -> str:
        out = f"ShuffleReaderExec: {len(self.partition_locations)} partitions"
        if self.read_partitions:
            from ..adaptive.rules import describe_layout

            n_before = self.original_partitions or len(self.read_partitions)
            out += f" [adaptive: {describe_layout(n_before, self.read_partitions)}]"
        return out
