"""Device-resident input assembly for mesh-fused stages.

Replaces the round-2 host funnel: fused-stage producers used to execute on
host, get concatenated in numpy, and be re-uploaded per stage
(`np.asarray` of every column). Now producer partitions are executed with
their output pinned round-robin across the mesh devices, laid out into
uniform per-device batches ON DEVICE (dictionary remap + concat + compact
are XLA gathers), and assembled into one sharded global array with
``jax.make_array_from_single_device_arrays`` — data never round-trips
host memory; only per-slot live-row COUNTS (int32 scalars) sync to pick
the uniform capacity.

Chaining: when a fused stage's producer is itself a mesh-fused operator
(or a projection/filter/partial-aggregate pipeline over one), the
producer's stacked per-device output is fed straight into the consumer's
SPMD program — an HBM-resident stage boundary. This is SURVEY §7's
"device-memory partition cache": consecutive fused stages exchange data
over ICI only (reference model being replaced: materialized IPC files +
rust/core/src/execution_plans/shuffle_reader.rs:77-99).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..columnar import (
    Column,
    ColumnBatch,
    Dictionary,
    empty_batch,
)
from ..compile import bucket_capacity, governed
from ..datatypes import Schema
from ..parallel.mesh import shard_map

# Instrumentation (tests assert the device path actually ran):
#   slot_assemblies — producer outputs laid out over the mesh on device;
#   chained_stages  — stage inputs taken straight from a fused producer's
#                     stacked HBM output (no re-assembly at all).
STATS = {"slot_assemblies": 0, "chained_stages": 0}


def reset_stats() -> None:
    STATS["slot_assemblies"] = 0
    STATS["chained_stages"] = 0


# ---------------------------------------------------------------------------
# dictionary unification (host metadata only; code remap is a device gather)
# ---------------------------------------------------------------------------


def _union_dicts(schema: Schema, batches: List[ColumnBatch]):
    """Per field: one shared dictionary for every batch + per-batch int32
    remap tables (None where codes are already in the shared space).
    Only dictionary VALUES (host metadata) are touched; row data stays on
    device."""
    n_fields = len(schema.fields)
    remaps = [[None] * n_fields for _ in batches]
    dicts: List[Optional[Dictionary]] = []
    for i in range(n_fields):
        ds = [b.columns[i].dictionary for b in batches]
        d0 = next((d for d in ds if d is not None), None)
        if d0 is None:
            dicts.append(None)
            continue
        if all(d is None or d is d0 for d in ds):
            dicts.append(d0)
            continue
        from ..observability import trace_span
        from .. import columnar_registry

        with trace_span("host.dictionary", site="mesh.union",
                        column=schema.fields[i].name, n_dicts=len(ds)):
            # registry: shared-entry dictionaries resolve to the max
            # version + cached int32 remaps (the device gather in
            # _apply_remaps); unregistered fall back to the legacy
            # sorted union inside the registry module
            ud, rms = columnar_registry.unify(ds)
            for bi, r in enumerate(rms):
                if r is not None:
                    remaps[bi][i] = r
        dicts.append(ud)
    return dicts, remaps


def _apply_remaps(schema: Schema, b: ColumnBatch, remap_row, dicts
                  ) -> ColumnBatch:
    """Rebind a batch to the shared dictionaries (device-side code
    gather); also normalizes the schema object so every slot shares one
    pytree aux."""
    cols = []
    for col, r, ud in zip(b.columns, remap_row, dicts):
        d = col.dictionary
        vals = col.values
        if ud is not None:
            if r is not None and d is not ud:
                vals = jnp.take(jnp.asarray(r), vals.astype(jnp.int32),
                                mode="clip")
            d = ud
        cols.append(Column(vals, col.dtype, col.validity, d))
    return ColumnBatch(schema, cols, b.selection, b.num_rows)


# ---------------------------------------------------------------------------
# per-slot layout: compact live rows into a uniform fixed capacity
# ---------------------------------------------------------------------------


def _compact_impl(big: ColumnBatch, cap: int) -> ColumnBatch:
    """Gather live rows to the front of a [cap] batch (validity
    materialized so every slot shares one pytree structure). Traced."""
    from .base import compact_perm

    n = big.capacity
    perm = compact_perm(big.selection, min(cap, n))
    if cap > n:
        perm = jnp.concatenate(
            [perm, jnp.zeros((cap - n,), jnp.int32)]
        )
    live = jnp.arange(cap, dtype=jnp.int32) < big.num_rows
    cols = []
    for col in big.columns:
        vals = jnp.take(col.values, perm)
        validity = (
            jnp.take(col.validity, perm)
            if col.validity is not None
            else jnp.ones((cap,), jnp.bool_)
        )
        cols.append(Column(vals, col.dtype, jnp.logical_and(validity, live),
                           col.dictionary))
    return ColumnBatch(big.schema, cols, live,
                       big.num_rows.astype(jnp.int32))


def _compact_to(big: ColumnBatch, cap: int) -> ColumnBatch:
    """Governed jit of :func:`_compact_impl` at a static capacity."""
    return governed(
        ("mesh.compact_to", cap),
        lambda: partial(_compact_impl, cap=cap),
    )(big)


# ---------------------------------------------------------------------------
# mesh assembly
# ---------------------------------------------------------------------------


def stack_to_mesh(slot_batches: List[ColumnBatch], mesh):
    """Per-device batches -> one stacked ColumnBatch pytree whose leaves
    are [n_dev, ...] arrays sharded over the mesh axis. Each slot's
    leaves are placed on their device (a device-to-device copy when the
    slot was computed elsewhere — ICI, never host) and assembled without
    any global materialization. Single-process alias of
    multihost.stack_local_to_global (where local devices = all)."""
    from ..parallel.multihost import stack_local_to_global

    return stack_local_to_global(slot_batches, mesh)


def assemble_over_mesh(producer, schema: Schema, mesh
                       ) -> Tuple[ColumnBatch, int]:
    """Execute ``producer`` with each partition pinned to a mesh device
    (round-robin) and lay the output over the mesh: per-slot dictionary
    remap + concat + compaction all run as device gathers; only live-row
    counts sync to host. Producers with fewer partitions than devices
    are ROW-split instead (device-side window slices of the compacted
    whole), so a 1-partition dim-table scan doesn't put every row in one
    slot and inflate the uniform capacity n_dev-fold.

    Multi-process (cross-host) meshes: each process executes only the
    partitions of ITS devices' slots and supplies only local shards; the
    uniform capacity is agreed through a replicated global max.
    Correctness requires utf8 dictionaries to be content-identical
    across processes — guaranteed for table scans (table-wide
    dictionaries are built over all partitions of the source, io/text.py).
    Returns (stacked batch, per-device capacity)."""
    from ..parallel import multihost

    devices = list(mesh.devices.flat)
    n_dev = len(devices)
    multi = multihost.is_multiprocess()
    local_ids = {d.id for d in jax.local_devices()}
    local_slots = [i for i, d in enumerate(devices)
                   if not multi or d.id in local_ids]
    nparts = producer.output_partitioning().num_partitions
    row_split = nparts < n_dev
    slots: List[List[ColumnBatch]] = [[] for _ in range(n_dev)]
    for p in range(nparts):
        slot = p % n_dev
        if multi and not row_split and slot not in local_slots:
            continue  # another process owns this slot's device
        if row_split:
            slots[slot].extend(producer.execute(p))
        else:
            with jax.default_device(devices[slot]):
                for b in producer.execute(p):
                    slots[slot].append(b)
    for i in local_slots:
        if not slots[i] and not row_split:
            slots[i].append(empty_batch(schema))

    flat = [b for s in slots for b in s]
    dicts, remap_rows = _union_dicts(schema, flat)

    from .base import concat_batches

    slot_bigs: dict = {}
    i = 0
    for idx in range(n_dev):
        s = slots[idx]
        if not s:
            continue
        rows = remap_rows[i : i + len(s)]
        i += len(s)
        remapped = [
            _apply_remaps(schema, b, r, dicts) for b, r in zip(s, rows)
        ]
        slot_bigs[idx] = (remapped[0] if len(remapped) == 1
                          else concat_batches(schema, remapped))

    STATS["slot_assemblies"] += 1
    if row_split:
        # every process reads the whole (small) producer and slices its
        # local windows — duplicated work, but globally consistent
        bigs = [slot_bigs[k] for k in sorted(slot_bigs)]
        if not bigs:  # producer emitted nothing (e.g. empty MemTable)
            bigs = [empty_batch(schema)]
        big = bigs[0] if len(bigs) == 1 else concat_batches(schema, bigs)
        n = int(big.num_rows)  # scalar sync only
        cap = bucket_capacity(max(-(-n // n_dev), 1))
        packed = _compact_to(big, cap=n_dev * cap)
        slot_batches = [
            _window_slot(packed, d * cap, cap,
                         min(max(n - d * cap, 0), cap))
            for d in local_slots
        ]
        return multihost.stack_local_to_global(slot_batches, mesh), cap

    if multi:
        # capacity must agree across processes: replicated global max
        local_counts = [slot_bigs[i].num_rows for i in local_slots]
        gcounts = multihost.stack_local_to_global(local_counts, mesh)
        cap = bucket_capacity(max(multihost.host_max(gcounts), 1))
    else:
        # ONE batched fetch for all slot counts: sequential int() reads
        # would pay a device->host round-trip per device
        from ..observability.tracing import trace_span

        with trace_span("device.block", site="mesh.input_counts",
                        n=len(local_slots)):
            counts = [int(c) for c in jax.device_get(
                [slot_bigs[i].num_rows for i in local_slots])]
        cap = bucket_capacity(max(max(counts), 1))
    slot_batches = [_compact_to(slot_bigs[i], cap=cap)
                    for i in local_slots]
    return multihost.stack_local_to_global(slot_batches, mesh), cap


def _window_slot(packed: ColumnBatch, start: int, cap: int,
                 count: int) -> ColumnBatch:
    """Rows [start, start+cap) of a front-compacted batch as a slot batch
    (device-side slices; ``count`` live rows at the front)."""
    cols = [
        Column(c.values[start : start + cap], c.dtype,
               (c.validity[start : start + cap]
                if c.validity is not None
                else jnp.ones((cap,), jnp.bool_)),
               c.dictionary)
        for c in packed.columns
    ]
    live = packed.selection[start : start + cap]
    return ColumnBatch(packed.schema, cols, live,
                       jnp.asarray(np.int32(count)))


# ---------------------------------------------------------------------------
# HBM chaining: fused producer -> fused consumer without leaving the mesh
# ---------------------------------------------------------------------------


# mesh.* governed namespaces are LRU-bounded (compile.MESH_NS_CAP):
# their keys hold meshes and pytree structures whose aux-data pins
# identity-hashed per-query Dictionary objects — an unbounded cache
# would pin executables + dictionaries forever
from ..compile import MESH_NS_CAP as _MESH_NS_CAP


def _maybe_compact_stacked(stacked: ColumnBatch, mesh,
                           shrink_factor: int = 4) -> ColumnBatch:
    """Shrink a sparse stacked batch with one per-device SPMD compaction
    (costs a host sync on the [n_dev] live counts — int32s, not data)."""
    from ..parallel.multihost import host_max

    cap = int(stacked.selection.shape[1])
    new_cap = max(bucket_capacity(host_max(stacked.num_rows)), 8)
    if new_cap * shrink_factor > cap:
        return stacked
    axis = mesh.axis_names[0]

    def build():
        @partial(shard_map, mesh=mesh, in_specs=(P(axis),),
                 out_specs=P(axis), check_vma=False)
        def run(st):
            b = jax.tree.map(lambda x: x[0], st)
            out = _compact_impl(b, new_cap)
            return jax.tree.map(lambda x: x[None], out)

        return run

    key = ("mesh.compact", mesh, cap, new_cap, jax.tree.structure(stacked))
    return governed(key, build, cap=_MESH_NS_CAP)(stacked)


def _chain_pipeline(plan, chain, inner: ColumnBatch, mesh) -> ColumnBatch:
    """Apply a fused PipelineOp chain per device over a stacked input."""
    axis = mesh.axis_names[0]

    def build():
        # twins: don't pin the producer subtree in the governed entry
        twins = [op.trace_twin() for op in chain]

        @partial(shard_map, mesh=mesh, in_specs=(P(axis),),
                 out_specs=P(axis), check_vma=False)
        def run(st):
            b = jax.tree.map(lambda x: x[0], st)
            for op in twins:
                b = op.device_transform(b)
            return jax.tree.map(lambda x: x[None], b)

        return run

    key = ("mesh.chain", tuple(op.compile_signature() for op in chain),
           mesh, int(inner.selection.shape[1]))
    return governed(key, build, cap=_MESH_NS_CAP,
                    metrics=plan.metrics())(inner)


def _chain_partial_agg(agg, inner: ColumnBatch, mesh) -> ColumnBatch:
    """Run a partial HashAggregate per device over a stacked input
    (adaptive group capacity with whole-SPMD retry, like the final
    aggregate inside MeshAggExec)."""
    from ..columnar import round_capacity

    axis = mesh.axis_names[0]
    in_cap = int(inner.selection.shape[1])
    cap = agg.group_capacity
    while True:
        fn = agg._get_grouped_fn(cap, in_cap)

        def build():
            @partial(shard_map, mesh=mesh, in_specs=(P(axis),),
                     out_specs=(P(axis), P(axis)), check_vma=False)
            def run(st):
                b = jax.tree.map(lambda x: x[0], st)
                out, ng = fn(b)
                return jax.tree.map(lambda x: x[None], out), ng[None]

            return run

        key = ("mesh.partial_agg", agg.compile_signature(), mesh, in_cap,
               cap)
        out_stacked, ngs = governed(key, build, cap=_MESH_NS_CAP,
                                    metrics=agg.metrics())(inner)
        from ..parallel.multihost import host_max

        ng = host_max(ngs)  # multihost-safe replicated max
        if ng <= cap:
            return out_stacked
        cap = round_capacity(ng)


def _try_chain(plan, mesh) -> Optional[ColumnBatch]:
    """Stacked per-device output for plans rooted in a mesh-fused
    operator (possibly under projection/filter/partial-agg wrappers), or
    None when the plan must be assembled from host-driven partitions."""
    from .aggregate import HashAggregateExec
    from .base import PipelineOp
    from .mesh_agg import MeshAggExec, MeshJoinExec

    n_dev = mesh.devices.size
    if isinstance(plan, (MeshAggExec, MeshJoinExec)):
        if plan.n_devices != n_dev:
            return None
        return plan.execute_stacked(mesh)
    if isinstance(plan, PipelineOp):
        chain, source = plan._pipeline_chain()
        inner = _try_chain(source, mesh)
        if inner is None:
            return None
        return _chain_pipeline(plan, chain, inner, mesh)
    if isinstance(plan, HashAggregateExec) and plan.mode == "partial" \
            and plan.group_exprs:
        inner = _try_chain(plan.child, mesh)
        if inner is None:
            return None
        return _chain_partial_agg(plan, inner, mesh)
    return None


def stacked_input(producer, schema: Schema, mesh) -> Tuple[ColumnBatch, int]:
    """The mesh-fused operator input contract: ``producer``'s rows as a
    stacked [n_dev, cap] ColumnBatch sharded over the mesh, + cap.
    Chains HBM-resident when the producer is itself mesh-fused; never
    round-trips row data through host either way."""
    chained = _try_chain(producer, mesh)
    if chained is not None:
        STATS["chained_stages"] += 1
        chained = _maybe_compact_stacked(chained, mesh)
        return chained, int(chained.selection.shape[1])
    return assemble_over_mesh(producer, schema, mesh)
