"""Join physical operator.

TPU-native equivalent of the reference's ``HashJoinExec`` (reference:
rust/core/proto/ballista.proto:399-407; the distributed planner passes join
children through without a co-partition stage, rust/scheduler/src/
planner.rs:172-173 — we do the same in round 1, with the build side merged
to a single partition).

The build (left) side is materialized once and sorted (kernels.join);
probe-side batches stream through a jitted probe that appends gathered
build columns. FK->PK joins (unique build keys) take the no-expansion fast
path; duplicate build keys fall back to the expanding probe with adaptive
output capacity.

Join types: inner, left (preserves PROBE side — the planner picks which
logical side becomes the probe accordingly), semi, anti.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, ColumnBatch, round_capacity
from ..datatypes import Schema
from ..errors import ExecutionError, NotImplementedError_
from ..kernels import join as join_k
from .base import PhysicalPlan, Partitioning, concat_batches

JOIN_TYPES = ("inner", "left", "semi", "anti")


class JoinExec(PhysicalPlan):
    """build = left child (merged to 1 partition), probe = right child."""

    def __init__(
        self,
        build: PhysicalPlan,
        probe: PhysicalPlan,
        on: List[Tuple[str, str]],  # (build_col, probe_col)
        how: str = "inner",
        null_aware: bool = False,
        partitioned: bool = False,
    ):
        if how not in JOIN_TYPES:
            raise NotImplementedError_(f"join type {how}")
        if not 1 <= len(on) <= 2:
            raise NotImplementedError_("joins support 1-2 key columns")
        self.build = build
        self.probe = probe
        self.on = list(on)
        self.how = how
        self.null_aware = null_aware  # SQL NOT IN anti-join semantics
        # partitioned: both children are hash-partitioned on the join keys
        # with the SAME partition count/hash (the planner wraps them in
        # RepartitionExec), so partition p joins build[p] x probe[p] and
        # the build side never merges across partitions. Beats the
        # reference, which always passes join children through unsplit
        # (reference: rust/scheduler/src/planner.rs:172-173).
        self.partitioned = partitioned
        self._build_data = {}  # partition -> (table, batch, unique, has_null)
        self._jit_probe = {}

    # -- composite keys ------------------------------------------------------

    def _key_of(self, batch: ColumnBatch, cols: List[str]):
        """(int64 key, live-mask-extension). Two-column keys pack as
        (a << 32) | b — exact for the 31/32-bit key ranges checked in
        _check_key_ranges."""
        first = batch.column(cols[0])
        keys = first.values.astype(jnp.int64)
        live_ext = first.validity
        if len(cols) == 2:
            second = batch.column(cols[1])
            keys = (keys << 32) | (second.values.astype(jnp.int64)
                                   & jnp.int64(0xFFFFFFFF))
            if second.validity is not None:
                live_ext = (
                    second.validity if live_ext is None
                    else jnp.logical_and(live_ext, second.validity)
                )
        return keys, live_ext

    def _check_key_ranges(self, batch: ColumnBatch, cols: List[str]):
        if len(cols) != 2:
            return
        import numpy as np

        a = np.asarray(batch.column(cols[0]).values)
        b = np.asarray(batch.column(cols[1]).values)
        sel = np.asarray(batch.selection)
        if sel.any():
            if (np.abs(a[sel]) >= (1 << 31)).any() or (b[sel] < 0).any() \
                    or (b[sel] >= (1 << 32) - 1).any():
                raise ExecutionError(
                    f"composite join keys {cols} exceed the packable 31/32-bit "
                    "range"
                )

    # -- schema -------------------------------------------------------------

    def output_schema(self) -> Schema:
        bs, ps = self.build.output_schema(), self.probe.output_schema()
        if self.how in ("semi", "anti"):
            return ps
        seen = {f.name for f in bs.fields}
        extra = [f for f in ps.fields if f.name not in seen]
        # build fields become nullable under probe-preserving (left) joins
        bf = list(bs.fields)
        return Schema(bf + extra)

    def output_partitioning(self) -> Partitioning:
        return self.probe.output_partitioning()

    def children(self):
        return [self.build, self.probe]

    def with_new_children(self, children):
        return JoinExec(children[0], children[1], self.on, self.how,
                        self.null_aware, self.partitioned)

    def display(self) -> str:
        on = ", ".join(f"{l}={r}" for l, r in self.on)
        part = " partitioned" if self.partitioned else ""
        return f"JoinExec: how={self.how} on=[{on}]{part}"

    # -- execution ----------------------------------------------------------

    def _empty_build_batch(self) -> ColumnBatch:
        """All-dead build batch for legitimately empty hash partitions."""
        from ..columnar import empty_batch

        return empty_batch(self.build.output_schema())

    def _materialize_build(self, partition: int = 0):
        key = partition if self.partitioned else 0
        if key in self._build_data:
            return self._build_data[key]
        if self.partitioned:
            batches = list(self.build.execute(partition))
        else:
            nparts = self.build.output_partitioning().num_partitions
            batches = []
            for p in range(nparts):
                batches.extend(self.build.execute(p))
        if not batches:
            if self.partitioned:  # a hash partition may be empty
                batches = [self._empty_build_batch()]
            else:
                raise ExecutionError("join build side produced no batches")
        bb = concat_batches(self.build.output_schema(), batches)
        bcols = [b for b, _ in self.on]
        self._check_key_ranges(bb, bcols)
        keys, live_ext = self._key_of(bb, bcols)
        live = bb.selection
        has_null_key = False
        if live_ext is not None:
            has_null_key = bool(
                np.any(np.asarray(bb.selection) & ~np.asarray(live_ext))
            )
            live = jnp.logical_and(live, live_ext)
        table = jax.jit(join_k.build_lookup)(keys, live)
        sk = np.asarray(table.sorted_keys)
        nlive = int(table.num_live)
        unique = not bool(np.any(sk[1 : nlive] == sk[: nlive - 1])) if nlive > 1 else True
        self._build_data[key] = (table, bb, unique, has_null_key)
        return self._build_data[key]

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        table, build_batch, unique, has_null_key = \
            self._materialize_build(partition)
        if self.how == "anti" and self.null_aware and has_null_key:
            # SQL NOT IN with a NULL in the subquery: predicate is never
            # true -> empty result
            for pb in self.probe.execute(partition):
                yield pb.with_selection(
                    jnp.zeros((pb.capacity,), jnp.bool_)
                )
            return
        pcols = [p for _, p in self.on]
        for pb in self.probe.execute(partition):
            self._check_key_ranges(pb, pcols)
            if unique:
                yield self._probe_unique_batch(table, build_batch, pb)
            else:
                yield from self._probe_expand_batch(table, build_batch, pb)

    # fast path: unique build keys ------------------------------------------

    def _probe_keys(self, pb: ColumnBatch):
        pkeys, live_ext = self._key_of(pb, [p for _, p in self.on])
        plive = pb.selection
        if live_ext is not None:
            plive = jnp.logical_and(plive, live_ext)
        return pkeys, plive

    def _probe_unique_batch(self, table, build_batch, pb: ColumnBatch) -> ColumnBatch:
        key = ("u", pb.capacity, build_batch.capacity)
        if key not in self._jit_probe:

            def run(table, bb: ColumnBatch, pb: ColumnBatch) -> ColumnBatch:
                pkeys, plive = self._probe_keys(pb)
                build_rows, matched = join_k.probe_unique(table, pkeys, plive)
                return self._assemble(bb, pb, build_rows, matched,
                                      pb.selection, None)

            self._jit_probe[key] = jax.jit(run)
        return self._jit_probe[key](table, build_batch, pb)

    # general path: expanding probe -----------------------------------------

    def _probe_expand_batch(self, table, build_batch,
                            pb: ColumnBatch) -> Iterator[ColumnBatch]:
        if self.how not in ("inner", "left", "semi", "anti"):
            raise NotImplementedError_(
                f"{self.how} join with duplicate build keys"
            )
        if self.how in ("semi", "anti"):
            # membership only: unique probe works regardless of build dups
            yield self._probe_unique_batch(table, build_batch, pb)
            return
        out_cap = pb.capacity
        while True:
            key = ("e", pb.capacity, build_batch.capacity, out_cap)
            if key not in self._jit_probe:

                def run(table, bb, pb, _cap=out_cap):
                    pkeys, plive = self._probe_keys(pb)
                    prows, brows, olive, total = join_k.probe_expand(
                        table, pkeys, plive, _cap
                    )
                    out = self._assemble_expanded(bb, pb, prows, brows, olive)
                    return out, total

                self._jit_probe[key] = jax.jit(run)
            out, total = self._jit_probe[key](table, build_batch, pb)
            t = int(total)
            if t <= out_cap:
                break
            out_cap = round_capacity(t)
        yield out
        if self.how == "left":
            # preserved probe rows with no match, null build columns
            key = ("l", pb.capacity, build_batch.capacity)
            if key not in self._jit_probe:

                def run_unmatched(table, bb, pb):
                    pkeys, plive = self._probe_keys(pb)
                    counts = join_k.probe_counts(table, pkeys)
                    unmatched = jnp.logical_and(pb.selection,
                                                jnp.logical_or(
                                                    jnp.logical_not(plive),
                                                    counts == 0))
                    zero = jnp.zeros((pb.capacity,), jnp.int32)
                    no_match = jnp.zeros((pb.capacity,), jnp.bool_)
                    return self._assemble(bb, pb, zero, no_match, unmatched,
                                          None)

                self._jit_probe[key] = jax.jit(run_unmatched)
            yield self._jit_probe[key](table, build_batch, pb)

    # assembly --------------------------------------------------------------

    def _assemble(self, bb, pb, build_rows, matched, probe_sel, _):
        """Probe-aligned output (no expansion). Traced."""
        schema = self.output_schema()
        if self.how == "semi":
            sel = jnp.logical_and(probe_sel, matched)
            return pb.with_selection(sel)
        if self.how == "anti":
            sel = jnp.logical_and(probe_sel, jnp.logical_not(matched))
            if self.null_aware:
                # NULL NOT IN (...) is unknown, not true: drop null keys
                for _, pcol in self.on:
                    v = pb.column(pcol).validity
                    if v is not None:
                        sel = jnp.logical_and(sel, v)
            return pb.with_selection(sel)
        if self.how == "inner":
            sel = jnp.logical_and(probe_sel, matched)
        else:  # left (probe-preserving outer)
            sel = probe_sel
        cols = []
        ps = pb.schema
        for f in schema.fields:
            if ps.has_field(f.name):
                c = pb.column(f.name)
                cols.append(c)
            else:
                c = bb.column(f.name)
                vals = jnp.take(c.values, build_rows)
                validity = jnp.take(c.validity, build_rows) if c.validity is not None \
                    else jnp.ones((pb.capacity,), jnp.bool_)
                validity = jnp.logical_and(validity, matched)
                cols.append(Column(vals, c.dtype, validity, c.dictionary))
        return ColumnBatch(schema, cols, sel, jnp.sum(sel).astype(jnp.int32))

    def _assemble_expanded(self, bb, pb, prows, brows, olive):
        schema = self.output_schema()
        cols = []
        ps = pb.schema
        for f in schema.fields:
            if ps.has_field(f.name):
                c = pb.column(f.name)
                vals = jnp.take(c.values, prows)
                validity = jnp.take(c.validity, prows) if c.validity is not None else None
            else:
                c = bb.column(f.name)
                vals = jnp.take(c.values, brows)
                validity = jnp.take(c.validity, brows) if c.validity is not None else None
            cols.append(Column(vals, c.dtype, validity, c.dictionary))
        return ColumnBatch(
            schema, cols, olive, jnp.sum(olive).astype(jnp.int32)
        )
