"""Join physical operator.

TPU-native equivalent of the reference's ``HashJoinExec`` (reference:
rust/core/proto/ballista.proto:399-407; the distributed planner passes join
children through without a co-partition stage, rust/scheduler/src/
planner.rs:172-173 — we do the same in round 1, with the build side merged
to a single partition).

The build (left) side is materialized once and sorted (kernels.join);
probe-side batches stream through a jitted probe that appends gathered
build columns. FK->PK joins (unique build keys) take the no-expansion fast
path; duplicate build keys fall back to the expanding probe with adaptive
output capacity.

Join types: inner, left (preserves PROBE side — the planner picks which
logical side becomes the probe accordingly), semi, anti, and full (a
probe-preserving pass plus one batch of unmatched build rows).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, ColumnBatch, round_capacity
from ..compile import bucket_capacity, governed
from ..datatypes import Schema
from ..errors import ExecutionError, NotImplementedError_
from ..kernels import join as join_k
from ..observability.metrics import metrics_enabled
from .base import PhysicalPlan, Partitioning, concat_batches

JOIN_TYPES = ("inner", "left", "semi", "anti", "full")


class JoinExec(PhysicalPlan):
    """build = left child (merged to 1 partition), probe = right child."""

    def __init__(
        self,
        build: PhysicalPlan,
        probe: PhysicalPlan,
        on: List[Tuple[str, str]],  # (build_col, probe_col)
        how: str = "inner",
        null_aware: bool = False,
        partitioned: bool = False,
        adaptive_note: Optional[str] = None,
        probe_chain: Optional[List] = None,
        probe_key_raw: Optional[dict] = None,
    ):
        if how not in JOIN_TYPES:
            raise NotImplementedError_(f"join type {how}")
        if not on:
            raise NotImplementedError_("joins require at least one key")
        self.build = build
        self.probe = probe
        self.on = list(on)
        self.how = how
        self.null_aware = null_aware  # SQL NOT IN anti-join semantics
        # partitioned: both children are hash-partitioned on the join keys
        # with the SAME partition count/hash (the planner wraps them in
        # RepartitionExec), so partition p joins build[p] x probe[p] and
        # the build side never merges across partitions. Beats the
        # reference, which always passes join children through unsplit
        # (reference: rust/scheduler/src/planner.rs:172-173).
        self.partitioned = partitioned
        # set when adaptive execution rewrote this join (EXPLAIN surface)
        self.adaptive_note = adaptive_note
        # whole-stage fusion (physical/fusion.py): the Filter/Projection
        # chain that used to feed the probe side, applied INSIDE every
        # traced probe program instead of as a separate per-batch jit.
        # When set, ``probe`` is the chain's SOURCE; ``probe_key_raw``
        # maps each post-chain probe key column name to its raw source
        # column (for the host-side dictionary remap).
        self.probe_chain = tuple(probe_chain or ())
        self.probe_key_raw = dict(probe_key_raw or {})
        # partition -> (table, batch, unique, has_null, key mode,
        #               codec tables, build keys, build live)
        self._build_data = {}
        self._remap_cache = {}
        # concurrent partition execution (ingest iter_partitions): a
        # merged build is shared by every partition (key 0) and must
        # materialize exactly once — the heavy device work makes this
        # NOT a benign race. Per-KEY locks so a partitioned join's
        # independent per-partition builds still overlap.
        from ..ingest import KeyedLocks

        self._build_locks = KeyedLocks()

    def _signature_parts(self) -> tuple:
        # partitioned/adaptive_note steer HOST orchestration only — no
        # traced closure reads them, so a demoted (adaptive) join reuses
        # the original join's compiled probes. A fused probe chain IS
        # traced, so its signatures ride the key.
        return (self.how, tuple(self.on), self.null_aware,
                self.build.output_schema(), self._probe_out_schema(),
                tuple(op.compile_signature() for op in self.probe_chain))

    def _probe_out_schema(self) -> Schema:
        """Schema of probe batches AFTER the fused chain (equals the
        probe child's schema when nothing is fused)."""
        if self.probe_chain:
            return self.probe_chain[-1].output_schema()
        return self.probe.output_schema()

    def _probe_prologue(self, pb: ColumnBatch) -> ColumnBatch:
        """Fused probe-side chain (innermost first). Traced."""
        for op in self.probe_chain:
            pb = op.device_transform(pb)
        return pb

    def _detach(self) -> None:
        from .base import SchemaLeaf

        self.build = SchemaLeaf(self.build.output_schema())
        self.probe = SchemaLeaf(self.probe.output_schema())
        self.probe_chain = tuple(op.trace_twin()
                                 for op in self.probe_chain)
        self._build_data = {}   # materialized build-side device buffers
        self._remap_cache = {}  # per-query dictionaries

    # -- composite keys ------------------------------------------------------
    #
    # Three representations, picked at build materialization:
    #   "raw"    1 key column: its int64 values, exact.
    #   "packed" 2 key columns within 31/32-bit ranges: (a << 32) | b.
    #   "codec"  anything else: each key column is iteratively RANKED
    #            against the (sorted) build side and packed with the
    #            running code, which is re-ranked back under the build
    #            capacity — exact for any number/width of key columns
    #            (no hash collisions), static shapes, ~2 sorts per extra
    #            column. Probe rows ride the same tables; a probe value
    #            absent from the build fails its exactness check and can
    #            never collide into a live build code.

    def _key_of(self, batch: ColumnBatch, cols: List[str]):
        """raw/packed representations (codec handled separately)."""
        first = batch.column(cols[0])
        keys = first.values.astype(jnp.int64)
        live_ext = first.validity
        if len(cols) == 2:
            second = batch.column(cols[1])
            keys = (keys << 32) | (second.values.astype(jnp.int64)
                                   & jnp.int64(0xFFFFFFFF))
            if second.validity is not None:
                live_ext = (
                    second.validity if live_ext is None
                    else jnp.logical_and(live_ext, second.validity)
                )
        return keys, live_ext

    # Dense direct-index mode limits: table entries are int32 rows; cap
    # the table at 16M entries (64 MB HBM) and at 8x the build capacity
    # so pathological sparse keys (e.g. hash-like ids) stay on the
    # sorted path.
    _DENSE_MAX_SIZE = 1 << 24
    _DENSE_FACTOR = 8

    def _build_stats(self, bb: ColumnBatch, cols: List[str]):
        """ONE jitted program -> (host scalars, device live mask): per-col
        min/max over selected rows, live-key min/max for the first col,
        null-key flag. Only the scalars cross to host — replaces the old
        host-side full-column pulls, which over a slow host<->device link
        cost more than the join itself. The combined live mask stays on
        device for the build to reuse (it is exactly the
        selection & key-validity reduction the raw/packed paths need)."""

        tw = self.trace_twin()

        def stats(bb):
            live_ext = tw._key_live_ext(bb, cols)
            live = bb.selection
            if live_ext is not None:
                live = jnp.logical_and(live, live_ext)
                has_null = jnp.any(jnp.logical_and(
                    bb.selection, jnp.logical_not(live_ext)))
            else:
                has_null = jnp.asarray(False)
            out = {"has_null": has_null,
                   "nlive": jnp.sum(live.astype(jnp.int32))}
            maxi = jnp.iinfo(jnp.int64).max
            for i, c in enumerate(cols):
                v = bb.column(c).values.astype(jnp.int64)
                out[f"sel_min_{i}"] = jnp.min(
                    jnp.where(bb.selection, v, maxi))
                out[f"sel_max_{i}"] = jnp.max(
                    jnp.where(bb.selection, v, -maxi))
            v0 = bb.column(cols[0]).values.astype(jnp.int64)
            out["live_min"] = jnp.min(jnp.where(live, v0, maxi))
            out["live_max"] = jnp.max(jnp.where(live, v0, -maxi))
            return out, live

        fn = self.governed_jit(("join.stats",), lambda: stats)
        scalars, live = fn(bb)
        from ..observability import trace_span

        with trace_span("device.block", site="join.stats"):
            return jax.device_get(scalars), live

    def _pick_mode(self, stats, ncols: int) -> str:
        if ncols == 1:
            return "raw"
        if ncols > 2:
            return "codec"  # codec handles any column count
        amin, amax = int(stats["sel_min_0"]), int(stats["sel_max_0"])
        bmin, bmax = int(stats["sel_min_1"]), int(stats["sel_max_1"])
        if amin > amax:
            return "packed"  # no selected rows: any representation works
        packable = (max(abs(amin), abs(amax)) < (1 << 31)
                    and bmin >= 0 and bmax < (1 << 32) - 1)
        return "packed" if packable else "codec"

    def _key_live_ext(self, batch: ColumnBatch, cols: List[str]):
        live_ext = None
        for c in cols:
            v = batch.column(c).validity
            if v is not None:
                live_ext = v if live_ext is None else jnp.logical_and(
                    live_ext, v)
        return live_ext

    def _codec_build(self, bb: ColumnBatch, cols: List[str]):
        """(codes, live, tables) for the build side. Traced."""
        live_ext = self._key_live_ext(bb, cols)
        live = bb.selection
        if live_ext is not None:
            live = jnp.logical_and(live, live_ext)
        nlive = jnp.sum(live.astype(jnp.int32))
        cap = bb.capacity
        maxi = jnp.iinfo(jnp.int64).max
        tables = []
        code = None
        for c in cols:
            v = bb.column(c).values.astype(jnp.int64)
            sv = jnp.sort(jnp.where(live, v, maxi))
            r = jnp.searchsorted(sv, v).astype(jnp.int64)
            if code is None:
                code = r
                tables.append((sv, None))
            else:
                combined = code * (cap + 1) + r
                sc = jnp.sort(jnp.where(live, combined, maxi))
                code = jnp.searchsorted(sc, combined).astype(jnp.int64)
                tables.append((sv, sc))
        return code, live, (tuple(tables), nlive)

    def _codec_probe(self, vals, tables, nlive):
        """(codes, exact mask) for probe key value arrays using the
        build's rank tables. Traced."""
        exact = jnp.ones(vals[0].shape, jnp.bool_)
        cap = tables[0][0].shape[0]
        code = None
        for v, (sv, sc) in zip(vals, tables):
            r = jnp.searchsorted(sv, v).astype(jnp.int64)
            hit = jnp.take(sv, jnp.minimum(r, cap - 1)) == v
            exact = jnp.logical_and(exact,
                                    jnp.logical_and(r < nlive, hit))
            if code is None:
                code = r
            else:
                combined = code * (cap + 1) + r
                rc = jnp.searchsorted(sc, combined).astype(jnp.int64)
                hitc = jnp.take(sc, jnp.minimum(rc, cap - 1)) == combined
                exact = jnp.logical_and(exact,
                                        jnp.logical_and(rc < nlive, hitc))
                code = rc
        return code, exact

    # -- schema -------------------------------------------------------------

    def output_schema(self) -> Schema:
        bs, ps = self.build.output_schema(), self._probe_out_schema()
        if self.how in ("semi", "anti"):
            return ps
        seen = {f.name for f in bs.fields}
        extra = [f for f in ps.fields if f.name not in seen]
        # build fields become nullable under probe-preserving (left) joins
        bf = list(bs.fields)
        return Schema(bf + extra)

    def estimated_rows(self):
        """Semi/anti joins emit a SUBSET of the probe side — the base
        sum-of-children over-estimate would also count the membership
        list, inflating a pruned side enough to flip cost-based
        orientation the wrong way (q18's IN-subquery side estimated
        above the full lineitem scan)."""
        if self.how in ("semi", "anti"):
            return self.probe.estimated_rows()
        return super().estimated_rows()

    def output_partitioning(self) -> Partitioning:
        if self.how == "full":
            # one task streams every probe partition and appends the
            # unmatched build rows (needs the global build-hit bitmap)
            return Partitioning("unknown", 1)
        return self.probe.output_partitioning()

    def children(self):
        return [self.build, self.probe]

    def with_new_children(self, children):
        return JoinExec(children[0], children[1], self.on, self.how,
                        self.null_aware, self.partitioned,
                        self.adaptive_note, list(self.probe_chain),
                        self.probe_key_raw)

    def display(self) -> str:
        on = ", ".join(f"{l}={r}" for l, r in self.on)
        part = " partitioned" if self.partitioned else ""
        note = f" [adaptive: {self.adaptive_note}]" if self.adaptive_note \
            else ""
        fused = ""
        if self.probe_chain:
            ops = "→".join(type(op).__name__.replace("Exec", "")
                           for op in self.probe_chain)
            fused = f" [fused probe: {ops}]"
        return f"JoinExec: how={self.how} on=[{on}]{part}{note}{fused}"

    # -- execution ----------------------------------------------------------

    def _empty_build_batch(self) -> ColumnBatch:
        """All-dead build batch for legitimately empty hash partitions."""
        from ..columnar import empty_batch

        return empty_batch(self.build.output_schema())

    def _materialize_build(self, partition: int = 0):
        key = partition if self.partitioned else 0
        if key in self._build_data:  # fast path, no lock once built
            return self._build_data[key]
        with self._build_locks.get(key):
            return self._materialize_build_locked(key, partition)

    def _materialize_build_locked(self, key: int, partition: int):
        if key in self._build_data:
            return self._build_data[key]
        if self.partitioned:
            batches = list(self.build.execute(partition))
        else:
            from ..ingest import iter_partitions

            batches = list(iter_partitions(
                self.build,
                range(self.build.output_partitioning().num_partitions)))
        if not batches:
            if self.partitioned:  # a hash partition may be empty
                batches = [self._empty_build_batch()]
            else:
                raise ExecutionError("join build side produced no batches")
        bb = concat_batches(self.build.output_schema(), batches)
        bcols = [b for b, _ in self.on]
        stats, stats_live = self._build_stats(bb, bcols)
        has_null_key = bool(stats["has_null"])
        nlive = int(stats["nlive"])
        mode = self._pick_mode(stats, len(bcols))
        if mode in ("raw", "packed"):
            keys, _ = self._key_of(bb, bcols)
            live = stats_live
            key_tables = ()
        else:
            codec_fn = self.governed_jit(
                ("join.codec_build",),
                lambda: (lambda b, _tw=self.trace_twin():
                         _tw._codec_build(b, bcols)))
            keys, live, key_tables = codec_fn(bb)
        table = None
        unique = True
        if mode == "raw" and nlive > 0:
            base = int(stats["live_min"])
            size = int(stats["live_max"]) - base + 1
            if 0 < size <= min(self._DENSE_MAX_SIZE,
                               self._DENSE_FACTOR * bb.capacity):
                # quantize the (static) table size so successive builds
                # with different key ranges reuse one compiled program;
                # padding slots stay -1 and can never match
                size = round_capacity(size)
                # operator-independent kernel: key WITHOUT the join
                # signature so every join shares one compiled entry
                # (metrics still bind to this operator)
                dense_fn = governed(
                    ("join.dense",), lambda: join_k.build_dense,
                    metrics=self.metrics() if metrics_enabled() else None,
                    jit_kwargs={"static_argnames": ("size",)})
                rows, dup = dense_fn(keys, live, jnp.int64(base), size=size)
                if not bool(dup):
                    table = join_k.BuildTable(
                        sorted_keys=None, order=None,
                        num_live=jnp.asarray(nlive, jnp.int32),
                        dense_rows=rows, dense_base=jnp.int64(base))
        if table is None:
            sorted_fn = governed(
                ("join.sorted",), lambda: join_k.build_sorted_with_unique,
                metrics=self.metrics() if metrics_enabled() else None,
                aot=True)
            table, uniq = sorted_fn(keys, live)
            unique = bool(uniq)
        self._build_data[key] = (table, bb, unique, has_null_key, mode,
                                 key_tables, keys, live)
        return self._build_data[key]

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        (table, build_batch, unique, has_null_key, mode, key_tables,
         bkeys, blive) = self._materialize_build(partition)
        if self.how == "full":
            if partition != 0:
                raise ExecutionError("full outer join has a single partition")
            yield from self._execute_full(table, build_batch, unique,
                                          mode, key_tables, bkeys, blive)
            return
        if self.how == "anti" and self.null_aware and has_null_key:
            # SQL NOT IN with a NULL in the subquery: predicate is never
            # true -> empty result
            if self.probe_chain:
                # raw probe batches carry the SOURCE schema; emit one
                # all-dead batch of the (post-chain) output schema
                from ..columnar import empty_batch

                yield empty_batch(self.output_schema())
                return
            for pb in self.probe.execute(partition):
                yield pb.with_selection(
                    jnp.zeros((pb.capacity,), jnp.bool_)
                )
            return
        from .base import maybe_compact

        if unique:
            for pb in self.probe.execute(partition):
                remaps = self._remaps_for(build_batch, pb)
                # selective joins strand few live rows in huge batches;
                # compacting here shrinks every downstream operator
                yield maybe_compact(self._probe_unique_batch(
                    table, build_batch, pb, mode, key_tables, remaps))
        elif self.how in ("semi", "anti"):
            # membership only: unique probe works regardless of build
            # dups. Selective membership tests (q16's NOT IN keeps ~15%
            # of partsupp) strand few live rows in probe-capacity
            # batches; compacting shrinks every downstream shape, same
            # policy as the unique path above
            for pb in self.probe.execute(partition):
                remaps = self._remaps_for(build_batch, pb)
                yield maybe_compact(self._probe_unique_batch(
                    table, build_batch, pb, mode, key_tables, remaps))
        else:
            yield from self._probe_expand_stream(
                table, build_batch, self.probe.execute(partition), mode,
                key_tables)

    # full outer ------------------------------------------------------------

    def _execute_full(self, table, build_batch, unique, mode, key_tables,
                      bkeys, blive):
        """Probe-preserving (left) pass over every probe partition while
        accumulating which build rows matched, then one extra batch of
        unmatched build rows with null probe columns. The reference's
        DataFrame layer left joins as a TODO entirely
        (rust/client/src/context.rs:287-290)."""
        hit = np.zeros(build_batch.capacity, bool)
        nparts = self.probe.output_partitioning().num_partitions
        for p in range(nparts):
            for pb in self.probe.execute(p):
                remaps = self._remaps_for(build_batch, pb)
                if unique:
                    yield self._probe_unique_batch(table, build_batch, pb,
                                                   mode, key_tables, remaps)
                else:
                    yield from self._probe_expand_batch(
                        table, build_batch, pb, mode, key_tables)
                hit |= np.asarray(self._mark_hits(build_batch, pb, mode,
                                                  key_tables, remaps,
                                                  bkeys, blive))
        # selection, not blive: build rows with NULL join keys can never
        # match but SQL still emits them with null probe columns
        from ..observability import trace_span

        with trace_span("device.block", site="join.unmatched"):
            unmatched = np.asarray(build_batch.selection) & ~hit
        yield self._unmatched_build_batch(build_batch, jnp.asarray(unmatched))

    def _mark_hits(self, build_batch, pb, mode, key_tables, remaps,
                   bkeys, blive):
        """bool [build_cap]: build rows whose key appears among this probe
        batch's live keys (reverse membership probe; duplicates fine).
        NOTE: redoes the probe-key extraction the main pass already did;
        folding a build_rows scatter into the probe jits would halve the
        full-join probe cost if it ever shows up in profiles."""
        def build():
            tw = self.trace_twin()

            def run(pb, key_tables, remaps, bkeys, blive):
                pb = tw._probe_prologue(pb)
                pkeys, plive = tw._probe_keys(pb, mode, key_tables, remaps)
                pt = join_k.build_lookup(pkeys, plive)
                _, matched = join_k.probe_unique(pt, bkeys, blive)
                return jnp.logical_and(blive, matched)

            return run

        fn = self.governed_jit(("join.mark", mode), build)
        return fn(pb, key_tables, remaps, bkeys, blive)

    def _unmatched_build_batch(self, bb: ColumnBatch,
                               unmatched) -> ColumnBatch:
        from ..columnar import Dictionary

        schema = self.output_schema()
        ps = self._probe_out_schema()
        cols = []
        for f in schema.fields:
            if bb.schema.has_field(f.name):
                cols.append(bb.column(f.name))
            else:  # probe-only column: all-NULL
                dt = ps.field(f.name).dtype
                d = Dictionary([]) if dt.kind == "utf8" else None
                cols.append(Column(
                    jnp.zeros((bb.capacity,), dt.device_dtype()), dt,
                    jnp.zeros((bb.capacity,), jnp.bool_), d,
                ))
        return ColumnBatch(schema, cols, unmatched,
                           jnp.sum(unmatched).astype(jnp.int32))

    # fast path: unique build keys ------------------------------------------

    def _probe_col_values(self, pb: ColumnBatch, pcol: str, remap):
        """Probe key column as int64 values + validity; utf8 codes are
        remapped into the BUILD dictionary's code space (codes are
        producer-local; comparing them across tables would be wrong).
        Probe strings absent from the build dictionary map to -1 ->
        invalid (they cannot match anything)."""
        c = pb.column(pcol)
        v = c.values.astype(jnp.int64)
        valid = c.validity
        if remap is not None:
            idx = jnp.clip(v, 0, remap.shape[0] - 1).astype(jnp.int32)
            v2 = jnp.take(remap, idx)
            miss = v2 < 0
            valid = (
                jnp.logical_not(miss) if valid is None
                else jnp.logical_and(valid, jnp.logical_not(miss))
            )
            v = jnp.where(miss, jnp.int64(0), v2)
        return v, valid

    def _probe_keys(self, pb: ColumnBatch, mode: str, key_tables, remaps):
        # mode is static (baked into the jit cache key); key_tables and
        # remaps are traced arguments so per-partition builds / per-source
        # dictionaries don't leak into the cached traces as constants
        pcols = [p for _, p in self.on]
        vals = []
        valid_all = None
        for pcol, remap in zip(pcols, remaps):
            v, valid = self._probe_col_values(pb, pcol, remap)
            vals.append(v)
            if valid is not None:
                valid_all = (
                    valid if valid_all is None
                    else jnp.logical_and(valid_all, valid)
                )
        plive = pb.selection
        if valid_all is not None:
            plive = jnp.logical_and(plive, valid_all)
        if mode == "codec":
            tables, nlive = key_tables
            pkeys, exact = self._codec_probe(vals, tables, nlive)
            return pkeys, jnp.logical_and(plive, exact)
        if mode == "raw":
            return vals[0], plive
        # packed: probe keys outside the packable range cannot equal any
        # (in-range) build key — mask them out instead of aliasing
        a, b = vals
        in_range = jnp.logical_and(
            jnp.abs(a) < (jnp.int64(1) << 31),
            jnp.logical_and(b >= 0, b < (jnp.int64(1) << 32) - 1),
        )
        keys = (a << 32) | (b & jnp.int64(0xFFFFFFFF))
        return keys, jnp.logical_and(plive, in_range)

    def _remaps_for(self, build_batch: ColumnBatch, pb: ColumnBatch):
        """Per key column: probe-code -> build-code remap array (or None
        when no dictionary translation is needed). Host-computed once per
        (key column, probe dictionary), exact via sorted-dict search."""
        out = []
        for bcol, pcol in self.on:
            bd = build_batch.column(bcol).dictionary
            # with a fused probe chain, pb is a RAW source batch: read
            # the key column under its pre-chain name (fusion guarantees
            # probe keys pass through the chain as plain references)
            pd_ = pb.column(self.probe_key_raw.get(pcol, pcol)).dictionary
            if bd is None and pd_ is None:
                out.append(None)
                continue
            if bd is None or pd_ is None:
                raise ExecutionError(
                    f"join key {bcol}={pcol} mixes utf8 and non-utf8 columns"
                )
            if bd is pd_:
                out.append(None)  # shared dictionary: codes comparable
                continue
            # cache holds BOTH dictionaries and is keyed per column
            # (identity-compared on hit): a GC'd dictionary whose address
            # is reused can't pick up a stale remap, a per-partition
            # build dictionary can't reuse another partition's remap, and
            # at most one pair per key column stays pinned
            cached = self._remap_cache.get(bcol)
            if cached is None or cached[0] is not bd or cached[1] is not pd_:
                from ..observability import trace_span
                from .. import columnar_registry

                with trace_span("host.dictionary", site="join.remap",
                                column=bcol, n_build=len(bd),
                                n_probe=len(pd_)):
                    # registry: same-entry pairs compose integer step
                    # remaps; cross-entry pairs build ONE cached sorted
                    # search per (content, content) pair process-wide
                    # (the legacy behavior rebuilt it per join instance
                    # per dictionary pair)
                    remap = columnar_registry.remap_between(pd_, bd)
                    if remap is None:  # identical coding: identity map
                        remap = np.arange(len(pd_), dtype=np.int64) \
                            if len(pd_) else np.full(1, -1, np.int64)
                    cached = (bd, pd_,
                              jnp.asarray(remap.astype(np.int64)))
                self._remap_cache[bcol] = cached
            out.append(cached[2])
        return tuple(out)

    def _probe_unique_batch(self, table, build_batch, pb: ColumnBatch,
                            mode: str, key_tables, remaps) -> ColumnBatch:
        def build():
            tw = self.trace_twin()

            def run(table, bb: ColumnBatch, pb: ColumnBatch,
                    key_tables, remaps) -> ColumnBatch:
                pb = tw._probe_prologue(pb)
                pkeys, plive = tw._probe_keys(pb, mode, key_tables, remaps)
                build_rows, matched = join_k.probe_unique(table, pkeys, plive)
                return tw._assemble(bb, pb, build_rows, matched,
                                    pb.selection, None)

            return run

        fn = self.governed_jit(("join.unique", mode), build)
        return fn(table, build_batch, pb, key_tables, remaps)

    # general path: expanding probe -----------------------------------------

    def _expand_run(self, table, build_batch, pb, mode, key_tables, remaps,
                    out_cap: int):
        """One async expanding-probe launch at a fixed output capacity.
        Returns (out_batch, total_matches_device) WITHOUT syncing."""
        def build():
            tw = self.trace_twin()

            def run(table, bb, pb, key_tables, remaps, _cap=out_cap):
                pb = tw._probe_prologue(pb)
                pkeys, plive = tw._probe_keys(pb, mode, key_tables,
                                              remaps)
                prows, brows, olive, total = join_k.probe_expand(
                    table, pkeys, plive, _cap
                )
                out = tw._assemble_expanded(bb, pb, prows, brows, olive)
                return out, total

            return run

        fn = self.governed_jit(("join.expand", mode, out_cap), build)
        return fn(table, build_batch, pb, key_tables, remaps)

    def _unmatched_batch(self, table, build_batch, pb, mode, key_tables,
                         remaps) -> ColumnBatch:
        """left/full: preserved probe rows with no match, null build
        columns. Pure device work — no sync."""
        def build():
            tw = self.trace_twin()

            def run_unmatched(table, bb, pb, key_tables, remaps):
                pb = tw._probe_prologue(pb)
                pkeys, plive = tw._probe_keys(pb, mode, key_tables,
                                              remaps)
                counts = join_k.probe_counts(table, pkeys)
                unmatched = jnp.logical_and(pb.selection,
                                            jnp.logical_or(
                                                jnp.logical_not(plive),
                                                counts == 0))
                zero = jnp.zeros((pb.capacity,), jnp.int32)
                no_match = jnp.zeros((pb.capacity,), jnp.bool_)
                return tw._assemble(bb, pb, zero, no_match, unmatched,
                                    None)

            return run_unmatched

        fn = self.governed_jit(("join.unmatched", mode), build)
        return fn(table, build_batch, pb, key_tables, remaps)

    def _probe_expand_batch(self, table, build_batch, pb, mode,
                            key_tables) -> Iterator[ColumnBatch]:
        """Single-batch expanding probe (full-outer accumulation needs
        per-batch lockstep with its hit-marking pass)."""
        yield from self._probe_expand_stream(table, build_batch, iter([pb]),
                                             mode, key_tables)

    def _probe_expand_stream(self, table, build_batch, probe_iter,
                             mode: str, key_tables) -> Iterator[ColumnBatch]:
        """Expanding probe over a batch stream with DEFERRED overflow
        syncs: launches are asynchronous and match totals for a whole
        window are fetched in ONE ``device_get`` (each blocking sync
        costs ~80ms when the accelerator sits behind a tunnel — q5's
        per-batch check was the dominant on-chip cost). Only overflowed
        batches re-run; a learned capacity floor makes later windows
        overflow-free."""
        if self.how not in ("inner", "left", "full"):
            raise NotImplementedError_(
                f"{self.how} join with duplicate build keys"
            )
        import os as _os

        from .base import maybe_compact

        window = max(int(_os.environ.get("BALLISTA_JOIN_SYNC_WINDOW", 8)), 1)
        # the window also bounds BYTES pinned on device (probe + expanded
        # output buffers stay live until their totals are fetched), so a
        # wide join with huge batch capacities flushes early instead of
        # multiplying its peak memory by the batch-count window
        window_bytes = int(_os.environ.get(
            "BALLISTA_JOIN_SYNC_WINDOW_BYTES", str(1 << 30)))
        # fixed-size-list columns hold ``length`` elements per row, so
        # itemsize alone would under-count them by length x
        row_bytes = sum(
            f.dtype.device_dtype().itemsize * (getattr(f.dtype, "length", 0)
                                               or 1)
            for f in self.output_schema().fields
        ) + sum(f.dtype.device_dtype().itemsize
                * (getattr(f.dtype, "length", 0) or 1)
                for f in self._probe_out_schema().fields)
        pend: list = []
        pend_bytes = 0

        def flush():
            nonlocal pend_bytes
            pend_bytes = 0
            if not pend:
                return
            from ..observability import trace_span

            with trace_span("device.block", site="join.expand_totals",
                            n=len(pend)):
                totals = jax.device_get([p[-1] for p in pend])  # ONE sync
            for (pb, remaps, out, out_cap, _), total in zip(pend, totals):
                t = int(total)
                while t > out_cap:  # rare: re-run at a ladder capacity
                    self.metrics().add_counter("expand_reruns")
                    out_cap = bucket_capacity(t)
                    out, tot = self._expand_run(
                        table, build_batch, pb, mode, key_tables, remaps,
                        out_cap)
                    t = int(tot)
                    self._expand_cap_floor = max(
                        getattr(self, "_expand_cap_floor", 0), out_cap)
                # the overflow check above already synced the match
                # count, so compaction never costs an extra round-trip
                yield maybe_compact(out, known_rows=min(t, out_cap))
                if self.how in ("left", "full"):
                    yield self._unmatched_batch(table, build_batch, pb,
                                                mode, key_tables, remaps)
            pend.clear()

        for pb in probe_iter:
            remaps = self._remaps_for(build_batch, pb)
            out_cap = max(pb.capacity,
                          getattr(self, "_expand_cap_floor", 0))
            out, total = self._expand_run(table, build_batch, pb, mode,
                                          key_tables, remaps, out_cap)
            pend.append((pb, remaps, out, out_cap, total))
            pend_bytes += (pb.capacity + out_cap) * row_bytes
            if len(pend) >= window or pend_bytes >= window_bytes:
                yield from flush()
        yield from flush()

    # assembly --------------------------------------------------------------

    def _assemble(self, bb, pb, build_rows, matched, probe_sel, _):
        """Probe-aligned output (no expansion). Traced."""
        schema = self.output_schema()
        if self.how == "semi":
            sel = jnp.logical_and(probe_sel, matched)
            return pb.with_selection(sel)
        if self.how == "anti":
            sel = jnp.logical_and(probe_sel, jnp.logical_not(matched))
            if self.null_aware:
                # NULL NOT IN (...) is unknown, not true: drop null keys
                for _, pcol in self.on:
                    v = pb.column(pcol).validity
                    if v is not None:
                        sel = jnp.logical_and(sel, v)
            return pb.with_selection(sel)
        if self.how == "inner":
            sel = jnp.logical_and(probe_sel, matched)
        else:  # left (probe-preserving outer)
            sel = probe_sel
        cols = []
        ps = pb.schema
        for f in schema.fields:
            if ps.has_field(f.name):
                c = pb.column(f.name)
                cols.append(c)
            else:
                c = bb.column(f.name)
                vals = jnp.take(c.values, build_rows)
                validity = jnp.take(c.validity, build_rows) if c.validity is not None \
                    else jnp.ones((pb.capacity,), jnp.bool_)
                validity = jnp.logical_and(validity, matched)
                cols.append(Column(vals, c.dtype, validity, c.dictionary))
        return ColumnBatch(schema, cols, sel, jnp.sum(sel).astype(jnp.int32))

    def _assemble_expanded(self, bb, pb, prows, brows, olive):
        schema = self.output_schema()
        cols = []
        ps = pb.schema
        for f in schema.fields:
            if ps.has_field(f.name):
                c = pb.column(f.name)
                vals = jnp.take(c.values, prows)
                validity = jnp.take(c.validity, prows) if c.validity is not None else None
            else:
                c = bb.column(f.name)
                vals = jnp.take(c.values, brows)
                validity = jnp.take(c.validity, brows) if c.validity is not None else None
            cols.append(Column(vals, c.dtype, validity, c.dictionary))
        return ColumnBatch(
            schema, cols, olive, jnp.sum(olive).astype(jnp.int32)
        )
