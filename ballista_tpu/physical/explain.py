"""EXPLAIN execution: a leaf operator that yields pre-rendered plan text.

The reference serializes DataFusion's EXPLAIN through ExplainNode
(reference: rust/core/proto/ballista.proto:232); here the scheduler/client
renders the plan during physical planning and the result rows travel like
any other single-partition result (so distributed EXPLAIN needs no special
result channel — the text rides the normal shuffle/fetch path).
"""

from __future__ import annotations

from typing import Iterator, List, Tuple

from ..columnar import ColumnBatch
from ..datatypes import Schema
from ..logical import EXPLAIN_SCHEMA
from .base import Partitioning, PhysicalPlan


class ExplainExec(PhysicalPlan):
    """Leaf node holding rendered ``(plan_type, plan)`` rows."""

    def __init__(self, rows: List[Tuple[str, str]]):
        self.rows = [(str(t), str(p)) for t, p in rows]

    def output_schema(self) -> Schema:
        return EXPLAIN_SCHEMA

    def output_partitioning(self) -> Partitioning:
        return Partitioning("unknown", 1)

    def children(self) -> List[PhysicalPlan]:
        return []

    def with_new_children(self, children) -> "ExplainExec":
        return self

    def estimated_rows(self):
        return len(self.rows)

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        from ..io.memory import MemTableSource

        src = MemTableSource.from_pydict(
            EXPLAIN_SCHEMA,
            {"plan_type": [t for t, _ in self.rows],
             "plan": [p for _, p in self.rows]},
        )
        yield from src.scan(0)

    def display(self) -> str:
        return f"ExplainExec rows={len(self.rows)}"


class ExplainAnalyzeExec(PhysicalPlan):
    """EXPLAIN ANALYZE: execute the inner plan, drain its output, and
    yield the plan text annotated with live operator metrics.

    Presents as a LEAF (``children() == []``) on purpose: the
    distributed planner then never splits the inner plan into stages, so
    the whole analyzed query runs as ONE task on one executor and the
    annotated rows ride the existing single-partition result channel —
    the same trick ExplainExec uses for plain EXPLAIN. Metrics are
    force-enabled around the run, so ANALYZE measures even under
    BALLISTA_METRICS=0.
    """

    def __init__(self, inner: PhysicalPlan, verbose: bool = False,
                 logical_text: str | None = None, adaptive_conf=None):
        self.inner = inner
        self.verbose = verbose
        self.logical_text = logical_text
        # standalone adaptive execution config (AdaptiveConfig | None):
        # ANALYZE applies the same rules a plain collect would, so the
        # annotated plan shows the [adaptive: ...] decisions. None (the
        # deserialized cluster-task case) analyzes the static plan.
        self.adaptive_conf = adaptive_conf
        self._adapted = False

    def output_schema(self) -> Schema:
        return EXPLAIN_SCHEMA

    def output_partitioning(self) -> Partitioning:
        return Partitioning("unknown", 1)

    def children(self) -> List[PhysicalPlan]:
        return []  # leaf by design (see docstring)

    def with_new_children(self, children) -> "ExplainAnalyzeExec":
        return self

    def estimated_rows(self):
        return 2

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        import time as _time

        from ..io.memory import MemTableSource
        from ..observability.metrics import (force_metrics,
                                             reset_plan_metrics,
                                             resolve_plan_pending)

        # whole-stage fusion: ANALYZE measures (and renders) the same
        # fused stages a plain collect would run. Applied here rather
        # than at planning so the cluster path — which ships the inner
        # plan over the wire unfused — fuses executor-side too.
        from .fusion import maybe_fuse

        self.inner = maybe_fuse(self.inner)
        # the inner plan may be cached (standalone DataFrames reuse
        # their physical plan across collects): report THIS run only
        reset_plan_metrics(self.inner)
        t0 = _time.perf_counter()
        with force_metrics():
            # parallel ingest: ANALYZE measures the same pipelined
            # execution a plain collect would run (scan instances
            # survive the adaptive rewrite below)
            from ..ingest import cancel_plan, prime_plan

            prime_plan(self.inner)
            try:
                if self.adaptive_conf is not None and \
                        self.adaptive_conf.enabled and not self._adapted:
                    # inside force_metrics: the rewrite materializes
                    # pipeline-breaker inputs, and those executions must
                    # be measured like the rest of the run
                    from ..adaptive.standalone import apply_adaptive_rules
                    from .fusion import fuse_plan, fusion_enabled

                    self.inner = apply_adaptive_rules(self.inner,
                                                      self.adaptive_conf)
                    if fusion_enabled():
                        # re-fuse what the rewrite restructured (same
                        # policy as the plain collect path); mark it so
                        # a re-executed ANALYZE doesn't re-run the full
                        # pass over the demoted shape
                        self.inner = fuse_plan(self.inner,
                                               fuse_joins=False)
                        try:
                            self.inner._fusion_applied = True
                        except AttributeError:
                            pass
                    self._adapted = True
                for p in range(
                        self.inner.output_partitioning().num_partitions):
                    for _ in self.inner.execute(p):
                        pass  # drain: ANALYZE reports metrics, not rows
            finally:
                cancel_plan(self.inner)
        total = _time.perf_counter() - t0
        # one batched device_get for every operator's pending row counts
        # (pretty_metrics would otherwise pay one transfer per operator)
        resolve_plan_pending(self.inner)
        rows: List[Tuple[str, str]] = []
        if self.verbose and self.logical_text is not None:
            rows.append(("logical_plan", self.logical_text))
        rows.append(("plan_with_metrics", self.inner.pretty_metrics()))
        rows.append(("total_elapsed", f"{total:.6f}s"))
        # memory plane summary: process peaks + host bytes by category
        # (operator-level peak_host_bytes/peak_device_bytes gauges ride
        # the plan annotation above)
        from ..observability import memory as obs_memory

        snap = obs_memory.memory_snapshot()
        cats = ", ".join(f"{k}={v}" for k, v in
                         sorted(snap["by_category"].items()) if v)
        rows.append(("memory",
                     f"peak_host_bytes={snap['peak_bytes']}, "
                     f"peak_device_bytes={snap['peak_device_bytes']}, "
                     f"rss_bytes={snap['rss_bytes']}"
                     + (f", host[{cats}]" if cats else "")))
        src = MemTableSource.from_pydict(
            EXPLAIN_SCHEMA,
            {"plan_type": [t for t, _ in rows],
             "plan": [p for _, p in rows]},
        )
        yield from src.scan(0)

    def display(self) -> str:
        return "ExplainAnalyzeExec"


def make_explain_analyze(inner: PhysicalPlan, verbose: bool,
                         logical_text: "str | None",
                         settings: "dict | None") -> ExplainAnalyzeExec:
    """The one place an analyzed plan resolves its AdaptiveConfig —
    the SQL (execution.plan_logical) and direct (physical.planner)
    EXPLAIN ANALYZE paths must not drift apart."""
    from ..adaptive import AdaptiveConfig

    return ExplainAnalyzeExec(
        inner, verbose, logical_text=logical_text,
        adaptive_conf=AdaptiveConfig.from_settings(settings),
    )


def render_explain(logical_input, physical_input: PhysicalPlan,
                   verbose: bool,
                   unoptimized_text: str | None = None,
                   cost_notes: "tuple | None" = None) -> ExplainExec:
    """Build the EXPLAIN result rows from planned inputs.

    Non-verbose mirrors the two-row (logical_plan, physical_plan) surface;
    verbose additionally shows the pre-optimization logical plan when the
    caller captured one. ``cost_notes`` (the control plane's
    cost-feedback decisions for this plan shape) render as one extra
    ``cost_feedback`` row so planning history stays explainable.
    """
    from .fusion import maybe_fuse

    rows: List[Tuple[str, str]] = []
    if verbose and unoptimized_text is not None:
        rows.append(("initial_logical_plan", unoptimized_text))
    rows.append(("logical_plan", logical_input.pretty()))
    # render the FUSED plan — EXPLAIN must show the fusion groups the
    # standalone collect path will actually execute (text-only: the
    # fused operators never serialize)
    rows.append(("physical_plan", maybe_fuse(physical_input).pretty()))
    if cost_notes:
        rows.append(("cost_feedback", "\n".join(cost_notes)))
    return ExplainExec(rows)
