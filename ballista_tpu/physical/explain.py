"""EXPLAIN execution: a leaf operator that yields pre-rendered plan text.

The reference serializes DataFusion's EXPLAIN through ExplainNode
(reference: rust/core/proto/ballista.proto:232); here the scheduler/client
renders the plan during physical planning and the result rows travel like
any other single-partition result (so distributed EXPLAIN needs no special
result channel — the text rides the normal shuffle/fetch path).
"""

from __future__ import annotations

from typing import Iterator, List, Tuple

from ..columnar import ColumnBatch
from ..datatypes import Schema
from ..logical import EXPLAIN_SCHEMA
from .base import Partitioning, PhysicalPlan


class ExplainExec(PhysicalPlan):
    """Leaf node holding rendered ``(plan_type, plan)`` rows."""

    def __init__(self, rows: List[Tuple[str, str]]):
        self.rows = [(str(t), str(p)) for t, p in rows]

    def output_schema(self) -> Schema:
        return EXPLAIN_SCHEMA

    def output_partitioning(self) -> Partitioning:
        return Partitioning("unknown", 1)

    def children(self) -> List[PhysicalPlan]:
        return []

    def with_new_children(self, children) -> "ExplainExec":
        return self

    def estimated_rows(self):
        return len(self.rows)

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        from ..io.memory import MemTableSource

        src = MemTableSource.from_pydict(
            EXPLAIN_SCHEMA,
            {"plan_type": [t for t, _ in self.rows],
             "plan": [p for _, p in self.rows]},
        )
        yield from src.scan(0)

    def display(self) -> str:
        return f"ExplainExec rows={len(self.rows)}"


def render_explain(logical_input, physical_input: PhysicalPlan,
                   verbose: bool,
                   unoptimized_text: str | None = None) -> ExplainExec:
    """Build the EXPLAIN result rows from planned inputs.

    Non-verbose mirrors the two-row (logical_plan, physical_plan) surface;
    verbose additionally shows the pre-optimization logical plan when the
    caller captured one.
    """
    rows: List[Tuple[str, str]] = []
    if verbose and unoptimized_text is not None:
        rows.append(("initial_logical_plan", unoptimized_text))
    rows.append(("logical_plan", logical_input.pretty()))
    rows.append(("physical_plan", physical_input.pretty()))
    return ExplainExec(rows)
