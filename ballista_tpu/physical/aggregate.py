"""Hash-aggregate physical operator (Partial / Final modes).

TPU-native equivalent of the reference's ``HashAggregateExec`` with its
Partial|Final mode enum (reference: rust/core/proto/ballista.proto:370-384;
two-phase split at rust/scheduler/src/planner.rs:149-171). Instead of a CPU
hash table, grouping is sort-based on device (kernels.aggregate); the whole
input pipeline + per-batch partial aggregation trace into one XLA program.

State layout: Partial emits "group columns + state columns" batches
(avg -> sum+count states), Final regroups the concatenated partial tables,
merges states, and finalizes (avg division in scaled int64 -> Decimal(6)).
Group capacity is adaptive: if a pass overflows, it re-runs with the next
power of two >= the true group count (one recompile, known exact).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, ColumnBatch, round_capacity
from ..datatypes import DataType, Decimal, Field, Float64, Int64, Schema
from ..errors import ExecutionError, NotImplementedError_
from .. import expr as ex
from ..kernels.aggregate import (
    AggInput,
    avg_fixed,
    dense_grouped_aggregate,
    dense_grouped_scatter,
    grouped_aggregate,
    scalar_aggregate,
)

# dictionary-coded group keys with product-of-cardinalities at or below
# this use the sort-free dense path
DENSE_GROUP_LIMIT = 256
from ..kernels.expr_eval import Evaluator
from .base import PhysicalPlan, Partitioning, concat_batches

DEFAULT_GROUP_CAPACITY = 1 << 12


def _state_ops(agg: ex.AggregateExpr):
    """[(state_suffix, op)] for one aggregate expr."""
    if agg.fn == "count":
        return [("count", "count")]
    if agg.fn == "sum":
        return [("sum", "sum")]
    if agg.fn == "avg":
        return [("sum", "sum"), ("count", "count")]
    if agg.fn in ("min", "max"):
        return [(agg.fn, agg.fn)]
    raise NotImplementedError_(f"aggregate fn {agg.fn}")


def _state_specs(agg: ex.AggregateExpr, idx: int, in_schema: Schema):
    """Partial mode: [(state_field_name, op, state_dtype)] typed from the
    original input schema."""
    if agg.fn == "count":
        return [(f"__s{idx}_count", "count", Int64)]
    dt = agg.expr.to_field(in_schema).dtype
    if agg.fn in ("sum", "avg"):
        if dt.is_integer:
            sum_t: DataType = Int64
        elif dt.kind == "decimal":
            sum_t = dt
        else:
            sum_t = Float64
        out = [(f"__s{idx}_sum", "sum", sum_t)]
        if agg.fn == "avg":
            out.append((f"__s{idx}_count", "count", Int64))
        return out
    return [(f"__s{idx}_{agg.fn}", agg.fn, dt)]


class HashAggregateExec(PhysicalPlan):
    """mode: 'partial' (per input partition) or 'final' (after merge)."""

    def __init__(
        self,
        mode: str,
        group_exprs: List[ex.Expr],
        agg_exprs: List[ex.Expr],  # AggregateExpr or Alias(AggregateExpr)
        child: PhysicalPlan,
        group_capacity: int = DEFAULT_GROUP_CAPACITY,
    ):
        assert mode in ("partial", "final")
        self.mode = mode
        self.group_exprs = list(group_exprs)
        self.agg_exprs = list(agg_exprs)
        self.child = child
        self.group_capacity = group_capacity
        self._in_schema = child.output_schema()
        self._ev = Evaluator(self._in_schema)
        self._aggs = [
            (e.name(), ex.strip_alias(e)) for e in self.agg_exprs
        ]
        for name, a in self._aggs:
            if not isinstance(a, ex.AggregateExpr):
                raise ExecutionError(f"not an aggregate expression: {name}")
        self._ranged_rejected = False
        # None = unprobed; () = permanently ineligible; else ONE tuple
        # (dict-length fingerprint, layout) — published atomically, so
        # concurrent partition execution (ingest iter_partitions) can
        # never pair one thread's layout with another's fingerprint
        self._mixed_cache = None

    # -- schemas ------------------------------------------------------------

    def group_fields(self) -> List[Field]:
        if self.mode == "partial":
            return [e.to_field(self._in_schema) for e in self.group_exprs]
        # final mode: group columns are already materialized in the input
        return [self._in_schema.field(e.name()) for e in self.group_exprs]

    def state_fields(self) -> List[Tuple[str, str, DataType]]:
        """Flattened (name, op, dtype) of all aggregate states."""
        out = []
        for i, (_, a) in enumerate(self._aggs):
            if self.mode == "partial":
                out.extend(_state_specs(a, i, self._in_schema))
            else:
                # final mode: dtype comes from the partial output schema
                for suffix, op in _state_ops(a):
                    name = f"__s{i}_{suffix}"
                    out.append((name, op, self._in_schema.field(name).dtype))
        return out

    def output_schema(self) -> Schema:
        gf = self.group_fields()
        if self.mode == "partial":
            sf = [Field(n, dt, True) for n, _, dt in self.state_fields()]
            return Schema(gf + sf)
        af = []
        for name, a in self._aggs:
            f = self._agg_output_field(name, a)
            af.append(f)
        return Schema(gf + af)

    def _agg_output_field(self, name: str, a: ex.AggregateExpr) -> Field:
        # final output dtype must match logical Aggregate schema; state
        # dtypes live in the partial schema under __s{i}_* names
        if a.fn == "count":
            return Field(name, Int64, False)
        i = self._agg_index(name)
        if a.fn == "avg":
            sum_f = self._in_schema.field(f"__s{i}_sum")
            if sum_f.dtype.kind == "decimal" or sum_f.dtype.is_integer:
                return Field(name, Decimal(6), True)
            return Field(name, Float64, True)
        if a.fn == "sum":
            return Field(name, self._in_schema.field(f"__s{i}_sum").dtype, True)
        return Field(name, self._in_schema.field(f"__s{i}_{a.fn}").dtype, True)

    def _agg_index(self, name: str) -> int:
        for i, (n, _) in enumerate(self._aggs):
            if n == name:
                return i
        raise ExecutionError(name)

    def output_partitioning(self) -> Partitioning:
        if self.mode == "partial":
            return self.child.output_partitioning()
        # final mode: one output partition per input partition (1 after a
        # merge; N when the partial states were hash-shuffled on the
        # group keys, in which case groups are co-located per partition)
        return Partitioning(
            "unknown", self.child.output_partitioning().num_partitions
        )

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return HashAggregateExec(
            self.mode, self.group_exprs, self.agg_exprs, children[0],
            self.group_capacity,
        )

    def display(self) -> str:
        g = ", ".join(e.name() for e in self.group_exprs)
        a = ", ".join(n for n, _ in self._aggs)
        return f"HashAggregateExec: mode={self.mode} gby=[{g}] aggr=[{a}]"

    def _signature_parts(self) -> tuple:
        from ..compile import fingerprint

        return (self.mode, fingerprint(self.group_exprs),
                fingerprint(self.agg_exprs), self._in_schema)

    # -- execution ----------------------------------------------------------

    def _device_prologue(self, batch: ColumnBatch) -> ColumnBatch:
        """Batch transform applied INSIDE every traced aggregation
        program, before key/input evaluation. Identity here;
        :class:`fusion.FusedStageExec` overrides it with the fused
        pipeline chain (scan→filter→project→partial-agg as ONE XLA
        program). Traced."""
        return batch

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        from ..cache.donation import mark_transient

        batches = list(self.child.execute(partition))
        if not batches:
            return
        batch = concat_batches(self._in_schema, batches)
        if not self.group_exprs:
            out = self._exec_scalar(batch)
        else:
            out = self._exec_grouped(batch)
        # fresh program output, one downstream consumer: donatable
        mark_transient(out)
        yield out

    # grouped ---------------------------------------------------------------

    def _agg_inputs_partial(self, batch: ColumnBatch) -> List[AggInput]:
        aggs: List[AggInput] = []
        for i, (_, a) in enumerate(self._aggs):
            specs = _state_specs(a, i, self._in_schema)
            for (_, op, dt) in specs:
                if op == "count":
                    if a.is_star or a.fn == "avg" and a.expr is None:
                        aggs.append(AggInput("count", None, None))
                    else:
                        r = self._ev.evaluate(a.expr, batch)
                        aggs.append(AggInput("count", None, r.validity))
                else:
                    r = self._ev.evaluate(a.expr, batch)
                    v = jnp.broadcast_to(r.values, (batch.capacity,))
                    v = self._to_state_dtype(v, r.dtype, dt)
                    aggs.append(AggInput(op, v, r.validity))
        return aggs

    def _agg_inputs_final(self, batch: ColumnBatch) -> List[AggInput]:
        aggs: List[AggInput] = []
        for name, op, dt in self.state_fields():
            col = batch.column(name)
            # merging states: counts and sums add up; min/min, max/max
            merge_op = "sum" if op in ("count", "sum") else op
            aggs.append(AggInput(merge_op, col.values, col.validity))
        return aggs

    def _to_state_dtype(self, v, src: DataType, dst: DataType):
        if dst.kind == "decimal" or dst.is_integer:
            return v.astype(jnp.int64)
        return v.astype(jnp.float32)

    def _run_grouping(self, batch: ColumnBatch, key_evals, aggs, cap):
        """Pick dense (sort-free) or sort-based grouping. Traced."""
        cards = []
        for r in key_evals:
            if r.dictionary is not None:
                cards.append(len(r.dictionary))
            elif r.dtype.kind == "boolean":
                cards.append(2)
            else:
                cards = None
                break
        if cards is not None:
            g_total = 1
            for r, card in zip(key_evals, cards):
                g_total *= card + (1 if r.validity is not None else 0)
            if 0 < g_total <= min(DENSE_GROUP_LIMIT, cap):
                gid = jnp.zeros((batch.capacity,), jnp.int32)
                for r, card in zip(key_evals, cards):
                    slots = card + (1 if r.validity is not None else 0)
                    code = jnp.broadcast_to(
                        r.values.astype(jnp.int32), (batch.capacity,)
                    )
                    if r.validity is not None:
                        # NULL keys take the extra slot per key column
                        code = jnp.where(r.validity, code, card)
                    gid = gid * slots + code
                return dense_grouped_aggregate(gid, batch.selection, aggs,
                                               g_total)
        keys = [
            jnp.broadcast_to(r.values, (batch.capacity,)) for r in key_evals
        ]
        key_validities = [r.validity for r in key_evals]
        return grouped_aggregate(keys, batch.selection, aggs, cap,
                                 key_validities)

    def _static_group_bound(self, batch: ColumnBatch) -> Optional[int]:
        """Host-side upper bound on the group count when every group key
        is a plain column with known cardinality (dictionary/boolean) —
        mirrors the dense-path condition in ``_run_grouping``. Lets
        ``_exec_grouped`` skip the overflow-check device sync entirely:
        a blocking device->host read costs a full round-trip when the
        accelerator is remote."""
        g = 1
        for e in self.group_exprs:
            if self.mode == "partial":
                base = ex.strip_alias(e)
                if not isinstance(base, ex.ColumnRef):
                    return None
                name = base.column
            else:
                name = e.name()
            try:
                col = batch.column(name)
            except Exception:  # noqa: BLE001 - unknown column: no bound
                return None
            if col.dictionary is not None:
                card = len(col.dictionary)
            elif col.dtype.kind == "boolean":
                card = 2
            else:
                return None
            g *= card + (1 if col.validity is not None else 0)
        return g if g > 0 else None

    # Ranged/mixed dense grouping: when every group key is either
    # dictionary-coded (static cardinality) or integer-valued with a
    # live range fitting below these bounds, rows aggregate by O(N)
    # scatter into a mixed-radix [G] table — no sort, no overflow retry.
    # The range cap bounds table memory; the live-rows factor keeps
    # pathological sparse keys (hash-like ids) on the sort path. 16x
    # measured neutral-or-better across TPC-H vs the original 4x (the
    # scatter table is cheap up to the absolute cap; q16's 3-key final
    # agg was falling to the sort path at 11x rows).
    _RANGED_DENSE_LIMIT = 1 << 23
    _RANGED_CAP_FACTOR = 16
    _RANGED_KINDS = ("int32", "int64", "decimal", "date32", "timestamp_ns")

    def _mixed_layout(self, batch: ColumnBatch):
        """Per group key: ("dict", slots) for dictionary/boolean keys or
        ("int", None) for integer-valued keys (incl. expressions, e.g.
        EXTRACT(YEAR ...)); None when any key is neither. Classified by
        TRACING the evaluator (jax.eval_shape — no compute). Kind
        classification is stable for the operator's lifetime, but dict
        SPANS are not: different partitions' batches carry different
        dictionaries, and a span cached from a smaller dictionary would
        overflow its mixed-radix digit and collide groups. The cache is
        therefore keyed on the batch's dictionary lengths and re-probed
        when they change."""
        cached = self._mixed_cache  # one read: (fp, layout) or ()/None
        if cached == ():  # dtype kinds never change: permanent
            return None
        fp = tuple(
            len(c.dictionary) if c.dictionary is not None else -1
            for c in batch.columns
        )
        if cached is not None and cached[0] == fp:
            return cached[1]
        meta: List = []

        def probe(b):
            b = self._device_prologue(b)
            kes, _ = self._inputs_and_keys(b)
            for r in kes:
                meta.append((r.dtype, r.dictionary))
            return [r.values for r in kes]

        try:
            jax.eval_shape(probe, batch)
        except Exception:  # noqa: BLE001 - untraceable: not eligible
            self._mixed_cache = ()
            return None
        layout = []
        for dt, d in meta:
            if d is not None:
                layout.append(("dict", len(d) + 1))  # +1 NULL/code-0 slot
            elif dt.kind == "boolean":
                layout.append(("dict", 3))
            elif dt.kind in self._RANGED_KINDS:
                layout.append(("int", None))
            else:
                self._mixed_cache = ()
                return None
        self._mixed_cache = (fp, layout)  # atomic pair publication
        return layout

    def _mixed_stats(self, batch: ColumnBatch, layout):
        """(per-int-key (min, max) list, nlive): one jitted program,
        scalars only across the link."""

        def build():
            tw = self.trace_twin()

            def stats(b):
                b = tw._device_prologue(b)
                kes, _ = tw._inputs_and_keys(b)
                maxi = jnp.iinfo(jnp.int64).max
                mm = []
                for (kind, _), r in zip(layout, kes):
                    if kind != "int":
                        continue
                    v = jnp.broadcast_to(r.values, (b.capacity,)) \
                        .astype(jnp.int64)
                    live = b.selection
                    if r.validity is not None:
                        live = jnp.logical_and(live, r.validity)
                    mm.append((jnp.min(jnp.where(live, v, maxi)),
                               jnp.max(jnp.where(live, v, -maxi))))
                return mm, jnp.sum(b.selection.astype(jnp.int32))

            return stats

        fn = self.governed_jit(("agg.mstats", tuple(layout)), build)
        from ..observability import trace_span

        # launch OUTSIDE the span: a cold call compiles synchronously
        # and the governor already attributes that to the compile lane —
        # only the blocking fetch is device-blocked time
        res = fn(batch)
        with trace_span("device.block", site="agg.mstats"):
            mm, nlive = jax.device_get(res)
        return [(int(lo), int(hi)) for lo, hi in mm], int(nlive)

    def _exec_grouped(self, batch: ColumnBatch) -> ColumnBatch:
        cap = self.group_capacity
        bound = self._static_group_bound(batch)
        if bound is not None and bound <= min(DENSE_GROUP_LIMIT, cap):
            # one call, no overflow retry: safe to donate the batch
            out, _ng = self.governed_call(("agg.grouped", cap),
                                          self._grouped_build(cap), batch)
            return out  # dense path, can't overflow: no sync needed
        # rejected once (hash-like sparse ids / huge products) -> rejected
        # for the operator's lifetime: don't pay the stats round-trip again
        layout = None if self._ranged_rejected else self._mixed_layout(batch)
        if layout is not None:
            mm, nlive = self._mixed_stats(batch, layout)
            if any(lo > hi for lo, hi in mm):
                pass  # no live rows: sort path handles the empty batch
            else:
                spans, bases = [], []
                true_total = 1  # product of UNQUANTIZED spans
                it = iter(mm)
                for kind, slots in layout:
                    if kind == "dict":
                        spans.append(slots)
                        true_total *= slots
                    else:
                        lo, hi = next(it)
                        # +1 NULL slot; quantized so successive batches
                        # with similar ranges reuse one compiled program
                        spans.append(round_capacity(hi - lo + 2))
                        bases.append(lo)
                        true_total *= hi - lo + 2
                g_total = 1
                for s in spans:
                    g_total *= s
                # admission gates on LIVE rows (not capacity): sparse
                # post-filter batches must not allocate huge group tables.
                # The rows-proportional test uses the TRUE span product —
                # quantization (up to 2x per int key) is a compile-reuse
                # artifact, not a cost the data asked for; a 1.5M-group
                # final agg over a 6M-wide key must not lose the O(N)
                # path because 6M rounds to 8.4M (q18's HAVING subquery:
                # 3.7s sort -> 0.2s scatter). The quantized table still
                # has to fit the absolute limit.
                if (true_total <= self._RANGED_CAP_FACTOR * (nlive + 256)
                        and g_total <= self._RANGED_DENSE_LIMIT):
                    # final call on this batch (_mixed_stats's read has
                    # fully completed — device_get blocks): donatable
                    out, _ng = self.governed_call(
                        ("agg.mixed", tuple(spans), tuple(layout)),
                        self._mixed_build(tuple(spans), layout),
                        batch, jnp.asarray(bases, jnp.int64))
                    return out  # gid < G by construction: no overflow sync
                self._ranged_rejected = True
        # overflow-retry loop re-reads the SAME batch after an
        # undersized attempt — never donate here
        while True:
            fn = self._get_grouped_fn(cap, batch.capacity)
            out, num_groups = fn(batch)
            ng = int(num_groups)
            if ng <= cap:
                # persist the learned capacity: the operator instance is
                # reused across partitions AND collects (plan cache), so
                # later runs skip the undersized attempt + retry sync
                self.group_capacity = max(self.group_capacity, cap)
                return out
            cap = round_capacity(ng)

    def _inputs_and_keys(self, batch: ColumnBatch):
        """(key_evals, aggs) for the current mode. Traced."""
        if self.mode == "partial":
            key_evals = [self._ev.evaluate(e, batch) for e in self.group_exprs]
            aggs = self._agg_inputs_partial(batch)
        else:
            key_evals = [
                self._ev.evaluate(ex.ColumnRef(e.name()), batch)
                for e in self.group_exprs
            ]
            aggs = self._agg_inputs_final(batch)
        return key_evals, aggs

    def _assemble(self, batch: ColumnBatch, key_evals, res, cap: int):
        """GroupedResult -> output ColumnBatch. Traced."""
        out_cols: List[Column] = []
        gf = self.group_fields()
        for f, r in zip(gf, key_evals):
            vals = jnp.take(
                jnp.broadcast_to(r.values, (batch.capacity,)),
                res.rep_indices,
            )
            validity = (
                jnp.take(r.validity, res.rep_indices)
                if r.validity is not None
                else None
            )
            out_cols.append(Column(vals, f.dtype, validity, r.dictionary))
        if self.mode == "partial":
            for (name, op, dt), arr, va in zip(
                self.state_fields(), res.aggregates, res.agg_valid
            ):
                out_cols.append(Column(arr, dt, va, None))
        else:
            out_cols.extend(self._finalize(res))
        return ColumnBatch(
            self.output_schema(), out_cols, res.group_valid,
            jnp.minimum(res.num_groups, cap),
        )

    def _grouped_build(self, cap: int):
        def build():
            tw = self.trace_twin()  # don't pin the input subtree

            def run(batch: ColumnBatch):
                batch = tw._device_prologue(batch)
                key_evals, aggs = tw._inputs_and_keys(batch)
                res = tw._run_grouping(batch, key_evals, aggs, cap)
                return tw._assemble(batch, key_evals, res, cap), \
                    res.num_groups

            return run

        return build

    def _get_grouped_fn(self, cap: int, in_cap: int):
        # in_cap rides the traced batch shape; only the static group
        # capacity needs to be in the key
        return self.governed_jit(("agg.grouped", cap),
                                 self._grouped_build(cap))

    def _get_mixed_fn(self, spans, in_cap: int, layout):
        """Grouping program for mixed dict/ranged-int keys: mixed-radix
        gid over per-key slots (slot 0 of each radix = NULL), O(N)
        scatter aggregation, no sort and no overflow. Integer-key bases
        are a traced argument so consecutive batches with different
        ranges but the same quantized spans reuse one compiled
        program."""
        return self.governed_jit(("agg.mixed", spans, tuple(layout)),
                                 self._mixed_build(spans, layout))

    def _mixed_build(self, spans, layout):
        def build():
            tw = self.trace_twin()
            g_total = 1
            for s in spans:
                g_total *= s
            # pad the table so the output batch capacity is a power of
            # two (downstream jit caches key on capacity); gids stay
            # below the exact strides product
            G = round_capacity(g_total)

            def run(batch: ColumnBatch, bases):
                batch = tw._device_prologue(batch)
                key_evals, aggs = tw._inputs_and_keys(batch)
                gid = jnp.zeros((batch.capacity,), jnp.int64)
                bi = 0
                for (kind, _), span, r in zip(layout, spans, key_evals):
                    v = jnp.broadcast_to(r.values, (batch.capacity,))
                    if kind == "dict":
                        c = v.astype(jnp.int64) + 1
                    else:
                        c = v.astype(jnp.int64) - bases[bi] + 1
                        bi += 1
                    if r.validity is not None:
                        c = jnp.where(r.validity, c, 0)
                    gid = gid * span + c
                res = dense_grouped_scatter(gid.astype(jnp.int32),
                                            batch.selection, aggs, G)
                return tw._assemble(batch, key_evals, res, G), \
                    res.num_groups

            return run

        return build

    def _finalize(self, res) -> List[Column]:
        """final mode: merge states -> output aggregate columns."""
        cols: List[Column] = []
        state_arrays = res.aggregates
        si = 0
        for i, (name, a) in enumerate(self._aggs):
            ops = _state_ops(a)
            n_states = len(ops)
            arrs = state_arrays[si : si + n_states]
            dts = [
                self._in_schema.field(f"__s{i}_{suffix}").dtype
                for suffix, _ in ops
            ]
            si += n_states
            valids = res.agg_valid[si - n_states : si]
            out_f = self._agg_output_field(name, a)
            if a.fn == "count":
                cols.append(Column(arrs[0], Int64, None, None))
            elif a.fn == "avg":
                s, c = arrs[0], arrs[1]
                sum_dt = dts[0]
                if sum_dt.kind == "decimal" or sum_dt.is_integer:
                    scale = sum_dt.scale if sum_dt.kind == "decimal" else 0
                    val = avg_fixed(s, c, scale)
                    cols.append(Column(val, Decimal(6), c > 0, None))
                else:
                    val = s.astype(jnp.float32) / jnp.maximum(c, 1).astype(jnp.float32)
                    cols.append(Column(val, Float64, c > 0, None))
            else:  # sum/min/max: NULL when no valid input was seen
                cols.append(Column(arrs[0], out_f.dtype, valids[0], None))
        return cols

    # ungrouped -------------------------------------------------------------

    def _scalar_build(self):
        def build():
            tw = self.trace_twin()

            def run(b: ColumnBatch):
                b = tw._device_prologue(b)
                if tw.mode == "partial":
                    aggs = tw._agg_inputs_partial(b)
                else:
                    aggs = tw._agg_inputs_final(b)
                return scalar_aggregate(b.selection, aggs)

            return run

        return build

    def _get_scalar_fn(self):
        return self.governed_jit(("agg.scalar",), self._scalar_build())

    def _exec_scalar(self, batch: ColumnBatch) -> ColumnBatch:
        # single call, batch never touched again: donate when transient
        vals, valids = self.governed_call(("agg.scalar",),
                                          self._scalar_build(), batch)

        cap = 8
        sel = np.zeros(cap, dtype=bool)
        sel[0] = True

        def expand(v, valid, dt):
            arr = jnp.zeros((cap,), dt.device_dtype()).at[0].set(
                v.astype(dt.device_dtype())
            )
            validity = (
                jnp.zeros((cap,), jnp.bool_).at[0].set(valid)
                if valid is not None
                else None
            )
            return arr, validity

        cols: List[Column] = []
        if self.mode == "partial":
            schema = self.output_schema()
            for (name, op, dt), v, va in zip(self.state_fields(), vals, valids):
                arr, validity = expand(v, va, dt)
                cols.append(Column(arr, dt, validity, None))
        else:
            schema = self.output_schema()
            si = 0
            for i, (name, a) in enumerate(self._aggs):
                ops = _state_ops(a)
                arrs = vals[si : si + len(ops)]
                vas = valids[si : si + len(ops)]
                dts = [
                    self._in_schema.field(f"__s{i}_{suffix}").dtype
                    for suffix, _ in ops
                ]
                si += len(ops)
                out_f = self._agg_output_field(name, a)
                if a.fn == "avg":
                    s, c = arrs[0], arrs[1]
                    sum_dt = dts[0]
                    if sum_dt.kind == "decimal" or sum_dt.is_integer:
                        scale = sum_dt.scale if sum_dt.kind == "decimal" else 0
                        v = avg_fixed(s, c, scale)
                    else:
                        v = s.astype(jnp.float32) / jnp.maximum(c, 1).astype(
                            jnp.float32
                        )
                    arr, validity = expand(v, c > 0, out_f.dtype)
                    cols.append(Column(arr, out_f.dtype, validity, None))
                elif a.fn == "count":
                    arr, _ = expand(arrs[0], None, out_f.dtype)
                    cols.append(Column(arr, out_f.dtype, None, None))
                else:  # sum/min/max: NULL when no valid input
                    arr, validity = expand(arrs[0], vas[0], out_f.dtype)
                    cols.append(Column(arr, out_f.dtype, validity, None))
        return ColumnBatch(
            schema, cols, jnp.asarray(sel), jnp.asarray(np.int32(1))
        )
