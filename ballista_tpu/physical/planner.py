"""Physical planner: logical plan -> physical operator tree.

The reference gets this from DataFusion's ``create_physical_plan``
(reference: rust/scheduler/src/lib.rs:317-331). Ours maps each logical node
to the TPU operators in this package, inserting the Partial->Merge->Final
aggregate split and probe/build side selection for joins.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from ..errors import NotImplementedError_, PlanError
from .. import expr as ex
from ..logical import (
    Aggregate,
    EmptyRelation,
    Explain,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Projection,
    Repartition,
    Sort,
    TableScan,
)
from .aggregate import HashAggregateExec
from .base import PhysicalPlan
from .join import JoinExec
from .operators import (
    EmptyExec,
    FilterExec,
    LimitExec,
    MergeExec,
    ProjectionExec,
    RepartitionExec,
    ScanExec,
    SortExec,
)


@dataclass
class PlannerOptions:
    """Physical planning knobs (client ``settings`` map them by key).

    ``join_partition_threshold``: estimated build-side row count above
    which both join inputs are hash-shuffled on the join keys and the join
    runs co-partitioned (partition p joins build[p] x probe[p]) instead of
    merging the whole build side to every task. None disables.
    ``join_partitions``: partition count for such shuffled joins.
    """

    join_partition_threshold: Optional[int] = 4_000_000
    join_partitions: int = 8
    # hash-shuffled aggregation: partial -> Repartition(hash on group
    # keys) -> final, instead of merging all partial tables to one task.
    # None keeps the merge plan; N produces an N-partition final stage
    # (the shape the mesh ICI fast path fuses — see distributed/scheduler)
    agg_partitions: Optional[int] = None

    @staticmethod
    def from_settings(settings: Optional[Dict[str, str]]) -> "PlannerOptions":
        opts = PlannerOptions()
        s = settings or {}
        if "join.partitioned.threshold" in s:
            v = s["join.partitioned.threshold"]
            opts.join_partition_threshold = (
                None if v in ("", "off", "none") else int(v)
            )
        if "join.partitions" in s:
            opts.join_partitions = int(s["join.partitions"])
        if "agg.partitions" in s:
            v = s["agg.partitions"]
            opts.agg_partitions = None if v in ("", "off", "none") else int(v)
        return opts


def create_physical_plan(
    plan: LogicalPlan, options: Optional[PlannerOptions] = None
) -> PhysicalPlan:
    return _create(plan, options or PlannerOptions())


def _create(plan: LogicalPlan, opts: PlannerOptions) -> PhysicalPlan:
    def create_physical_plan(p):  # threads opts through the recursion
        return _create(p, opts)

    if isinstance(plan, TableScan):
        return ScanExec(plan.table_name, plan.source, plan.projection)

    if isinstance(plan, Projection):
        return ProjectionExec(plan.exprs, create_physical_plan(plan.input))

    if isinstance(plan, Filter):
        return FilterExec(plan.predicate, create_physical_plan(plan.input))

    if isinstance(plan, Aggregate):
        child = create_physical_plan(plan.input)
        partial = HashAggregateExec("partial", plan.group_exprs, plan.agg_exprs, child)
        if opts.agg_partitions and plan.group_exprs:
            # shuffled aggregation: co-locate groups by hashing the
            # materialized group columns, final-aggregate per partition
            shuffled = RepartitionExec(
                partial, opts.agg_partitions,
                [ex.ColumnRef(e.name()) for e in plan.group_exprs],
            )
            return HashAggregateExec("final", plan.group_exprs,
                                     plan.agg_exprs, shuffled)
        merged: PhysicalPlan = partial
        if partial.output_partitioning().num_partitions > 1:
            merged = MergeExec(partial)
        return HashAggregateExec("final", plan.group_exprs, plan.agg_exprs, merged)

    if isinstance(plan, Sort):
        child = create_physical_plan(plan.input)
        if child.output_partitioning().num_partitions > 1:
            child = MergeExec(child)
        return SortExec(plan.sort_exprs, child)

    if isinstance(plan, Limit):
        child = create_physical_plan(plan.input)
        if child.output_partitioning().num_partitions > 1:
            child = MergeExec(child)
        return LimitExec(plan.n, child)

    if isinstance(plan, Repartition):
        return RepartitionExec(
            create_physical_plan(plan.input), plan.num_partitions, plan.hash_exprs
        )

    if isinstance(plan, Join):
        left = create_physical_plan(plan.left)
        right = create_physical_plan(plan.right)
        # Probe side = the row-preserving side; build side is merged to one
        # partition and sorted (see JoinExec docstring).
        if plan.how == "inner":
            build, probe, how = left, right, "inner"
            on = list(plan.on)
            # inner is symmetric and the projection below restores column
            # order, so build on the smaller estimated side: the build is
            # merged/sorted/tabled in full, and a small unique build side
            # keeps probes on the cheap non-expanding path. Skip the swap
            # when the sides share column names: JoinExec resolves name
            # collisions in favor of the build side, so swapping would
            # change which side's values a collided name refers to.
            le, re_ = left.estimated_rows(), right.estimated_rows()
            collide = (set(left.output_schema().names())
                       & set(right.output_schema().names()))
            if (not collide and le is not None and re_ is not None
                    and re_ < le):
                build, probe = right, left
                on = [(r, l) for l, r in plan.on]
        elif plan.how == "left":
            build, probe, how = right, left, "left"
            on = [(r, l) for l, r in plan.on]
        elif plan.how == "right":
            build, probe, how = left, right, "left"
            on = list(plan.on)
        elif plan.how == "full":
            # build = right, probe = left; JoinExec streams every probe
            # partition itself and appends the unmatched build rows
            build, probe, how = right, left, "full"
            on = [(r, l) for l, r in plan.on]
        elif plan.how in ("semi", "anti"):
            build, probe, how = right, left, plan.how
            on = [(r, l) for l, r in plan.on]
        else:
            raise NotImplementedError_(f"join type {plan.how}")
        threshold = opts.join_partition_threshold
        # null-aware anti joins (NOT IN) must see the WHOLE build side:
        # one NULL subquery value empties every partition's result, so a
        # per-bucket build would miss nulls that hashed elsewhere
        partitionable = (not plan.null_aware and threshold is not None
                         and how != "full")
        est = build.estimated_rows() if partitionable else None
        if partitionable and est is not None and est > threshold:
            # co-partitioned join: hash-shuffle BOTH sides on the join keys
            # with the same partition count, so each task joins one bucket
            # and no task ever holds the whole build side. (The reference
            # passes join children through unsplit: planner.rs:172-173.)
            n = opts.join_partitions
            build = RepartitionExec(
                build, n, [ex.ColumnRef(b) for b, _ in on]
            )
            probe = RepartitionExec(
                probe, n, [ex.ColumnRef(p) for _, p in on]
            )
            joined: PhysicalPlan = JoinExec(build, probe, on, how,
                                            null_aware=plan.null_aware,
                                            partitioned=True)
        else:
            if build.output_partitioning().num_partitions > 1:
                build = MergeExec(build)
            joined = JoinExec(build, probe, on, how,
                              null_aware=plan.null_aware)
        # restore logical column order if the physical (build-first) order
        # differs (e.g. preserved-left joins probe the left side)
        want = plan.schema().names()
        got = joined.output_schema().names()
        if want != got:
            joined = ProjectionExec([ex.ColumnRef(n) for n in want], joined)
        return joined

    if isinstance(plan, EmptyRelation):
        return EmptyExec(plan.produce_one_row)

    if isinstance(plan, Explain):
        raise PlanError("Explain handled by the client layer")

    raise NotImplementedError_(f"no physical plan for {type(plan).__name__}")
