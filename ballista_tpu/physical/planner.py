"""Physical planner: logical plan -> physical operator tree.

The reference gets this from DataFusion's ``create_physical_plan``
(reference: rust/scheduler/src/lib.rs:317-331). Ours maps each logical node
to the TPU operators in this package, inserting the Partial->Merge->Final
aggregate split and probe/build side selection for joins.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional

from ..errors import NotImplementedError_, PlanError
from .. import expr as ex
from ..logical import (
    Aggregate,
    EmptyRelation,
    Explain,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Projection,
    Repartition,
    Sort,
    TableScan,
)
from .aggregate import HashAggregateExec
from .base import PhysicalPlan
from .join import JoinExec
from .operators import (
    EmptyExec,
    FilterExec,
    LimitExec,
    MergeExec,
    ProjectionExec,
    RepartitionExec,
    ScanExec,
    SortExec,
)


@dataclass
class PlannerOptions:
    """Physical planning knobs (client ``settings`` map them by key).

    ``join_partition_threshold``: estimated build-side row count above
    which both join inputs are hash-shuffled on the join keys and the join
    runs co-partitioned (partition p joins build[p] x probe[p]) instead of
    merging the whole build side to every task. None disables.
    ``join_partitions``: partition count for such shuffled joins.
    """

    # build side is the SMALLER estimated side for merged inner joins
    # (they swap), so this gates on the min side: above it,
    # co-partitioned buckets beat a merged build, whose concat+table
    # rebuild repeats per query run
    join_partition_threshold: Optional[int] = 1_000_000
    join_partitions: int = 8
    # cost-based inner-join orientation (see the swap block below);
    # settings key "join.swap", env BALLISTA_JOIN_SWAP as default source
    join_swap: bool = True
    # hash-shuffled aggregation: partial -> Repartition(hash on group
    # keys) -> final, instead of merging all partial tables to one task.
    # None keeps the merge plan; N produces an N-partition final stage
    # (the shape the mesh ICI fast path fuses — see distributed/scheduler)
    agg_partitions: Optional[int] = None
    # raw settings snapshot: EXPLAIN ANALYZE resolves its AdaptiveConfig
    # from here so analyzed plans run (and annotate) the same adaptive
    # rules a plain collect would
    adaptive_settings: Optional[Dict[str, str]] = None
    # cost-feedback decisions applied to these options (set by
    # controlplane.costs.advise); EXPLAIN renders them as a
    # cost_feedback row so history-informed plans stay explainable
    cost_notes: tuple = ()

    @staticmethod
    def from_settings(settings: Optional[Dict[str, str]]) -> "PlannerOptions":
        opts = PlannerOptions()
        s = settings or {}
        opts.adaptive_settings = dict(s)
        if "join.partitioned.threshold" in s:
            v = s["join.partitioned.threshold"]
            opts.join_partition_threshold = (
                None if v in ("", "off", "none") else int(v)
            )
        if "join.partitions" in s:
            opts.join_partitions = int(s["join.partitions"])
        swap = s.get("join.swap",
                     os.environ.get("BALLISTA_JOIN_SWAP", "on")).lower()
        if swap in ("off", "0", "false"):
            opts.join_swap = False
        elif swap not in ("on", "1", "true", ""):
            import logging

            logging.getLogger("ballista.planner").warning(
                "unrecognized join.swap value %r; keeping swap ON", swap)
        if "agg.partitions" in s:
            v = s["agg.partitions"]
            opts.agg_partitions = None if v in ("", "off", "none") else int(v)
        return opts


def create_physical_plan(
    plan: LogicalPlan, options: Optional[PlannerOptions] = None
) -> PhysicalPlan:
    return _create(plan, options or PlannerOptions())


def _create(plan: LogicalPlan, opts: PlannerOptions) -> PhysicalPlan:
    def create_physical_plan(p):  # threads opts through the recursion
        return _create(p, opts)

    if isinstance(plan, TableScan):
        return ScanExec(plan.table_name, plan.source, plan.projection)

    if isinstance(plan, Projection):
        return ProjectionExec(plan.exprs, create_physical_plan(plan.input))

    if isinstance(plan, Filter):
        return FilterExec(plan.predicate, create_physical_plan(plan.input))

    if isinstance(plan, Aggregate):
        child = create_physical_plan(plan.input)
        partial = HashAggregateExec("partial", plan.group_exprs, plan.agg_exprs, child)
        if opts.agg_partitions and plan.group_exprs:
            # shuffled aggregation: co-locate groups by hashing the
            # materialized group columns, final-aggregate per partition
            shuffled = RepartitionExec(
                partial, opts.agg_partitions,
                [ex.ColumnRef(e.name()) for e in plan.group_exprs],
            )
            return HashAggregateExec("final", plan.group_exprs,
                                     plan.agg_exprs, shuffled)
        merged: PhysicalPlan = partial
        if partial.output_partitioning().num_partitions > 1:
            merged = MergeExec(partial)
        return HashAggregateExec("final", plan.group_exprs, plan.agg_exprs, merged)

    if isinstance(plan, Sort):
        child = create_physical_plan(plan.input)
        if child.output_partitioning().num_partitions > 1:
            child = MergeExec(child)
        return SortExec(plan.sort_exprs, child)

    if isinstance(plan, Limit):
        child = create_physical_plan(plan.input)
        if child.output_partitioning().num_partitions > 1:
            child = MergeExec(child)
        return LimitExec(plan.n, child)

    if isinstance(plan, Repartition):
        return RepartitionExec(
            create_physical_plan(plan.input), plan.num_partitions, plan.hash_exprs
        )

    if isinstance(plan, Join):
        left = create_physical_plan(plan.left)
        right = create_physical_plan(plan.right)
        # Probe side = the row-preserving side; build side is merged to one
        # partition and sorted (see JoinExec docstring).
        if plan.how == "inner":
            build, probe, how = left, right, "inner"
            on = list(plan.on)
        elif plan.how == "left":
            build, probe, how = right, left, "left"
            on = [(r, l) for l, r in plan.on]
        elif plan.how == "right":
            build, probe, how = left, right, "left"
            on = list(plan.on)
        elif plan.how == "full":
            # build = right, probe = left; JoinExec streams every probe
            # partition itself and appends the unmatched build rows
            build, probe, how = right, left, "full"
            on = [(r, l) for l, r in plan.on]
        elif plan.how in ("semi", "anti"):
            build, probe, how = right, left, plan.how
            on = [(r, l) for l, r in plan.on]
        else:
            raise NotImplementedError_(f"join type {plan.how}")
        threshold = opts.join_partition_threshold
        # null-aware anti joins (NOT IN) must see the WHOLE build side:
        # one NULL subquery value empties every partition's result, so a
        # per-bucket build would miss nulls that hashed elsewhere
        partitionable = (not plan.null_aware and threshold is not None
                         and how != "full")
        # Inner joins are symmetric and the projection below restores
        # column order, so orient by cost (measured on TPC-H, see
        # benchmarks/RESULTS.md). Co-partitioned mode: build the LARGER
        # side — output capacities ride the probe side, so probing the
        # small side keeps every downstream shape small. Merged mode:
        # build the SMALLER side — the build is concatenated and tabled
        # whole, and a small unique build keeps probes off the expanding
        # path. Skipped when the sides share column names (JoinExec
        # resolves collisions build-first, so a swap would change which
        # side a collided name refers to) or estimates are unknown.
        if plan.how == "inner" and opts.join_swap:
            le, re_ = build.estimated_rows(), probe.estimated_rows()
            collide = (set(build.output_schema().names())
                       & set(probe.output_schema().names()))
            if not collide and le is not None and re_ is not None:
                goes_partitioned = (partitionable
                                    and min(le, re_) > threshold)
                want_larger_build = goes_partitioned
                if (re_ > le) == want_larger_build and re_ != le:
                    build, probe = probe, build
                    on = [(p, b) for b, p in on]
        est = build.estimated_rows() if partitionable else None
        if partitionable and est is not None and est > threshold:
            # co-partitioned join: hash-shuffle BOTH sides on the join keys
            # with the same partition count, so each task joins one bucket
            # and no task ever holds the whole build side. (The reference
            # passes join children through unsplit: planner.rs:172-173.)
            n = opts.join_partitions
            build = RepartitionExec(
                build, n, [ex.ColumnRef(b) for b, _ in on]
            )
            probe = RepartitionExec(
                probe, n, [ex.ColumnRef(p) for _, p in on]
            )
            joined: PhysicalPlan = JoinExec(build, probe, on, how,
                                            null_aware=plan.null_aware,
                                            partitioned=True)
        else:
            if build.output_partitioning().num_partitions > 1:
                build = MergeExec(build)
            joined = JoinExec(build, probe, on, how,
                              null_aware=plan.null_aware)
        # restore logical column order if the physical (build-first) order
        # differs (e.g. preserved-left joins probe the left side)
        want = plan.schema().names()
        got = joined.output_schema().names()
        if want != got:
            joined = ProjectionExec([ex.ColumnRef(n) for n in want], joined)
        return joined

    if isinstance(plan, EmptyRelation):
        return EmptyExec(plan.produce_one_row)

    if isinstance(plan, Explain):
        # direct-call path (plan already optimized by the caller);
        # execution.plan_logical captures the pre-optimization text too
        from .explain import make_explain_analyze, render_explain

        if plan.analyze:
            return make_explain_analyze(
                create_physical_plan(plan.input), plan.verbose,
                plan.input.pretty(), opts.adaptive_settings)
        return render_explain(plan.input, create_physical_plan(plan.input),
                              plan.verbose, cost_notes=opts.cost_notes)

    raise NotImplementedError_(f"no physical plan for {type(plan).__name__}")
