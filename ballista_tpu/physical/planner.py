"""Physical planner: logical plan -> physical operator tree.

The reference gets this from DataFusion's ``create_physical_plan``
(reference: rust/scheduler/src/lib.rs:317-331). Ours maps each logical node
to the TPU operators in this package, inserting the Partial->Merge->Final
aggregate split and probe/build side selection for joins.
"""

from __future__ import annotations

from ..errors import NotImplementedError_, PlanError
from .. import expr as ex
from ..logical import (
    Aggregate,
    EmptyRelation,
    Explain,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Projection,
    Repartition,
    Sort,
    TableScan,
)
from .aggregate import HashAggregateExec
from .base import PhysicalPlan
from .join import JoinExec
from .operators import (
    EmptyExec,
    FilterExec,
    LimitExec,
    MergeExec,
    ProjectionExec,
    RepartitionExec,
    ScanExec,
    SortExec,
)


def create_physical_plan(plan: LogicalPlan) -> PhysicalPlan:
    if isinstance(plan, TableScan):
        return ScanExec(plan.table_name, plan.source, plan.projection)

    if isinstance(plan, Projection):
        return ProjectionExec(plan.exprs, create_physical_plan(plan.input))

    if isinstance(plan, Filter):
        return FilterExec(plan.predicate, create_physical_plan(plan.input))

    if isinstance(plan, Aggregate):
        child = create_physical_plan(plan.input)
        partial = HashAggregateExec("partial", plan.group_exprs, plan.agg_exprs, child)
        merged: PhysicalPlan = partial
        if partial.output_partitioning().num_partitions > 1:
            merged = MergeExec(partial)
        return HashAggregateExec("final", plan.group_exprs, plan.agg_exprs, merged)

    if isinstance(plan, Sort):
        child = create_physical_plan(plan.input)
        if child.output_partitioning().num_partitions > 1:
            child = MergeExec(child)
        return SortExec(plan.sort_exprs, child)

    if isinstance(plan, Limit):
        child = create_physical_plan(plan.input)
        if child.output_partitioning().num_partitions > 1:
            child = MergeExec(child)
        return LimitExec(plan.n, child)

    if isinstance(plan, Repartition):
        return RepartitionExec(
            create_physical_plan(plan.input), plan.num_partitions, plan.hash_exprs
        )

    if isinstance(plan, Join):
        left = create_physical_plan(plan.left)
        right = create_physical_plan(plan.right)
        # Probe side = the row-preserving side; build side is merged to one
        # partition and sorted (see JoinExec docstring).
        if plan.how == "inner":
            build, probe, how = left, right, "inner"
            on = list(plan.on)
        elif plan.how == "left":
            build, probe, how = right, left, "left"
            on = [(r, l) for l, r in plan.on]
        elif plan.how == "right":
            build, probe, how = left, right, "left"
            on = list(plan.on)
        elif plan.how in ("semi", "anti"):
            build, probe, how = right, left, plan.how
            on = [(r, l) for l, r in plan.on]
        else:
            raise NotImplementedError_(f"join type {plan.how}")
        if build.output_partitioning().num_partitions > 1:
            build = MergeExec(build)
        joined: PhysicalPlan = JoinExec(build, probe, on, how,
                                        null_aware=plan.null_aware)
        # restore logical column order if the physical (build-first) order
        # differs (e.g. preserved-left joins probe the left side)
        want = plan.schema().names()
        got = joined.output_schema().names()
        if want != got:
            joined = ProjectionExec([ex.ColumnRef(n) for n in want], joined)
        return joined

    if isinstance(plan, EmptyRelation):
        return EmptyExec(plan.produce_one_row)

    if isinstance(plan, Explain):
        raise PlanError("Explain handled by the client layer")

    raise NotImplementedError_(f"no physical plan for {type(plan).__name__}")
