"""Physical plan layer: executable operators over ColumnBatches.

TPU-native equivalents of the reference's 15 ``PhysicalPlanNode`` operator
variants (reference: rust/core/proto/ballista.proto:294-312): scan, filter,
projection, hash-aggregate (partial/final), sort, limits, merge, join,
repartition, plus the distributed shuffle trio (query-stage, shuffle-reader,
unresolved-shuffle) in ``shuffle.py``.
"""

from .base import PhysicalPlan, PipelineOp, Partitioning  # noqa: F401
