"""Whole-stage fusion: compile each pipeline stage into ONE governed
XLA program.

Runs AFTER physical planning (and re-runs after adaptive re-planning),
at execution boundaries only — the standalone collect path, the
executor's task runner, and EXPLAIN [ANALYZE] — so serialized cluster
plans never carry fused operators and serde stays untouched.

Three rewrites (all gated by ``BALLISTA_FUSION``, default on):

- **Aggregate stages** (:class:`FusedStageExec`): a partial/final
  ``HashAggregateExec`` absorbs the scan→filter→project pipeline chain
  feeding it. The chain's ``device_transform``s run INSIDE the
  aggregate's traced programs (``HashAggregateExec._device_prologue``),
  so the whole stage is one governed jit entry — and the stage executes
  once per partition over the CONCATENATED source batches instead of
  dispatching the chain per scan chunk (each chunk's fresh dictionaries
  previously forced a re-trace per chunk; q1+q5 cold minted 122 XLA
  programs, most of them these).
- **Probe-side join chains**: Filter/Projection chains feeding a
  ``JoinExec`` probe fold into the join's probe programs
  (``JoinExec.probe_chain``) when every probe key column passes through
  the chain as a plain column reference — the inter-join column-order
  projections q5 plans between every pair of joins stop being separate
  per-batch programs.
- **Distinct-within-group** (:class:`FusedDistinctCountExec`): the SQL
  planner's COUNT(DISTINCT) two-level rewrite (dedup on (g, x), then
  count per g — three sort-based groupings) collapses into ONE
  single-pass kernel (``kernels.aggregate.grouped_distinct_count``,
  one lexicographic sort). This is the fused kernel plan merging alone
  cannot produce — q16's group-then-recount double-agg held ~1.6s of
  its 1.9s warm time.

Fusion reorders NOTHING: live-row order, group emission order and all
arithmetic (int64/decimal exact; f32 sums add identical sequences) are
preserved, so results are byte-identical with ``BALLISTA_FUSION=0``.
"""

from __future__ import annotations

import itertools
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import Column, ColumnBatch, round_capacity
from ..compile import fingerprint
from ..datatypes import Field, Schema
from .. import expr as ex
from ..kernels.aggregate import grouped_distinct_count
from ..kernels.expr_eval import Evaluator
from ..observability import trace_event, trace_span
from .aggregate import HashAggregateExec
from .base import (PhysicalPlan, PipelineOp, Partitioning, SchemaLeaf,
                   concat_batches)
from .join import JoinExec
from .operators import FilterExec, MergeExec, ProjectionExec

# pipeline operators whose device_transform may run inside a fused
# stage program (the only PipelineOps today; a future stateful one must
# opt in explicitly)
_FUSABLE_OPS = (FilterExec, ProjectionExec)


def fusion_enabled() -> bool:
    return os.environ.get("BALLISTA_FUSION", "on").lower() not in (
        "0", "off", "false", "no")


# ---------------------------------------------------------------------------
# shared chain mechanics
# ---------------------------------------------------------------------------


def _chain_prologue(chain: Sequence[PipelineOp], batch: ColumnBatch):
    """Apply a fused chain (innermost first). Traced."""
    for op in chain:
        batch = op.device_transform(batch)
    return batch


def _rebuild_chain(chain: Sequence[PipelineOp], source: PhysicalPlan):
    """Re-link a fused chain over a replacement source (adaptive
    re-planning swaps children); signatures are value-based, so the
    rebuilt stage hits the same governed entries."""
    node: PhysicalPlan = source
    rebuilt: List[PipelineOp] = []
    for op in chain:
        node = op.with_new_children([node])
        rebuilt.append(node)
    return rebuilt, node


def _chain_label(chain, source, head: str, stage_no: int) -> str:
    parts = [type(source).__name__.replace("Exec", "")]
    parts += [type(op).__name__.replace("Exec", "") for op in chain]
    parts.append(head)
    return f"[fused stage {stage_no}: {'→'.join(parts)}]"


def _fused_pretty(node, indent: int, with_metrics: bool) -> str:
    """Plan text for a fused stage: the stage line (with its
    compile/execute split under ANALYZE), the absorbed operators marked
    ``[fused]``, then the source subtree."""
    if with_metrics:
        ann = node.metrics().summary()
        head = node.display() + (f", metrics=[{ann}]" if ann else "")
    else:
        head = node.display()
    out = "  " * indent + head + "\n"
    for op in reversed(node.chain):
        out += "  " * (indent + 1) + "· " + op.display() + " [fused]\n"
    sub = (node.source.pretty_metrics(indent + 1) if with_metrics
           else node.source.pretty(indent + 1))
    return out + sub


# ---------------------------------------------------------------------------
# FusedStageExec: pipeline chain + aggregate as one program
# ---------------------------------------------------------------------------


class FusedStageExec(HashAggregateExec):
    """A ``HashAggregateExec`` fused with the pipeline chain feeding it.

    ``chain`` holds the absorbed PipelineOps in apply order (innermost —
    closest to the source — first); ``child`` remains the chain's
    outermost operator so every schema derivation of the base class
    stays valid, but execution pulls RAW batches from ``source`` and
    the chain runs inside the traced aggregation programs via
    ``_device_prologue``.
    """

    def __init__(self, mode, group_exprs, agg_exprs, chain, source,
                 group_capacity, stage_no: int = 0):
        assert chain, "a fused stage absorbs at least one pipeline op"
        super().__init__(mode, group_exprs, agg_exprs, chain[-1],
                         group_capacity)
        self.chain = list(chain)
        self.source = source
        self.stage_no = stage_no
        # (dict-length fingerprint, post-chain abstract batch) — see
        # _post_chain_abstract
        self._chain_probe = None

    @classmethod
    def from_agg(cls, agg: HashAggregateExec, chain, source,
                 stage_no: int) -> "FusedStageExec":
        return cls(agg.mode, agg.group_exprs, agg.agg_exprs, chain,
                   source, agg.group_capacity, stage_no)

    # -- plan surface --------------------------------------------------------

    def children(self) -> List[PhysicalPlan]:
        return [self.source]

    def with_new_children(self, children):
        rebuilt, _top = _rebuild_chain(self.chain, children[0])
        return FusedStageExec(self.mode, self.group_exprs, self.agg_exprs,
                              rebuilt, children[0], self.group_capacity,
                              self.stage_no)

    def output_partitioning(self) -> Partitioning:
        if self.mode == "partial":
            return self.source.output_partitioning()
        return Partitioning(
            "unknown", self.source.output_partitioning().num_partitions)

    def _signature_parts(self) -> tuple:
        return HashAggregateExec._signature_parts(self) + (
            tuple(op.compile_signature() for op in self.chain),)

    def _detach(self) -> None:
        HashAggregateExec._detach(self)
        self.source = SchemaLeaf(self.source.output_schema())
        self.chain = [op.trace_twin() for op in self.chain]
        self._chain_probe = None

    def display(self) -> str:
        head = "PartialAgg" if self.mode == "partial" else "FinalAgg"
        return (HashAggregateExec.display(self) + " "
                + _chain_label(self.chain, self.source, head,
                               self.stage_no))

    def pretty(self, indent: int = 0) -> str:
        return _fused_pretty(self, indent, with_metrics=False)

    def pretty_metrics(self, indent: int = 0) -> str:
        return _fused_pretty(self, indent, with_metrics=True)

    # -- execution -----------------------------------------------------------

    def _device_prologue(self, batch: ColumnBatch) -> ColumnBatch:
        return _chain_prologue(self.chain, batch)

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        from ..cache.donation import mark_transient

        batches = list(self.source.execute(partition))
        if not batches:
            return
        batch = concat_batches(self.source.output_schema(), batches)
        if not self.group_exprs:
            out = self._exec_scalar(batch)
        else:
            out = self._exec_grouped(batch)
        # fresh program output, one downstream consumer: donatable
        mark_transient(out)
        yield out

    def _post_chain_abstract(self, batch: ColumnBatch):
        """Abstract (eval_shape) post-chain batch for host-side path
        probing: dictionaries/validity ride the pytree aux data, so the
        base class's static-group-bound check works unchanged on it.
        Cached per dictionary-length fingerprint like ``_mixed_cache``
        — the warm path must not pay a re-trace per call."""
        fp = (batch.capacity,) + tuple(
            len(c.dictionary) if c.dictionary is not None else -1
            for c in batch.columns)
        cached = self._chain_probe
        if cached is not None and cached[0] == fp:
            return cached[1]
        tw = self.trace_twin()
        try:
            probe = jax.eval_shape(tw._device_prologue, batch)
        except Exception:  # noqa: BLE001 - unprobeable: no static bound
            probe = None
        self._chain_probe = (fp, probe)
        return probe

    def _static_group_bound(self, batch: ColumnBatch) -> Optional[int]:
        probe = self._post_chain_abstract(batch)
        if probe is None:
            return None
        return super()._static_group_bound(probe)


# ---------------------------------------------------------------------------
# FusedDistinctCountExec: single-pass COUNT(DISTINCT x) GROUP BY g
# ---------------------------------------------------------------------------


class FusedDistinctCountExec(PhysicalPlan):
    """Replaces the COUNT(DISTINCT) double-aggregate tower
    (final-count ← partial-count ← final-dedup [← merge ← partial-dedup])
    with one program: sort by (g, x) once, count distinct-pair starts
    per group (``grouped_distinct_count``). When the dedup ran on a
    single partition it is dropped entirely and this operator fuses the
    dedup's pipeline chain instead (the kernel dedups anyway)."""

    def __init__(self, group_exprs: List[ex.Expr], distinct_expr: ex.Expr,
                 out_field: Field, chain: Sequence[PipelineOp],
                 source: PhysicalPlan, group_capacity: int,
                 stage_no: int = 0):
        self.group_exprs = list(group_exprs)
        self.distinct_expr = distinct_expr
        self.out_field = out_field
        self.chain = list(chain)
        self.source = source
        self.group_capacity = group_capacity
        self.stage_no = stage_no
        self._in_schema = (chain[-1] if chain else source).output_schema()
        self._ev = Evaluator(self._in_schema)
        gf = [e.to_field(self._in_schema) for e in self.group_exprs]
        self._schema = Schema(gf + [out_field])

    # -- plan surface --------------------------------------------------------

    def output_schema(self) -> Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning(
            "unknown", self.source.output_partitioning().num_partitions)

    def children(self) -> List[PhysicalPlan]:
        return [self.source]

    def with_new_children(self, children):
        chain, _top = _rebuild_chain(self.chain, children[0])
        return FusedDistinctCountExec(
            self.group_exprs, self.distinct_expr, self.out_field, chain,
            children[0], self.group_capacity, self.stage_no)

    def _signature_parts(self) -> tuple:
        return (fingerprint(self.group_exprs),
                fingerprint(self.distinct_expr), self.out_field,
                self._in_schema,
                tuple(op.compile_signature() for op in self.chain))

    def _detach(self) -> None:
        self.source = SchemaLeaf(self.source.output_schema())
        self.chain = [op.trace_twin() for op in self.chain]

    def display(self) -> str:
        g = ", ".join(e.name() for e in self.group_exprs)
        return (f"FusedDistinctCountExec: gby=[{g}] "
                f"distinct={self.distinct_expr.name()} "
                + _chain_label(self.chain, self.source, "DistinctCount",
                               self.stage_no))

    def pretty(self, indent: int = 0) -> str:
        return _fused_pretty(self, indent, with_metrics=False)

    def pretty_metrics(self, indent: int = 0) -> str:
        return _fused_pretty(self, indent, with_metrics=True)

    # -- execution -----------------------------------------------------------

    def _device_prologue(self, batch: ColumnBatch) -> ColumnBatch:
        return _chain_prologue(self.chain, batch)

    def _get_fn(self, cap: int):
        def build():
            tw = self.trace_twin()

            def run(b: ColumnBatch):
                b = tw._device_prologue(b)
                key_evals = [tw._ev.evaluate(e, b) for e in tw.group_exprs]
                d = tw._ev.evaluate(tw.distinct_expr, b)
                keys = [jnp.broadcast_to(r.values, (b.capacity,))
                        for r in key_evals]
                res = grouped_distinct_count(
                    keys, b.selection,
                    jnp.broadcast_to(d.values, (b.capacity,)), cap,
                    [r.validity for r in key_evals], d.validity)
                return tw._assemble(b, key_evals, res, cap), \
                    res.num_groups

            return run

        return self.governed_jit(("agg.distinct", cap), build)

    def _assemble(self, batch, key_evals, res, cap: int):
        """GroupedResult -> output batch (group cols + count). Traced."""
        cols: List[Column] = []
        for f, r in zip(self._schema.fields[:-1], key_evals):
            vals = jnp.take(
                jnp.broadcast_to(r.values, (batch.capacity,)),
                res.rep_indices)
            validity = (jnp.take(r.validity, res.rep_indices)
                        if r.validity is not None else None)
            cols.append(Column(vals, f.dtype, validity, r.dictionary))
        cols.append(Column(res.aggregates[0], self.out_field.dtype, None,
                           None))
        return ColumnBatch(self._schema, cols, res.group_valid,
                           jnp.minimum(res.num_groups, cap))

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        batches = list(self.source.execute(partition))
        if not batches:
            return
        batch = concat_batches(self.source.output_schema(), batches)
        cap = self.group_capacity
        while True:
            out, num_groups = self._get_fn(cap)(batch)
            with trace_span("device.block", site="agg.distinct"):
                ng = int(num_groups)
            if ng <= cap:
                # persist like HashAggregateExec: later collects skip
                # the undersized attempt + retry sync
                self.group_capacity = max(self.group_capacity, cap)
                yield out
                return
            cap = round_capacity(ng)


# ---------------------------------------------------------------------------
# the fusion planner pass
# ---------------------------------------------------------------------------


def _passthrough_map(chain: Sequence[PipelineOp],
                     names: Sequence[str]) -> Optional[Dict[str, str]]:
    """post-chain column name -> raw source column name for ``names``,
    or None when any of them is computed/renamed by something other
    than a plain (possibly aliased) column reference."""
    mapping = {n: n for n in names}
    for op in reversed(chain):  # outermost first
        if isinstance(op, FilterExec):
            continue
        if not isinstance(op, ProjectionExec):
            return None
        nxt: Dict[str, str] = {}
        for post, cur in mapping.items():
            e = next((e for e in op.exprs if e.name() == cur), None)
            base = ex.strip_alias(e) if e is not None else None
            if not isinstance(base, ex.ColumnRef):
                return None
            nxt[post] = base.column
        mapping = nxt
    return mapping


def _match_distinct(node) -> Optional[tuple]:
    """Match the physical tower the SQL planner's COUNT(DISTINCT)
    rewrite produces:

        HashAggregateExec(final,  G, [count(x)])        <- node
          HashAggregateExec(partial, G, [count(x)])
            HashAggregateExec(final, G+[x], [])
              <base>   (MergeExec(partial-dedup) | partial-dedup | other)

    Returns (outer_final, inner_final, base, distinct_col, out_name)
    or None. Only exact HashAggregateExec nodes participate (an already
    fused subclass never re-matches)."""
    if not (type(node) is HashAggregateExec and node.mode == "final"):
        return None
    if not node.group_exprs or len(node._aggs) != 1:
        return None
    out_name, cagg = node._aggs[0]
    if cagg.fn != "count" or cagg.is_star or cagg.expr is None:
        return None
    tgt = ex.strip_alias(cagg.expr)
    if not isinstance(tgt, ex.ColumnRef):
        return None
    for e in node.group_exprs:
        if not isinstance(ex.strip_alias(e), ex.ColumnRef):
            return None
    part = node.child
    if not (type(part) is HashAggregateExec and part.mode == "partial"
            and fingerprint(part.group_exprs) == fingerprint(node.group_exprs)
            and fingerprint(part.agg_exprs) == fingerprint(node.agg_exprs)):
        return None
    inner = part.child
    if not (type(inner) is HashAggregateExec and inner.mode == "final"
            and not inner._aggs):
        return None
    inner_names = [e.name() for e in inner.group_exprs]
    outer_names = [e.name() for e in node.group_exprs]
    if inner_names[:-1] != outer_names or inner_names[-1] != tgt.column:
        return None
    return node, inner, inner.child, tgt.column, out_name


def _build_distinct(match, transform, counter, stats):
    node, inner, base, distinct_col, out_name = match

    def _matching_dedup(cand) -> bool:
        return (type(cand) is HashAggregateExec and cand.mode == "partial"
                and not cand._aggs
                and fingerprint(cand.group_exprs)
                == fingerprint(inner.group_exprs))

    chain: List[PipelineOp] = []
    if isinstance(base, MergeExec) and _matching_dedup(base.child):
        # Merge(partial-dedup): in-process the dedup is pure overhead —
        # the distinct kernel dedups by construction, and its row-wise
        # input chain commutes with the merge's concat. Merge the
        # dedup's RAW input partitions and absorb its chain. (A cluster
        # stage split at a shuffle never produces this shape; the
        # per-partition dedup stays the shuffle reducer there.)
        dedup = base.child
        if isinstance(dedup.child, PipelineOp):
            chain, src = dedup.child._pipeline_chain()
            if not all(isinstance(op, _FUSABLE_OPS) for op in chain):
                chain, src = [], dedup.child
        else:
            src = dedup.child
        source: PhysicalPlan = MergeExec(src)
        group_exprs = list(dedup.group_exprs[:-1])
        distinct_expr: ex.Expr = dedup.group_exprs[-1]
    elif isinstance(base, MergeExec):
        # multi-partition dedup of an unrecognized shape stays; the
        # generic pass below fuses the partial-dedup with its own chain
        # when it recurses into the merge
        source = base
        group_exprs = [ex.ColumnRef(n) for n in
                       [e.name() for e in inner.group_exprs[:-1]]]
        distinct_expr = ex.ColumnRef(distinct_col)
    elif (type(base) is HashAggregateExec and base.mode == "partial"
          and not base._aggs
          and fingerprint(base.group_exprs) == fingerprint(inner.group_exprs)
          and base.output_partitioning().num_partitions == 1):
        # single-partition dedup is pure overhead — the distinct kernel
        # dedups by construction. Fuse the dedup's own pipeline chain
        # into this stage instead.
        if isinstance(base.child, PipelineOp):
            chain, src = base.child._pipeline_chain()
            if not all(isinstance(op, _FUSABLE_OPS) for op in chain):
                chain, src = [], base.child
        else:
            src = base.child
        source = src
        group_exprs = list(base.group_exprs[:-1])
        distinct_expr = base.group_exprs[-1]
    elif base.output_partitioning().num_partitions == 1:
        source = base
        group_exprs = [ex.ColumnRef(n) for n in
                       [e.name() for e in inner.group_exprs[:-1]]]
        distinct_expr = ex.ColumnRef(distinct_col)
    else:
        return None  # multi-partition base without a merge: leave as-is
    out_field = node.output_schema().fields[-1]
    fused = FusedDistinctCountExec(
        group_exprs, distinct_expr, out_field, chain, source,
        node.group_capacity, next(counter))
    if fused.output_schema() != node.output_schema():
        return None  # safety: the rewrite must be schema-invisible
    src2 = transform(source)
    if src2 is not source:
        fused = fused.with_new_children([src2])
    stats["distinct"] += 1
    trace_event("compile.fuse", kind="distinct",
                stage=fused.stage_no, ops=fused.display()[:160])
    return fused


def fuse_plan(phys: PhysicalPlan, *, fuse_joins: bool = True,
              _counter=None) -> PhysicalPlan:
    """One bottom-up fusion pass. Idempotent: already-fused operators
    only have their sources revisited, so re-running after an adaptive
    re-plan fuses new subtrees and (value-keyed signatures) reuses every
    compiled entry. ``fuse_joins=False`` skips probe-chain fusion — the
    post-adaptive re-pass uses it so a demoted join keeps the probe
    chain (and compiled programs) it already has."""
    counter = _counter or itertools.count(1)
    stats = {"stages": 0, "joins": 0, "distinct": 0}

    def transform(node: PhysicalPlan) -> PhysicalPlan:
        if isinstance(node, (FusedStageExec, FusedDistinctCountExec)):
            src = transform(node.source)
            return (node if src is node.source
                    else node.with_new_children([src]))
        m = _match_distinct(node)
        if m is not None:
            fused = _build_distinct(m, transform, counter, stats)
            if fused is not None:
                return fused
        if type(node) is HashAggregateExec and \
                isinstance(node.child, PipelineOp):
            chain, source = node.child._pipeline_chain()
            if all(isinstance(op, _FUSABLE_OPS) for op in chain):
                fused = FusedStageExec.from_agg(node, chain, source,
                                                next(counter))
                src = transform(source)
                if src is not source:
                    fused = fused.with_new_children([src])
                stats["stages"] += 1
                trace_event("compile.fuse", kind="stage",
                            stage=fused.stage_no,
                            ops=fused.display()[:160])
                return fused
        if (fuse_joins and isinstance(node, JoinExec)
                and not node.probe_chain
                and isinstance(node.probe, PipelineOp)):
            chain, source = node.probe._pipeline_chain()
            if all(isinstance(op, _FUSABLE_OPS) for op in chain):
                key_map = _passthrough_map(chain,
                                           [p for _, p in node.on])
                if key_map is not None:
                    build = transform(node.build)
                    src = transform(source)
                    stats["joins"] += 1
                    fused_join = JoinExec(
                        build, src, node.on, node.how,
                        null_aware=node.null_aware,
                        partitioned=node.partitioned,
                        adaptive_note=node.adaptive_note,
                        probe_chain=chain, probe_key_raw=key_map)
                    trace_event("compile.fuse", kind="join_probe",
                                ops=fused_join.display()[:160])
                    return fused_join
        kids = node.children()
        if kids:
            new = [transform(c) for c in kids]
            if not all(a is b for a, b in zip(kids, new)):
                node = node.with_new_children(new)
        return node

    with trace_span("compile.fuse"):
        out = transform(phys)
        if any(stats.values()):
            # aggregate counts next to the per-stage events: the first
            # thing to grep when hunting silent de-fusion
            trace_event("compile.fuse", kind="summary",
                        stages=stats["stages"], joins=stats["joins"],
                        distinct=stats["distinct"])
    return out


def maybe_fuse(phys: PhysicalPlan, *,
               fuse_joins: bool = True) -> PhysicalPlan:
    """``fuse_plan`` behind the ``BALLISTA_FUSION`` gate, marking the
    root so repeated collect calls on a cached plan skip the walk."""
    if not fusion_enabled():
        return phys
    if getattr(phys, "_fusion_applied", False):
        return phys
    out = fuse_plan(phys, fuse_joins=fuse_joins)
    try:
        out._fusion_applied = True
    except AttributeError:
        pass
    return out
