"""Core physical operators: scan, filter, projection, merge, sort, limit,
repartition.

TPU-native equivalents of the reference's PhysicalPlanNode variants
CsvScan/ParquetScan/Filter/Projection/Merge/Sort/GlobalLimit/LocalLimit/
Repartition/CoalesceBatches (reference: rust/core/proto/ballista.proto:
294-312). Filter and Projection are PipelineOps — they fuse with adjacent
pipeline stages into a single XLA program (batches never round-trip to HBM
between them).
"""

from __future__ import annotations

import threading
from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, ColumnBatch
from ..compile import bucket_capacity, fingerprint
from ..datatypes import Schema
from ..errors import ExecutionError, NotImplementedError_
from .. import expr as ex
from ..kernels.expr_eval import Evaluator
from ..kernels.sort import sort_permutation
from ..kernels.hashing import splitmix64
from ..logical import TableSource
from .base import (PhysicalPlan, PipelineOp, Partitioning, concat_batches,
                   pad_batch, take_batch)


def compute_partition_ids(batch: ColumnBatch, hash_exprs, num_partitions: int,
                          row_offset: int, evaluator: Evaluator):
    """int32 partition id per row: chained splitmix64 over the hash exprs,
    or round-robin by global row index. Shared by the in-process
    RepartitionExec and the executor's shuffle writes so both planes agree.

    utf8 keys hash their STRING VALUE (via per-dictionary stable FNV-1a
    hashes), never the dictionary code — codes are producer-local and would
    break hash co-location across independent producers."""
    if hash_exprs:
        h = jnp.zeros((batch.capacity,), jnp.uint64)
        for e in hash_exprs:
            r = evaluator.evaluate(e, batch)
            v = jnp.broadcast_to(r.values, (batch.capacity,))
            if r.dictionary is not None:
                str_hashes = jnp.asarray(r.dictionary.stable_hashes())
                v = jnp.take(str_hashes, v.astype(jnp.int32), mode="clip")
            h = splitmix64(h ^ splitmix64(v.astype(jnp.int64)))
        return (h % jnp.uint64(num_partitions)).astype(jnp.int32)
    idx = row_offset + jnp.arange(batch.capacity, dtype=jnp.int32)
    return idx % num_partitions


class ScanExec(PhysicalPlan):
    """Table scan over a partitioned source (reference: CsvScanExecNode /
    ParquetScanExecNode, ballista.proto:334-354).

    Execution rides the ingest pipeline (ballista_tpu/ingest): with
    ``BALLISTA_PREFETCH_BATCHES`` > 0 the source generator runs on a
    pool worker behind a bounded queue, so parse+H2D of chunk N+1
    overlaps the consumer's device compute on chunk N, and scans
    ``prime()``d ahead (client/executor collect paths) overlap each
    other cross-table. ``BALLISTA_PREFETCH_BATCHES=0`` restores the
    serial inline pull exactly."""

    def __init__(self, table_name: str, source: TableSource,
                 projection: Optional[Sequence[str]] = None):
        self.table_name = table_name
        self.source = source
        self.projection = tuple(projection) if projection is not None else None
        # partition -> live PrefetchHandle (primed ahead of execution);
        # the lock covers priming from the collect thread racing an
        # executor worker's execute()
        self._primed: dict = {}
        self._primed_lock = threading.Lock()

    def output_schema(self) -> Schema:
        s = self.source.table_schema()
        return s.project(self.projection) if self.projection else s

    def output_partitioning(self) -> Partitioning:
        return Partitioning("unknown", self.source.num_partitions())

    def with_new_children(self, children):
        assert not children
        return self

    def _recorder(self):
        from ..ingest.phases import PhaseRecorder
        from ..observability.metrics import metrics_enabled

        return PhaseRecorder(self.metrics() if metrics_enabled() else None)

    def _prefetchable(self, partition: int) -> bool:
        """False when there is no parse/H2D to overlap: memory-resident
        sources, cache sources already materialized for this
        (partition, projection), and device-resident partitions (table
        cache hit) — the warm path stays queue-free."""
        from ..io.cache import CacheSource
        from ..io.memory import MemTableSource

        src = self.source
        if isinstance(src, MemTableSource):
            return False
        if isinstance(src, CacheSource) and \
                src.is_materialized(partition, self.projection):
            return False
        is_resident = getattr(src, "is_resident", None)
        if is_resident is not None and is_resident(partition,
                                                   self.projection):
            return False
        return True

    def prime(self, partition: int):
        """Start background parse+H2D for one partition on the ingest
        pool (idempotent). Returns the handle, or None when the
        pipeline is gated off or there is nothing to overlap."""
        from ..ingest import prefetch_batches
        from ..ingest.pipeline import PrefetchHandle

        depth = prefetch_batches()
        if depth <= 0 or not self._prefetchable(partition):
            return None
        with self._primed_lock:
            h = self._primed.get(partition)
            if h is None:
                h = PrefetchHandle(
                    lambda p=partition: self.source.scan(p, self.projection),
                    depth,
                    label=f"{self.table_name}[{partition}]",
                    recorder=self._recorder(),
                )
                self._primed[partition] = h
        return h

    def cancel_primed(self) -> None:
        """Drop every unconsumed primed handle (plan abandoned or
        rewritten away): producers stop, queued batches release."""
        with self._primed_lock:
            handles, self._primed = list(self._primed.values()), {}
        for h in handles:
            h.cancel()

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        from ..ingest import prefetch_batches
        from ..ingest.phases import bound_iter

        if prefetch_batches() > 0:
            self.prime(partition)  # no-op when nothing to overlap
        with self._primed_lock:
            handle = self._primed.pop(partition, None)
        if handle is None:  # pipeline off: the old serial pull loop
            yield from bound_iter(
                self.source.scan(partition, self.projection),
                self._recorder())
        else:
            try:
                yield from handle
            finally:
                # consumer may abandon the stream early (LimitExec):
                # stop the producer instead of leaving it blocked on a
                # full queue
                handle.cancel()
        self._record_cache_outcome(partition)

    def _record_cache_outcome(self, partition: int) -> None:
        from ..observability.metrics import metrics_enabled

        if not metrics_enabled():
            return
        fn = getattr(self.source, "scan_cache_outcome", None)
        if fn is not None and fn(partition) == "hit":
            self.metrics().add_counter("table_cache_hits")

    def estimated_rows(self):
        return self.source.estimated_rows()

    def display(self) -> str:
        p = f" projection={list(self.projection)}" if self.projection else ""
        return f"ScanExec: {self.table_name}{p}"

    def pretty_metrics(self, indent: int = 0) -> str:
        """EXPLAIN ANALYZE line with the device-residency outcome of
        the latest scan(s) appended — deliberately NOT in display(),
        which feeds compile signatures and must stay run-invariant."""
        fn = getattr(self.source, "scan_cache_outcome", None)
        outcomes = set()
        if fn is not None:
            for p in range(self.source.num_partitions()):
                o = fn(p)
                if o is not None:
                    outcomes.add(o)
        cache_ann = (f" [cache: {'|'.join(sorted(outcomes))}]"
                     if outcomes else "")
        ann = self.metrics().summary()
        return ("  " * indent + self.display() + cache_ann
                + (f", metrics=[{ann}]" if ann else "") + "\n")


class FilterExec(PipelineOp):
    compactable = True  # kills rows: fused chain output is compacted

    def __init__(self, predicate: ex.Expr, child: PhysicalPlan):
        self.predicate = predicate
        self.child = child
        self._ev = Evaluator(child.output_schema())

    def _signature_parts(self) -> tuple:
        return (fingerprint(self.predicate), self.child.output_schema())

    def output_schema(self) -> Schema:
        return self.child.output_schema()

    def with_new_children(self, children):
        return FilterExec(self.predicate, children[0])

    def device_transform(self, batch: ColumnBatch) -> ColumnBatch:
        mask = self._ev.evaluate_predicate(self.predicate, batch)
        sel = jnp.logical_and(batch.selection, mask)
        return batch.with_selection(sel)

    def display(self) -> str:
        return f"FilterExec: {self.predicate.name()}"


class ProjectionExec(PipelineOp):
    def __init__(self, exprs: List[ex.Expr], child: PhysicalPlan):
        self.exprs = list(exprs)
        self.child = child
        self._in_schema = child.output_schema()
        self._ev = Evaluator(self._in_schema)
        self._schema = Schema([e.to_field(self._in_schema) for e in self.exprs])

    def _signature_parts(self) -> tuple:
        return (fingerprint(self.exprs), self._in_schema)

    def output_schema(self) -> Schema:
        return self._schema

    def with_new_children(self, children):
        return ProjectionExec(self.exprs, children[0])

    def device_transform(self, batch: ColumnBatch) -> ColumnBatch:
        cols = [self._ev.to_column(e, batch) for e in self.exprs]
        # trust planned schema for dtypes (evaluator agrees by construction)
        return batch.with_columns(self._schema, cols)

    def display(self) -> str:
        return f"ProjectionExec: {', '.join(e.name() for e in self.exprs)}"


class MergeExec(PhysicalPlan):
    """Gather all input partitions into one (reference: MergeExecNode,
    ballista.proto:409-413; planner boundary at planner.rs:136-148)."""

    def __init__(self, child: PhysicalPlan):
        self.child = child

    def output_schema(self) -> Schema:
        return self.child.output_schema()

    def output_partitioning(self) -> Partitioning:
        return Partitioning("unknown", 1)

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return MergeExec(children[0])

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        if partition != 0:
            raise ExecutionError("MergeExec has a single output partition")
        from ..ingest import iter_partitions

        # pipelined: child partitions (each a whole scan/join/partial-agg
        # subtree) produce concurrently on the ingest pool, merged in
        # partition order — the serial pull loop when gated off
        yield from iter_partitions(
            self.child,
            range(self.child.output_partitioning().num_partitions))

    def display(self) -> str:
        return "MergeExec"


class CoalesceBatchesExec(PhysicalPlan):
    """Concatenate a partition's batches into one device batch (reference:
    CoalesceBatchesExecNode, ballista.proto:362-368 — there it re-chunks
    small batches; here it feeds barrier ops one static-shape batch)."""

    def __init__(self, child: PhysicalPlan):
        self.child = child

    def output_schema(self) -> Schema:
        return self.child.output_schema()

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return CoalesceBatchesExec(children[0])

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        batches = list(self.child.execute(partition))
        if not batches:
            return
        yield concat_batches(self.output_schema(), batches)

    def display(self) -> str:
        return "CoalesceBatchesExec"


class SortExec(PhysicalPlan):
    """Total sort of a single partition (reference: SortExecNode,
    ballista.proto:424-431)."""

    def __init__(self, sort_exprs: List[ex.SortExpr], child: PhysicalPlan):
        self.sort_exprs = list(sort_exprs)
        self.child = child
        self._ev = Evaluator(child.output_schema())

    def _signature_parts(self) -> tuple:
        return (fingerprint(self.sort_exprs), self.child.output_schema())

    def output_schema(self) -> Schema:
        return self.child.output_schema()

    def output_partitioning(self) -> Partitioning:
        return Partitioning("unknown", 1)

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return SortExec(self.sort_exprs, children[0])

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        batches = list(self.child.execute(partition))
        if not batches:
            return
        batch = concat_batches(self.output_schema(), batches)

        def build():
            tw = self.trace_twin()  # don't pin the child subtree

            def do_sort(b: ColumnBatch) -> ColumnBatch:
                keys = []
                for se in tw.sort_exprs:
                    r = tw._ev.evaluate(se.expr, b)
                    v = jnp.broadcast_to(r.values, (b.capacity,))
                    keys.append((v, se.ascending))
                perm = sort_permutation(keys, b.selection)
                live_sorted = jnp.take(b.selection, perm)
                return take_batch(b, perm, live_sorted)

            return do_sort

        yield self.governed_jit(("sort.run",), build)(batch)

    def display(self) -> str:
        return f"SortExec: {', '.join(e.name() for e in self.sort_exprs)}"


class LimitExec(PhysicalPlan):
    """Take the first n live rows of a (single) partition (reference:
    GlobalLimitExecNode/LocalLimitExecNode, ballista.proto:386-397)."""

    def __init__(self, n: int, child: PhysicalPlan):
        self.n = n
        self.child = child

    def _signature_parts(self) -> tuple:
        return ()  # take_first is operator-independent (n is traced)

    def output_schema(self) -> Schema:
        return self.child.output_schema()

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return LimitExec(self.n, children[0])

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        remaining = self.n

        def build():
            def take_first(b: ColumnBatch, k) -> ColumnBatch:
                rank = jnp.cumsum(b.selection.astype(jnp.int32)) - 1
                sel = jnp.logical_and(b.selection, rank < k)
                return b.with_selection(sel)

            return take_first

        take = self.governed_jit(("limit.take",), build)
        for batch in self.child.execute(partition):
            if remaining <= 0:
                break
            out = take(batch, jnp.int32(remaining))
            remaining -= out.num_rows_host()
            yield out

    def display(self) -> str:
        return f"LimitExec: {self.n}"


class RepartitionExec(PhysicalPlan):
    """Re-partition input into N output partitions by hash or round-robin
    (reference: RepartitionExecNode, ballista.proto:415-422).

    Single-process implementation: child partitions are materialized once and
    each output partition applies a selection mask (pid == p) — no compaction
    on device. The distributed path uses shuffle writes instead.
    """

    def __init__(self, child: PhysicalPlan, num_partitions: int,
                 hash_exprs: Optional[List[ex.Expr]] = None):
        self.child = child
        self.num_partitions = num_partitions
        self.hash_exprs = hash_exprs
        self._ev = Evaluator(child.output_schema())
        self._cache: Optional[List[ColumnBatch]] = None
        # concurrent partition execution (ingest iter_partitions, the
        # cluster analogue of which is per-task plan instances) must
        # materialize exactly once; RLock: _materialize_parts calls
        # _materialize
        self._mat_lock = threading.RLock()

    def _signature_parts(self) -> tuple:
        return (self.num_partitions, fingerprint(self.hash_exprs),
                self.child.output_schema())

    def _detach(self) -> None:
        super()._detach()
        self._cache = None
        self._parts = None  # materialized batches must not be pinned

    def output_schema(self) -> Schema:
        return self.child.output_schema()

    def output_partitioning(self) -> Partitioning:
        kind = "hash" if self.hash_exprs else "round_robin"
        cols = tuple(e.name() for e in (self.hash_exprs or []))
        return Partitioning(kind, self.num_partitions, cols)

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return RepartitionExec(children[0], self.num_partitions, self.hash_exprs)

    def partition_ids(self, batch: ColumnBatch, row_offset: int) -> jax.Array:
        """int32 partition id per row (traced)."""
        return compute_partition_ids(batch, self.hash_exprs,
                                     self.num_partitions, row_offset,
                                     self._ev)

    def _materialize(self) -> List[ColumnBatch]:
        with self._mat_lock:
            if self._cache is None:
                from ..ingest import iter_partitions

                self._cache = list(iter_partitions(
                    self.child,
                    range(self.child.output_partitioning()
                          .num_partitions)))
            return self._cache

    def _materialize_parts(self):
        """Materialize once and sort each batch by destination partition
        ONCE (not once per output partition): partition p is then a
        contiguous slice of the permutation. [(batch, perm, counts)]

        With the ingest pipeline on, the per-batch host syncs are
        DEFERRED: every batch's sort is dispatched back-to-back and the
        count scalars resolve in one ``jax.device_get`` at the end, so
        the device never waits on the host between batches (hash
        repartitions don't read the row offset at all — only
        round-robin does, and it needs the per-batch row count on
        host). ``BALLISTA_PREFETCH_BATCHES=0`` restores the serial
        sync-per-batch loop."""
        with self._mat_lock:
            return self._materialize_parts_locked()

    def _materialize_parts_locked(self):
        if getattr(self, "_parts", None) is None:
            from ..ingest import prefetch_batches

            def build():
                tw = self.trace_twin()  # don't pin materialized batches
                n_out = tw.num_partitions

                def sort_by_pid(b: ColumnBatch, offset):
                    pids = tw.partition_ids(b, offset)
                    d = jnp.where(b.selection, pids, n_out)  # dead last
                    idx = jnp.arange(b.capacity, dtype=jnp.int32)
                    _, perm = jax.lax.sort((d, idx), num_keys=1,
                                           is_stable=True)
                    counts = jnp.bincount(d, length=n_out + 1)[:n_out]
                    return perm, counts

                return sort_by_pid

            mask_fn = self.governed_jit(("repart.sort_by_pid",), build)
            pipelined = prefetch_batches() > 0 and self.hash_exprs
            batches = self._materialize()
            if pipelined:
                from ..ingest import parallel_map

                # offset is unread by hash partitioning, so batches are
                # independent: the first sorts inline (the governed
                # entry traces exactly once), the rest dispatch from
                # pool workers — independent XLA executions genuinely
                # overlap across cores — and every count scalar
                # resolves in ONE device_get
                zero = jnp.int32(0)
                pairs = ([mask_fn(batches[0], zero)] if batches else [])
                pairs += parallel_map(lambda b: mask_fn(b, zero),
                                      batches[1:])
                from ..observability import trace_span

                with trace_span("device.block", site="repart.counts",
                                n=len(pairs)):
                    resolved = jax.device_get([c for _, c in pairs])
                parts = [(b, perm, np.asarray(c))
                         for b, (perm, _), c in zip(batches, pairs,
                                                    resolved)]
            else:
                from ..observability import trace_span

                parts = []
                offset = 0
                for batch in batches:
                    perm, counts = mask_fn(batch, jnp.int32(offset))
                    # offset-dependent batches serialize: one sync per
                    # batch, each attributed to the blocked lane
                    with trace_span("device.block", site="repart.counts",
                                    n=1):
                        host_counts = np.asarray(counts)
                    parts.append((batch, perm, host_counts))
                    offset += batch.num_rows_host()
            self._parts = parts
        return self._parts

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        """Yields ONE COMPACTED batch: rows of the requested partition are
        gathered to the front of a capacity that fits, so a partitioned
        consumer (e.g. a co-partitioned join) does 1/N the work per
        partition instead of re-touching full-capacity masked batches.
        Per-source fragments are coalesced so a multi-file scan times N
        buckets doesn't fan out into source*N fragments, each paying
        per-batch dispatch and assembly downstream. Mirrors the
        distributed path, where shuffle files are mask-compacted on IPC
        write."""
        yield from self._execute_fragments(partition, 0, None)

    def execute_fragments(self, partition: int, frag_lo: int,
                          frag_hi: int) -> Iterator[ColumnBatch]:
        """``execute(partition)`` restricted to source fragments
        ``[frag_lo, frag_hi)`` — the read unit standalone adaptive skew
        splitting carves a heavy partition by (fragments play the role
        shuffle producers play in the cluster path)."""
        yield from self._execute_fragments(partition, frag_lo, frag_hi)

    def num_fragments(self) -> int:
        return len(self._materialize_parts())

    def observed_partition_rows(self):
        """Post-materialization row histogram: ``(rows_per_partition,
        rows[partition][fragment])`` — the standalone stand-in for the
        cluster's shuffle byte histogram (bytes = rows x schema row
        width, estimated by the caller)."""
        parts = self._materialize_parts()
        per = [[int(counts[q]) for _, _, counts in parts]
               for q in range(self.num_partitions)]
        return [sum(row) for row in per], per

    def _execute_fragments(self, partition: int, frag_lo: int,
                           frag_hi) -> Iterator[ColumnBatch]:
        pieces = []
        for batch, perm, counts in self._materialize_parts()[
                frag_lo:frag_hi]:
            n = int(counts[partition])
            start = int(counts[:partition].sum())
            # never exceed the source capacity: a longer slice would
            # silently clamp. Bucketed, so unevenly-filled output
            # partitions land on the canonical ladder
            cap = min(bucket_capacity(n), batch.capacity)
            idx = perm[start:start + cap]
            if int(idx.shape[0]) < cap:  # tail partition: pad the gather
                idx = jnp.pad(idx, (0, cap - int(idx.shape[0])))

            def build(_cap=cap):
                def take_front(b, idx, n):
                    live = jnp.arange(_cap, dtype=jnp.int32) < n
                    return take_batch(b, idx, live)

                return take_front

            take = self.governed_jit(("repart.take", cap), build)
            pieces.append(take(batch, idx, jnp.int32(n)))
        if len(pieces) == 1:
            yield pieces[0]
        elif pieces:
            out = concat_batches(self.output_schema(), pieces)
            # concat of ladder-sized pieces isn't itself a ladder rung
            # (128+64=192); pad up so downstream per-capacity jit caches
            # reuse one compiled program across output partitions
            target = bucket_capacity(out.capacity)
            if target != out.capacity:
                out = pad_batch(out, target)
            yield out

    def display(self) -> str:
        k = "hash" if self.hash_exprs else "round-robin"
        return f"RepartitionExec: {k} into {self.num_partitions}"


class EmptyExec(PhysicalPlan):
    """Zero- or one-row empty relation (reference: EmptyExecNode,
    ballista.proto:356-360)."""

    def __init__(self, produce_one_row: bool = False):
        self.produce_one_row = produce_one_row

    def output_schema(self) -> Schema:
        return Schema([])

    def with_new_children(self, children):
        return self

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        n = 1 if self.produce_one_row else 0
        sel = np.zeros(8, dtype=bool)
        sel[:n] = True
        yield ColumnBatch(
            Schema([]), [], jnp.asarray(sel), jnp.asarray(np.int32(n))
        )

    def display(self) -> str:
        return "EmptyExec"
