"""Physical plan base classes + batch utilities.

Execution model: ``execute(partition)`` yields ColumnBatches (host-driven
volcano at batch granularity), but *pipeline* operators (filter/projection/
partial-agg input chains) are traced together and jitted, so a whole chain
runs as ONE fused XLA program per batch — the TPU-native answer to the
reference's per-operator Rust volcano streams (reference:
rust/core/src/execution_plans/query_stage.rs:29-85 executes DataFusion
streams operator-by-operator).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, ColumnBatch
from ..compile import bucket_capacity, governed
from ..datatypes import Schema
from ..errors import ExecutionError
from ..observability.metrics import (MetricsSet, instrument_execute,
                                     metrics_enabled)


@dataclass(frozen=True)
class Partitioning:
    """Output partitioning descriptor."""

    kind: str  # "unknown" | "round_robin" | "hash"
    num_partitions: int
    hash_columns: tuple = ()


# Split-call donation convention (cache/donation.py): the batch treedef
# is static (arg 0), the column/validity/selection leaves are the
# donated payload (arg 1), num_rows rides as a plain argument (arg 2) —
# never donated, see PhysicalPlan.governed_call.
DONATING_JIT_KWARGS = {"static_argnums": (0,), "donate_argnums": (1,)}


def _donating_build(build):
    """Wrap a ``build()`` producing ``run(batch, *extra)`` into one
    producing the split-call form ``run(treedef, payload, num_rows,
    *extra)`` that reconstructs the batch inside the trace. num_rows is
    the LAST flattened leaf (columnar._flatten_batch), so unflatten
    appends it to the payload."""

    def build_donating():
        run = build()

        def run_split(treedef, payload, num_rows, *extra):
            batch = jax.tree_util.tree_unflatten(
                treedef, list(payload) + [num_rows])
            return run(batch, *extra)

        return run_split

    return build_donating


class PhysicalPlan:
    """Base physical operator.

    Every subclass that overrides ``execute`` is transparently
    instrumented (``__init_subclass__`` below): each call records
    ``output_rows``/``output_batches``/``elapsed_compute`` on the
    operator's :class:`MetricsSet` with zero per-operator boilerplate.
    Operators add their own domain counters (compaction, shuffle bytes,
    expand re-runs) via ``self.metrics()``.
    """

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        exec_fn = cls.__dict__.get("execute")
        if exec_fn is not None:
            cls.execute = instrument_execute(exec_fn)

    def metrics(self) -> MetricsSet:
        """The operator's MetricsSet (lazily created). Plain instance
        state, same benign-race policy as the adaptive counters below:
        concurrent partition execution can interleave updates and lose
        an increment, which skews a displayed number, never a result."""
        m = getattr(self, "_metrics", None)
        if m is None:
            m = self._metrics = MetricsSet()
        return m

    # -- compile governor ---------------------------------------------------

    def compile_signature(self) -> tuple:
        """Value-signature of everything this operator's traced closures
        read from instance state. Governed jit keys include it, so two
        instances with equal signatures (e.g. the same operator before
        and after an adaptive re-plan) share one compiled entry. The
        default covers operators whose ``display()`` renders their full
        configuration; operators with trace-relevant state beyond that
        override ``_signature_parts``."""
        sig = getattr(self, "_compile_sig", None)
        if sig is None:
            sig = self._compile_sig = (
                (type(self).__name__,) + self._signature_parts()
            )
        return sig

    def _signature_parts(self) -> tuple:
        return (self.display(), self.output_schema())

    def governed_jit(self, subkey: tuple, build, **kw):
        """Process-wide compiled function for this operator under
        ``subkey`` (namespace first); compiles it triggers are
        attributed to this operator's metrics. Replaces the per-instance
        ``self._jit_*`` dicts, which adaptive re-planning (new operator
        instances) used to throw away. Operator entries are AOT-eligible
        (compile/aot.py): with ``BALLISTA_FUSION_AOT_DIR`` set, whole
        programs serialize after first use and fresh processes
        deserialize instead of re-tracing; entries whose call shapes the
        AOT layer cannot fingerprint disable themselves safely."""
        key = (subkey[0], self.compile_signature()) + tuple(subkey[1:])
        metrics = self.metrics() if metrics_enabled() else None
        kw.setdefault("aot", True)
        return governed(key, build, metrics=metrics, **kw)

    def governed_call(self, subkey: tuple, build, batch: ColumnBatch,
                      *extra):
        """Run the governed program under ``subkey`` on ``batch``,
        donating the batch's device buffers when it is transient
        (single-consumer intermediate, cache/donation.py) and donation
        is enabled. The donating variant is a SEPARATE governed entry
        (``<namespace>.don``) because ``donate_argnums`` is
        incompatible with AOT attachment, and because its call
        convention splits the batch: the treedef rides as a static
        argument, column/validity/selection leaves are the donated
        payload, and ``num_rows`` stays an ordinary argument —
        MetricsSet.record_output_batch holds that scalar in
        ``_pending_rows`` long after the batch body is consumed, so
        donating it would hand ``_resolve_rows`` deleted buffers."""
        from ..cache.donation import (consume_transient, donation_enabled,
                                      record_donation)

        if donation_enabled() and consume_transient(batch):
            fn = self.governed_jit(
                (subkey[0] + ".don",) + tuple(subkey[1:]),
                _donating_build(build),
                jit_kwargs=dict(DONATING_JIT_KWARGS), aot=False)
            leaves, treedef = jax.tree_util.tree_flatten(batch)
            payload, num_rows = tuple(leaves[:-1]), leaves[-1]
            record_donation(sum(int(getattr(x, "nbytes", 0))
                                for x in payload))
            return fn(treedef, payload, num_rows, *extra)
        return self.governed_jit(subkey, build)(batch, *extra)

    def trace_twin(self) -> "PhysicalPlan":
        """Config-only shallow clone for governed closures to capture.

        Governed entries outlive operator instances, so a closure over
        ``self`` would pin the whole plan subtree — cached scan batches,
        repartition materializations, join build-side device buffers —
        for as long as the compiled entry lives. The twin carries
        everything traced closures actually read (mode/exprs/schemas/
        evaluators) while ``_detach`` severs children and data caches.
        Closures passed to ``governed_jit`` must reference the twin,
        never ``self``."""
        tw = getattr(self, "_trace_twin", None)
        if tw is None:
            import copy

            tw = copy.copy(self)
            self._trace_twin = tw
            tw._trace_twin = tw  # twin of the twin is itself
            tw._metrics = None
            tw._detach()
        return tw

    def _detach(self) -> None:
        """Sever plan-subtree and materialized-state references on a
        trace twin (runs on the COPY). Default: children become
        schema-only leaves. Operators whose traced closures read other
        heavy members override and extend."""
        if getattr(self, "child", None) is not None:
            self.child = SchemaLeaf(self.child.output_schema())
        if getattr(self, "_fused_fn", None) is not None:
            self._fused_fn = None  # no entry->twin->entry cycles
        if getattr(self, "_fused_don_fn", None) is not None:
            self._fused_don_fn = None

    def output_schema(self) -> Schema:
        raise NotImplementedError

    def output_partitioning(self) -> Partitioning:
        cs = self.children()
        if cs:
            return cs[0].output_partitioning()
        return Partitioning("unknown", 1)

    def children(self) -> List["PhysicalPlan"]:
        return []

    def with_new_children(self, children: List["PhysicalPlan"]) -> "PhysicalPlan":
        raise NotImplementedError(type(self).__name__)

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        raise NotImplementedError(type(self).__name__)

    def estimated_rows(self) -> Optional[int]:
        """Crude output-cardinality estimate for planning decisions (e.g.
        picking a partitioned join when the build side is large). Filters
        and joins deliberately over-estimate (pass-through / sum); None =
        unknown."""
        ests = [c.estimated_rows() for c in self.children()]
        # any unknown child makes the total unknown: silently dropping it
        # would UNDER-estimate, and callers rely on over-estimation
        if not ests or any(e is None for e in ests):
            return None
        return sum(ests)

    def display(self) -> str:
        return type(self).__name__

    def pretty(self, indent: int = 0) -> str:
        out = "  " * indent + self.display() + "\n"
        for c in self.children():
            out += c.pretty(indent + 1)
        return out

    def pretty_metrics(self, indent: int = 0) -> str:
        """Plan text annotated with live metrics (EXPLAIN ANALYZE).
        Operators fused into a pipeline chain show no numbers of their
        own — the chain's totals sit on its outermost operator."""
        ann = self.metrics().summary()
        out = ("  " * indent + self.display()
               + (f", metrics=[{ann}]" if ann else "") + "\n")
        for c in self.children():
            out += c.pretty_metrics(indent + 1)
        return out


class SchemaLeaf(PhysicalPlan):
    """Schema-only placeholder standing in for a severed child on a
    trace twin (mirrors mesh_agg's _SchemaOnly, but importable from
    base without cycles). Never executed."""

    def __init__(self, schema: Schema):
        self._schema = schema

    def output_schema(self) -> Schema:
        return self._schema

    def with_new_children(self, children):
        return self


class PipelineOp(PhysicalPlan):
    """Operator whose work is a pure batch->batch device transform.

    Chains of PipelineOps are fused into one jitted function; the chain's
    non-pipeline root feeds batches through it.
    """

    child: PhysicalPlan
    # True for transforms that can kill rows (FilterExec): the fused
    # chain's output is then adaptively compacted, so a selective filter
    # hands every downstream operator a capacity sized to the survivors
    # instead of the scan's (q15's 3-month window keeps 7.5% of lineitem
    # but aggregation paid full-capacity passes). Same policy/guards as
    # post-join compaction (maybe_compact: >=4x shrink, sync-cost-aware).
    compactable = False

    def device_transform(self, batch: ColumnBatch) -> ColumnBatch:
        raise NotImplementedError(type(self).__name__)

    def children(self) -> List[PhysicalPlan]:
        return [self.child]

    # fused execution ------------------------------------------------------

    def _pipeline_chain(self):
        """(transforms outer-to-inner reversed into apply order, source op)."""
        chain: List[PipelineOp] = []
        node: PhysicalPlan = self
        while isinstance(node, PipelineOp):
            chain.append(node)
            node = node.child
        chain.reverse()  # innermost transform first
        return chain, node

    def _fused_governed(self):
        """Governed fused transform for this operator's pipeline chain.
        Keyed on the chain's operator signatures, so a re-planned stage
        (fresh instances, same logical chain) reuses the compiled
        programs; compile time lands on this operator's metrics."""
        fused = getattr(self, "_fused_fn", None)
        if fused is None:
            chain, _ = self._pipeline_chain()

            def build():
                # twins: device_transform reads exprs/evaluators, never
                # .child — capturing the live ops would pin the source
                # (and its cached batches) in the process-wide cache
                twins = [op.trace_twin() for op in chain]

                def apply_all(batch):
                    for op in twins:
                        batch = op.device_transform(batch)
                    return batch

                return apply_all

            key = ("pipeline.fused",
                   tuple(op.compile_signature() for op in chain))
            metrics = self.metrics() if metrics_enabled() else None
            fused = self._fused_fn = governed(key, build, metrics=metrics,
                                              aot=True)
        return fused

    def _fused_governed_donating(self):
        """Donating twin of :meth:`_fused_governed` (split-call
        convention, see ``governed_call``): used per-batch when the
        incoming batch is transient. Shares the chain-signature key
        shape under the ``pipeline.fused.don`` namespace; not
        AOT-eligible (donate_argnums)."""
        fused = getattr(self, "_fused_don_fn", None)
        if fused is None:
            chain, _ = self._pipeline_chain()

            def build():
                twins = [op.trace_twin() for op in chain]

                def apply_all(batch):
                    for op in twins:
                        batch = op.device_transform(batch)
                    return batch

                return apply_all

            key = ("pipeline.fused.don",
                   tuple(op.compile_signature() for op in chain))
            metrics = self.metrics() if metrics_enabled() else None
            fused = self._fused_don_fn = governed(
                key, _donating_build(build), metrics=metrics,
                jit_kwargs=dict(DONATING_JIT_KWARGS))
        return fused

    def execute(self, partition: int) -> Iterator[ColumnBatch]:
        from ..cache.donation import (consume_transient, donation_enabled,
                                      mark_transient, record_donation)

        chain, source = self._pipeline_chain()
        fused = self._fused_governed()
        # Adaptive: a filter's selectivity is stationary within a query,
        # so after 2 consecutive batches that decline to compact, stop
        # paying the per-batch live-count sync for the operator's
        # lifetime (it would otherwise serialize host scan parsing
        # against device compute batch-by-batch for zero benefit on
        # unselective filters). The learned capacity floor keeps later
        # batches from compacting to ever-different power-of-two rungs,
        # bounding downstream per-capacity jit compiles to ~one extra.
        #
        # BENIGN RACE: _compact_misses/_compact_floor (and JoinExec's
        # _expand_cap_floor) are unsynchronized instance state mutated
        # here; executor worker threads running partitions of one
        # operator concurrently can interleave updates. Outcomes stay
        # correct — these only steer heuristics — but learned values can
        # thrash; the same policy covers the MetricsSet counters below.
        compact = any(op.compactable for op in chain)
        for batch in source.execute(partition):
            # the governor records the compile-vs-execute split: a call
            # that triggers an XLA compile lands its duration on this
            # operator's elapsed_compile / compile_count metrics
            if donation_enabled() and consume_transient(batch):
                # single-consumer scan/concat output: hand XLA the
                # buffers so the fused program writes in place instead
                # of allocating a second copy of the batch
                leaves, treedef = jax.tree_util.tree_flatten(batch)
                payload, num_rows = tuple(leaves[:-1]), leaves[-1]
                record_donation(sum(int(getattr(x, "nbytes", 0))
                                    for x in payload))
                out = self._fused_governed_donating()(
                    treedef, payload, num_rows)
            else:
                out = fused(batch)
            if compact and getattr(self, "_compact_misses", 0) < 2:
                res = maybe_compact(
                    out, floor=getattr(self, "_compact_floor", 8))
                if res is out:
                    self._compact_misses = \
                        getattr(self, "_compact_misses", 0) + 1
                else:
                    self._compact_misses = 0
                    self._compact_floor = max(
                        getattr(self, "_compact_floor", 8), res.capacity)
                    self.metrics().add_counter("compact_count")
                out = res
            # fresh XLA output (or fresh compaction), exactly one
            # downstream consumer: donation-eligible
            mark_transient(out)
            yield out


# ---------------------------------------------------------------------------
# Batch utilities shared by operators
# ---------------------------------------------------------------------------


def concat_batches(schema: Schema, batches: List[ColumnBatch]) -> ColumnBatch:
    """Concatenate batches (device) into one larger-capacity batch.

    utf8 columns whose batches carry DIFFERENT dictionaries (e.g. shuffle
    partitions from independent producers) are unified: a sorted union
    dictionary is built host-side and each batch's codes are remapped.
    Host-level only — never call inside a jit trace.

    Output capacity is the exact SUM of the inputs, deliberately NOT
    padded up to a bucket-ladder rung: inputs are already ladder-sized,
    so concat capacities quantize to rung sums (e.g. k * 2^20 for a
    k-chunk scan) — a bounded shape family — while padding to the next
    rung would make the downstream sort/aggregate touch up to ~2x the
    rows (q1's 6-chunk concat would grow 6M -> 8.4M), blowing the warm-
    throughput budget for a marginal compile saving. RepartitionExec is
    the exception (it pads): its fragment concats produce genuinely
    irregular sums across partitions of one shuffle.
    """
    if not batches:
        raise ExecutionError("concat of zero batches")
    if len(batches) == 1:
        return batches[0]
    cols: List[Column] = []
    for i, f in enumerate(schema.fields):
        values_list = [b.columns[i].values for b in batches]
        dicts = [b.columns[i].dictionary for b in batches]
        dict_ = next((d for d in dicts if d is not None), None)
        if dict_ is not None and any(
            d is not None and d is not dict_ for d in dicts
        ):
            # unify through the dictionary registry: shared-entry
            # dictionaries resolve to a no-op or a cached int32 remap
            # (a device gather); unregistered dictionaries fall back
            # to the legacy sorted union inside the registry module
            from ..observability import trace_span
            from .. import columnar_registry

            with trace_span("host.dictionary", site="concat.unify",
                            column=f.name, n_dicts=len(dicts)):
                target, remaps = columnar_registry.unify(dicts)
                dict_ = target
                remapped = []
                for v, remap in zip(values_list, remaps):
                    if remap is None:
                        remapped.append(v)
                        continue
                    remapped.append(
                        jnp.take(jnp.asarray(remap),
                                 v.astype(jnp.int32), mode="clip")
                    )
                values_list = remapped
        vals = jnp.concatenate(values_list)
        vs = [b.columns[i].validity for b in batches]
        if any(v is not None for v in vs):
            validity = jnp.concatenate(
                [
                    v if v is not None else jnp.ones((b.capacity,), jnp.bool_)
                    for v, b in zip(vs, batches)
                ]
            )
        else:
            validity = None
        cols.append(Column(vals, f.dtype, validity, dict_))
    selection = jnp.concatenate([b.selection for b in batches])
    num_rows = sum([b.num_rows for b in batches])
    out = ColumnBatch(schema, cols, selection, num_rows)
    # fresh jnp.concatenate buffers with exactly one consumer (the
    # aggregation/sort program the concat feeds): donation-eligible.
    # The len == 1 pass-through above deliberately inherits the input's
    # own transiency instead — pinned cache batches stay pinned.
    from ..cache.donation import mark_transient

    mark_transient(out)
    return out


# Measured cost of a blocking scalar device->host read (seconds). When the
# accelerator is remote (e.g. tunneled), one sync costs a network
# round-trip — far more than speculative compaction ever saves — so
# maybe_compact only pays for a sync while syncs are known to be cheap.
_SYNC_COST: List[float] = []
_SYNC_COST_LIMIT = 0.005


def _record_sync_cost(batch: ColumnBatch) -> None:
    """Measure a PURE round-trip: re-fetch a scalar that is already on
    its way/ready, so pending compute doesn't inflate the figure."""
    import time as _time

    t0 = _time.perf_counter()
    int(batch.num_rows)
    _SYNC_COST.append(_time.perf_counter() - t0)


def maybe_compact(batch: ColumnBatch, shrink_factor: int = 4,
                  known_rows: Optional[int] = None,
                  floor: int = 8) -> ColumnBatch:
    """Shrink a sparse batch: when live rows fill under 1/shrink_factor
    of the capacity, gather them to the front of a smaller batch. One
    sort+gather now buys every downstream operator a smaller shape —
    decisive after selective joins/filters in long pipelines.

    Pass ``known_rows`` when the live count is already on host (e.g. the
    join expand loop just synced its overflow check) — then this never
    blocks. Without it, the live-count sync is only paid while measured
    sync cost is low; on a remote accelerator the first call measures
    the round-trip and all later speculative syncs are skipped."""
    if known_rows is not None:
        n = known_rows
    else:
        if _SYNC_COST and _SYNC_COST[-1] > _SYNC_COST_LIMIT:
            return batch  # a sync costs more than compaction saves
        first = not _SYNC_COST
        n = int(batch.num_rows)
        if first:
            _record_sync_cost(batch)  # pure-RTT measurement
    cap = batch.capacity
    # compaction targets land on the bucket ladder: a selective filter's
    # survivors must not mint a fresh per-selectivity capacity downstream
    new_cap = max(bucket_capacity(n), floor, 8)
    if new_cap * shrink_factor > cap:
        return batch

    def build(_new=new_cap):
        def compact(b: ColumnBatch) -> ColumnBatch:
            perm = compact_perm(b.selection, _new)
            live = jnp.arange(_new, dtype=jnp.int32) < b.num_rows
            return take_batch(b, perm, live)

        return compact

    return governed(("batch.compact", new_cap), build, aot=True)(batch)


def pad_batch(batch: ColumnBatch, capacity: int) -> ColumnBatch:
    """Grow a batch's capacity with dead padding rows (device)."""
    if capacity <= batch.capacity:
        return batch
    extra = capacity - batch.capacity
    cols = []
    for col in batch.columns:
        vals = jnp.concatenate(
            [col.values, jnp.zeros((extra,), col.values.dtype)])
        validity = (
            jnp.concatenate([col.validity, jnp.zeros((extra,), jnp.bool_)])
            if col.validity is not None else None)
        cols.append(Column(vals, col.dtype, validity, col.dictionary))
    selection = jnp.concatenate(
        [batch.selection, jnp.zeros((extra,), jnp.bool_)])
    return ColumnBatch(batch.schema, cols, selection, batch.num_rows)


def compact_perm(selection: jax.Array, size: int) -> jax.Array:
    """Gather permutation putting live rows first, in order: stable
    front-compaction via static-size nonzero (cumsum + scatter, O(N)) —
    a full lax.sort costs more than the compaction saves on large
    capacities. Traced."""
    return jnp.nonzero(selection, size=size, fill_value=0)[0] \
        .astype(jnp.int32)


def take_batch(batch: ColumnBatch, perm: jax.Array, live: jax.Array) -> ColumnBatch:
    """Reorder a batch by ``perm``; ``live`` is the selection after reorder."""
    cols = []
    for col in batch.columns:
        vals = jnp.take(col.values, perm, axis=0)
        validity = (
            jnp.take(col.validity, perm, axis=0) if col.validity is not None else None
        )
        cols.append(Column(vals, col.dtype, validity, col.dictionary))
    return ColumnBatch(
        batch.schema, cols, live, jnp.sum(live).astype(jnp.int32)
    )
