"""ballista-tpu: a TPU-native distributed SQL/DataFrame query engine.

A from-scratch re-design of the capabilities of the reference engine
(Ballista, a Rust/Arrow distributed query engine "largely inspired by Apache
Spark" — reference: README.md:57-70, docs/architecture.md:5-46) for TPU
hardware: query stages compile to single XLA programs over columnar device
buffers, shuffles ride ICI ``all_to_all`` inside a slice and a host data
plane across slices, and the scheduler/executor control plane speaks gRPC.

Layering (mirrors reference SURVEY layer map, bottom-up):
  columnar/datatypes  - fixed-capacity struct-of-arrays batches (L0/L1)
  expr/logical/sql    - expression AST, logical plan, SQL frontend (L1/L5)
  physical/kernels    - XLA operator kernels + physical plans (L1)
  proto/serde         - wire contract (L2)
  distributed         - scheduler, executor, state, shuffle (L3/L4)
  client              - BallistaContext / DataFrame API (L5/L6)
"""

import os as _os
import sys as _sys

import jax as _jax

# pyarrow >= 25 defaults its memory pool to mimalloc, which intermittently
# corrupts under this engine's thread mix (executor thread pools + grpc +
# GIL-released ctypes scans): observed as flaky SIGSEGV inside pa.array
# during shuffle writes, reproducibly gone under jemalloc or the system
# allocator. Pin jemalloc BEFORE pyarrow's first import (the env var is
# only read then); if the application imported pyarrow already, flip the
# default pool at runtime instead. An explicit ARROW_DEFAULT_MEMORY_POOL
# from the user always wins.
if "ARROW_DEFAULT_MEMORY_POOL" not in _os.environ:
    if "pyarrow" in _sys.modules:
        try:
            import pyarrow as _pa

            _pa.set_memory_pool(_pa.jemalloc_memory_pool())
        except Exception:  # noqa: BLE001 - jemalloc absent in this build
            pass
    else:
        _os.environ["ARROW_DEFAULT_MEMORY_POOL"] = "jemalloc"
        # mark the choice as OURS by recording the VALUE we set:
        # io/ipc.py's runtime fallback must not override a pool the USER
        # explicitly selected, and child processes inherit this marker —
        # so it only counts as ours while ARROW_DEFAULT_MEMORY_POOL still
        # equals what we wrote (a user override in the child wins)
        _os.environ["_BALLISTA_SET_ARROW_POOL"] = "jemalloc"

# Exact decimal arithmetic uses scaled int64 columns; without x64, JAX would
# silently downcast them to int32. Float64 device arrays are never created
# (the engine stores logical f64 as f32 on device; see datatypes.py).
_jax.config.update("jax_enable_x64", True)

# Honor JAX_PLATFORMS even when an interpreter-level sitecustomize already
# imported jax with a different value baked in (the env var is only read at
# import time; the config update below is what actually switches platform).
if _os.environ.get("JAX_PLATFORMS"):
    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

# Persistent XLA compilation cache: keyed by HLO hash, so identical operator
# pipelines hit the disk cache across queries, operator instances, AND
# processes (per-shape recompilation was the dominant first-run cost; see
# benchmarks/RESULTS.md). Opt out with BALLISTA_XLA_CACHE="".


def _machine_tag() -> str:
    """XLA's CPU cache key does NOT include host CPU features, so AOT
    results compiled on one machine load on another and can SIGILL (they
    at minimum spam loader warnings). Version the cache dir by a
    fingerprint of the host's CPU flags so a moved home dir / changed
    host gets a fresh cache instead of stale native code."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    import hashlib

                    return hashlib.sha1(line.encode()).hexdigest()[:10]
    except OSError:
        pass
    return "generic"


_cache_dir = _os.environ.get(
    "BALLISTA_XLA_CACHE",
    _os.path.join(_os.path.expanduser("~"), ".cache",
                  f"ballista-tpu-xla-{_machine_tag()}"),
)
if _cache_dir:
    try:
        _min_compile_secs = float(
            _os.environ.get("BALLISTA_XLA_CACHE_MIN_COMPILE_SECS", "0"))
    except ValueError:
        _min_compile_secs = 0.0
    try:
        _os.makedirs(_cache_dir, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        # default 0: cache EVERY kernel. The old 0.1s floor silently
        # excluded small kernels from the disk cache, so they recompiled
        # in every fresh process — exactly the per-shape cold-path cost
        # the shape-bucket ladder exists to amortize. Raise via
        # BALLISTA_XLA_CACHE_MIN_COMPILE_SECS if cache-dir churn matters
        # more than cold-start latency.
        _jax.config.update("jax_persistent_cache_min_compile_time_secs",
                           _min_compile_secs)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except (OSError, AttributeError):  # unwritable dir / older jax
        pass

BALLISTA_TPU_VERSION = "0.2.0"

from .datatypes import (  # noqa: E402
    Boolean,
    DataType,
    Date32,
    Decimal,
    Field,
    Float32,
    Float64,
    Int32,
    Int64,
    Schema,
    Utf8,
    schema,
)
from .columnar import Column, ColumnBatch, Dictionary  # noqa: E402
from .expr import (  # noqa: E402
    avg,
    case,
    col,
    count,
    count_distinct,
    date_lit,
    lit,
    max_,
    min_,
    sum_,
)
from .errors import BallistaError  # noqa: E402


def print_version() -> None:
    print(f"ballista-tpu version: {BALLISTA_TPU_VERSION}")
