"""Multi-host mesh: one SPMD program spanning executor processes.

The reference scales across hosts by moving materialized partitions
through its Flight data plane (reference: docs/architecture.md:41-46,
shuffle_reader.rs:77-99). The TPU-native equivalent keeps the exchange
INSIDE the accelerator fabric: executor processes join one
``jax.distributed`` runtime (ICI within a slice, DCN/Gloo across
hosts), build a single global `Mesh` over every process's devices, and
run the same shuffle/aggregation/join SPMD programs the single-host
mesh path uses — `lax.all_to_all` rows cross host boundaries without
touching the host data plane.

Multi-controller model: every process runs the SAME program (standard
JAX multi-host). The scheduler hands a fused task to the group's
process 0, which broadcasts the task bytes to peers over the group
channel; all processes enter the SPMD program together, and
replicated outputs let process 0 report the result.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

import jax

_initialized = False


def init_group(coordinator: str, num_processes: int, process_id: int,
               local_device_count: Optional[int] = None) -> None:
    """Join this process to the group's jax.distributed runtime.

    Must run before any other jax call touches the backend. On CPU
    fleets ``local_device_count`` forces N virtual devices per process
    (tests/CI); on TPU hosts the platform provides real local devices.
    """
    global _initialized
    if _initialized:
        return
    import os

    if local_device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{local_device_count}"
            ).strip()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def global_mesh(axis: str = "data"):
    """Mesh over EVERY process's devices (global, ordered by process)."""
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (axis,))


def local_slot_range(mesh) -> List[int]:
    """Global slot indices owned by THIS process (its addressable
    devices' positions in the mesh)."""
    devs = list(mesh.devices.flat)
    local = set(d.id for d in jax.local_devices())
    return [i for i, d in enumerate(devs) if d.id in local]


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def host_max(arr) -> int:
    """max over a (possibly cross-process sharded) array, readable on
    every process. ``np.asarray`` on a global array whose shards live on
    other processes fails; a jitted max produces a replicated scalar
    every process holds locally. Works unchanged in single-process.
    (One governed entry so the retry hot paths hit its cache.)"""
    from ..compile import governed

    def build():
        import jax.numpy as jnp

        return jnp.max

    return int(governed(("misc.host_max",), build)(arr))


def replicate_stacked(stacked, mesh):
    """[n_dev, ...]-sharded pytree -> fully-replicated copy every
    process can read (an all_gather per leaf). Used to hand a fused
    stage's (small) final output to the group leader for
    materialization. Bounded governed namespace: keys hold
    identity-hashed per-query dictionaries via treedefs."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from ..compile import governed
    from .mesh import shard_map

    axis = mesh.axis_names[0]

    def build():
        @partial(shard_map, mesh=mesh, in_specs=(P(axis),), out_specs=P(),
                 check_vma=False)
        def rep(st):
            return jax.tree.map(
                lambda x: jax.lax.all_gather(x[0], axis), st
            )

        return rep

    from ..compile import MESH_NS_CAP

    key = ("mesh.replicate", mesh, jax.tree.structure(stacked),
           tuple(np.shape(x) for x in jax.tree.leaves(stacked)))
    return governed(key, build, cap=MESH_NS_CAP)(stacked)


def stack_local_to_global(slot_batches: Sequence, mesh):
    """Per-LOCAL-device pytrees -> one global stacked array sharded over
    the whole mesh. Mirrors mesh_input.stack_to_mesh but supplies only
    this process's shards; jax assembles the global view (other shards
    live on their owning processes)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    devices = [mesh.devices.flat[i] for i in local_slot_range(mesh)]
    assert len(devices) == len(slot_batches), (
        f"{len(slot_batches)} local slot batches for "
        f"{len(devices)} local devices"
    )
    n = mesh.devices.size
    sharding = NamedSharding(mesh, P(mesh.axis_names[0]))

    def build(*xs):
        shards = [
            jax.device_put(x[None, ...], d) for x, d in zip(xs, devices)
        ]
        return jax.make_array_from_single_device_arrays(
            (n,) + tuple(np.shape(xs[0])), sharding, shards
        )

    return jax.tree.map(build, *slot_batches)
