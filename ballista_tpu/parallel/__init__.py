"""In-mesh SPMD query execution over `jax.sharding.Mesh`.

This is the TPU-native fast path that replaces host shuffles when producer
and consumer stages run on chips of one slice: partitions shard over mesh
devices, pipelines run under ``shard_map`` as one SPMD XLA program, hash
repartition becomes an ICI ``all_to_all`` (kernels in mesh_shuffle.py), and
two-phase aggregation merges via ``all_gather`` — the design mapping called
out in SURVEY §5.7/§5.8 for the reference's Flight-based shuffle
(reference: rust/executor/src/flight_service.rs, rust/core/src/
execution_plans/shuffle_reader.rs).
"""

from .mesh import make_mesh, MeshQueryRunner  # noqa: F401
