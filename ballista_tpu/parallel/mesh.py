"""Mesh query runner: whole query stages as one SPMD XLA program.

Shards table partitions over the devices of a ``jax.sharding.Mesh`` and
runs scan-pipeline + two-phase aggregation with XLA collectives:

- per-device pipelines (filter/project/partial-agg) trace exactly like the
  single-chip operators;
- hash repartition = ICI ``all_to_all`` (kernels.mesh_shuffle);
- aggregate merge = ``all_gather`` of the partial group tables, final
  aggregation replicated (cheap: group tables are small).

This is the slice-internal fast path the SURVEY maps the reference's
Flight shuffle onto (SURVEY §5.7/§5.8); across hosts/slices the
distributed runtime's data plane takes over.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

try:  # jax >= 0.4.35 exports shard_map at top level
    from jax import shard_map
except ImportError:  # older jax: experimental location
    from jax.experimental.shard_map import shard_map  # type: ignore
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar import Column, ColumnBatch
from ..datatypes import Schema
from ..errors import ExecutionError
from ..kernels import mesh_shuffle

DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None, axis: str = DATA_AXIS) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise ExecutionError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:n]), (axis,))


def _stack_batches(schema: Schema, batches: List[ColumnBatch]):
    """[per-device ColumnBatch] -> stacked leaves [n_dev, cap] on host."""
    caps = {b.capacity for b in batches}
    if len(caps) != 1:
        raise ExecutionError(f"device batches must share capacity, got {caps}")
    from ..observability.tracing import trace_span

    cols = {}
    # the relayout round-trips every column through host memory — a
    # real blocking sync the profiler must attribute to device time
    with trace_span("device.block", site="mesh.stack",
                    n=len(batches)):
        for i, f in enumerate(schema.fields):
            cols[f.name] = np.stack(
                [np.asarray(b.columns[i].values) for b in batches]
            )
        sel = np.stack([np.asarray(b.selection) for b in batches])
    dicts = {
        f.name: batches[0].columns[i].dictionary
        for i, f in enumerate(schema.fields)
    }
    return cols, sel, dicts


class MeshQueryRunner:
    """Runs a per-device batch transform + merge under shard_map."""

    def __init__(self, mesh: Mesh, axis: str = DATA_AXIS):
        self.mesh = mesh
        self.axis = axis
        self.n_dev = mesh.devices.size

    def run_spmd(
        self,
        schema: Schema,
        batches: List[ColumnBatch],  # one per device
        device_fn: Callable,  # (cols dict, live) -> pytree of [*] arrays
        replicated_out: bool = True,
    ):
        """Shard the stacked batches over the mesh and run device_fn
        SPMD. device_fn may use lax collectives over the data axis."""
        cols, sel, dicts = _stack_batches(schema, batches)
        sharding = NamedSharding(self.mesh, P(self.axis))

        cols_dev = {
            k: jax.device_put(v, sharding) for k, v in cols.items()
        }
        sel_dev = jax.device_put(sel, sharding)

        out_spec = P() if replicated_out else P(self.axis)

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis)),
            out_specs=out_spec,
            check_vma=False,
        )
        def run(cols_blk, sel_blk):
            # shard_map gives [1, cap] blocks; squeeze the device axis
            cols1 = {k: v[0] for k, v in cols_blk.items()}
            live1 = sel_blk[0]
            out = device_fn(cols1, live1)
            if replicated_out:
                return out
            return jax.tree_util.tree_map(lambda x: x[None], out)

        # deliberately NOT governed: device_fn is an arbitrary caller
        # closure, so the only sound cache key is its identity — callers
        # pass fresh lambdas, giving a 0% hit rate while the cache would
        # pin the closures (and whatever they capture) process-wide. A
        # transient jit matches the utility-API lifetime.
        return jax.jit(run)(cols_dev, sel_dev), dicts  # jit-ok: transient

    # convenience: hash-repartition rows across the mesh ---------------------

    def shuffle_fn(self, key_col: str, dest_capacity: int):
        """Returns a traced helper usable inside device_fn:
        (cols, live) -> (cols', live', overflowed). ``overflowed`` is a
        traced bool — True when some device had more than dest_capacity
        rows for one destination, in which case rows were DROPPED and the
        caller must re-run with a larger capacity (check it host-side)."""
        axis = self.axis
        n_dev = self.n_dev

        def do_shuffle(cols: Dict[str, jax.Array], live: jax.Array):
            names = list(cols.keys())
            dest = mesh_shuffle.destination_ids(cols[key_col], live, n_dev)
            out_cols, out_live, counts = mesh_shuffle.all_to_all_rows(
                [cols[n] for n in names], live, dest, axis, n_dev,
                dest_capacity,
            )
            over = jnp.max(counts) > dest_capacity
            # any device overflowing poisons the global result
            overflowed = lax.pmax(over, axis)
            return dict(zip(names, out_cols)), out_live, overflowed

        return do_shuffle
