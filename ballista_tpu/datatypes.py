"""Arrow-flavored type system for ballista-tpu, designed for TPU storage.

The reference engine uses the Arrow type system directly (reference:
rust/core/proto/ballista.proto:611-800 defines Schema/Field/ArrowType
messages). We keep the same *logical* types but fix the *physical* device
representation up front, because XLA/TPU wants static dtypes and has no
efficient float64 or variable-length strings:

- ``Utf8``      -> dictionary-encoded int32 codes on device; the dictionary
                   (numpy object array of Python strings) stays host-side.
- ``Decimal``   -> scaled int64 ("value * 10^scale"), giving exact arithmetic
                   on TPU where f64 is emulated and slow. Sums of TPC-H money
                   columns stay well inside int64.
- ``Date32``    -> int32 days since Unix epoch (same as Arrow).
- ``Boolean``   -> bool_ on device.

Everything here is hashable/frozen so schemas can key jit caches.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Iterable, Optional, Sequence, Tuple

import numpy as np

from .errors import SchemaError


# ---------------------------------------------------------------------------
# DataType
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DataType:
    """Logical data type. ``kind`` is one of the KIND_* constants."""

    kind: str
    # Decimal only: digits after the point. Physical value = logical * 10**scale.
    scale: int = 0
    # FixedSizeList only: element type + fixed per-row length. Physical
    # representation is a (capacity, length) device array of the element's
    # physical dtype (SoA stays rectangular — no ragged buffers on TPU).
    element: Optional["DataType"] = None
    length: int = 0

    # -- constructors -------------------------------------------------------

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        if self.kind == "decimal":
            return f"Decimal(scale={self.scale})"
        if self.kind == "list":
            return f"FixedSizeList({self.element!r}, {self.length})"
        return self.kind.capitalize()

    # -- classification -----------------------------------------------------

    @property
    def is_numeric(self) -> bool:
        return self.kind in ("int32", "int64", "float32", "float64", "decimal")

    @property
    def is_integer(self) -> bool:
        return self.kind in ("int32", "int64")

    @property
    def is_floating(self) -> bool:
        return self.kind in ("float32", "float64")

    @property
    def is_string(self) -> bool:
        return self.kind == "utf8"

    @property
    def is_temporal(self) -> bool:
        return self.kind in ("date32", "timestamp_ns")

    # -- device representation ----------------------------------------------

    def device_dtype(self) -> np.dtype:
        """numpy dtype of the on-device physical column."""
        m = {
            "int32": np.int32,
            "int64": np.int64,
            "float32": np.float32,
            "float64": np.float32,  # TPU: f64 stored as f32 on device
            "decimal": np.int64,
            "boolean": np.bool_,
            "date32": np.int32,
            "timestamp_ns": np.int64,  # epoch nanoseconds
            "utf8": np.int32,  # dictionary codes
        }
        if self.kind == "list":
            return self.element.device_dtype()
        if self.kind not in m:
            raise SchemaError(f"no device representation for {self.kind}")
        return np.dtype(m[self.kind])


Int32 = DataType("int32")
Int64 = DataType("int64")
Float32 = DataType("float32")
Float64 = DataType("float64")
Boolean = DataType("boolean")
Utf8 = DataType("utf8")
Date32 = DataType("date32")
# Epoch-nanosecond timestamps (the reference's TOTIMESTAMP result type,
# reference: rust/core/proto/ballista.proto:104 TOTIMESTAMP)
TimestampNs = DataType("timestamp_ns")


def Decimal(scale: int = 2) -> DataType:
    return DataType("decimal", scale=scale)


def FixedSizeList(element: DataType, length: int) -> DataType:
    """ARRAY constructor result type (reference surface:
    rust/core/proto/ballista.proto:105 ARRAY -> DataFusion fixed-size
    list). Rectangular (capacity, length) physical layout."""
    if element.kind == "list":
        raise SchemaError("nested lists are not supported")
    return DataType("list", element=element, length=length)


_BY_NAME = {
    "int": Int64,
    "i32": Int32,
    "i64": Int64,
    "int32": Int32,
    "int64": Int64,
    "bigint": Int64,
    "integer": Int32,
    "f32": Float32,
    "f64": Float64,
    "float": Float32,
    "float32": Float32,
    "float64": Float64,
    "double": Float64,
    "bool": Boolean,
    "boolean": Boolean,
    "utf8": Utf8,
    "str": Utf8,
    "string": Utf8,
    "varchar": Utf8,
    "text": Utf8,
    "date": Date32,
    "date32": Date32,
    "timestamp": TimestampNs,
    "timestamp_ns": TimestampNs,
    "datetime": TimestampNs,
}


def dtype_from_name(name: str) -> DataType:
    """Parse a type name (as used in SQL DDL / config strings)."""
    key = name.strip().lower()
    if key.startswith("decimal"):
        # decimal(p, s) — precision ignored, scale kept
        if "(" in key:
            inner = key[key.index("(") + 1 : key.rindex(")")]
            parts = [p.strip() for p in inner.split(",")]
            scale = int(parts[1]) if len(parts) > 1 else 0
            return Decimal(scale)
        return Decimal(2)
    if key in _BY_NAME:
        return _BY_NAME[key]
    raise SchemaError(f"unknown type name: {name!r}")


# ---------------------------------------------------------------------------
# Field / Schema
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType
    nullable: bool = True

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        n = "" if self.nullable else " NOT NULL"
        return f"{self.name}: {self.dtype!r}{n}"


@dataclass(frozen=True)
class Schema:
    fields: Tuple[Field, ...]

    def __init__(self, fields: Iterable[Field]):
        object.__setattr__(self, "fields", tuple(fields))

    # -- access -------------------------------------------------------------

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def names(self) -> Tuple[str, ...]:
        return tuple(f.name for f in self.fields)

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise SchemaError(f"field {name!r} not in schema {self.names()}")

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise SchemaError(f"field {name!r} not in schema {self.names()}")

    def has_field(self, name: str) -> bool:
        return any(f.name == name for f in self.fields)

    def project(self, names: Sequence[str]) -> "Schema":
        return Schema([self.field(n) for n in names])

    def merge(self, other: "Schema") -> "Schema":
        return Schema(list(self.fields) + list(other.fields))

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        inner = ", ".join(repr(f) for f in self.fields)
        return f"Schema[{inner}]"


def schema(*pairs, nullable: bool = True) -> Schema:
    """Convenience: schema(("a", Int64), ("b", "utf8"), ...)."""
    fields = []
    for name, dt in pairs:
        if isinstance(dt, str):
            dt = dtype_from_name(dt)
        fields.append(Field(name, dt, nullable))
    return Schema(fields)


# ---------------------------------------------------------------------------
# Type coercion rules (used by the expression binder)
# ---------------------------------------------------------------------------

_NUMERIC_ORDER = ["int32", "int64", "decimal", "float32", "float64"]


def common_numeric_type(a: DataType, b: DataType) -> DataType:
    """Result type for binary arithmetic/comparison between a and b."""
    if a == b:
        return a
    if a.kind == "date32" and b.is_integer:
        return a
    if b.kind == "date32" and a.is_integer:
        return b
    if not (a.is_numeric and b.is_numeric):
        if a.kind == b.kind:
            return a
        raise SchemaError(f"no common type for {a!r} and {b!r}")
    if a.kind == "decimal" and b.kind == "decimal":
        return Decimal(max(a.scale, b.scale))
    ia, ib = _NUMERIC_ORDER.index(a.kind), _NUMERIC_ORDER.index(b.kind)
    winner = a if ia >= ib else b
    if winner.kind == "decimal":
        return Decimal(winner.scale)
    return winner
