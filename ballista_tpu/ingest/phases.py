"""Ingest phase timing: parse / H2D attribution with zero hot-path cost.

The io layer brackets its work in :func:`phase` blocks. Each block:

- accumulates into PROCESS totals (``phase_totals()``) — bench.py joins
  these with wall time for the cold-path parse/H2D/execute attribution;
- routes to the thread-bound :class:`PhaseRecorder` (if any), which
  forwards onto the owning operator's ``MetricsSet`` as
  ``elapsed_parse``/``elapsed_h2d`` timers so EXPLAIN ANALYZE shows the
  split per scan;
- emits an ``ingest.<name>`` span under ``BALLISTA_TRACE=1`` — spans
  from prefetch producer threads carry their own tids, which is what
  makes the overlap *observable* rather than inferred.

Binding is per-``next()`` (:func:`bound_iter`) or per-producer-loop
(PrefetchHandle), never per-generator-scope, so interleaved generators
on one thread can't cross-attribute. Nested same-name phases don't
double count (``_dictionary_for`` runs inside an already-timed parse).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, Optional

from ..observability.tracing import trace_span

_tls = threading.local()
_totals_lock = threading.Lock()
_totals: Dict[str, float] = {}


class PhaseRecorder:
    """Forwards phase timers / pipeline counters onto an operator's
    ``MetricsSet`` (or swallows them when metrics are disabled). The
    same benign-race policy as MetricsSet applies: producer and
    consumer threads may interleave updates to display values."""

    __slots__ = ("_metrics",)

    def __init__(self, metrics=None):
        self._metrics = metrics

    def record(self, name: str, secs: float) -> None:
        if self._metrics is not None:
            self._metrics.add_time("elapsed_" + name, secs)  # metric-names: elapsed_parse elapsed_h2d

    def add_wait(self, secs: float) -> None:
        """Time the consumer spent blocked on the prefetch queue — the
        pipeline's residual stall (≪ elapsed_parse when overlapped)."""
        if self._metrics is not None:
            self._metrics.add_time("elapsed_prefetch_wait", secs)

    def count_prefetched(self, n: int = 1) -> None:
        if self._metrics is not None:
            self._metrics.add_counter("prefetched_batches", n)


@contextmanager
def bind(recorder: Optional[PhaseRecorder]):
    """Route :func:`phase` timings on THIS thread to ``recorder``."""
    prev = getattr(_tls, "recorder", None)
    _tls.recorder = recorder
    try:
        yield
    finally:
        _tls.recorder = prev


@contextmanager
def phase(name: str, **attrs):
    """Time a parse/H2D block (see module docstring). Reentrant same-name
    blocks are transparent — only the outermost records."""
    active = getattr(_tls, "active", None)
    if active is None:
        active = _tls.active = set()
    if name in active:
        yield
        return
    active.add(name)
    span = trace_span("ingest." + name, **attrs)
    span.__enter__()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        active.discard(name)
        span.__exit__(None, None, None)
        with _totals_lock:
            _totals[name] = _totals.get(name, 0.0) + dt
        rec = getattr(_tls, "recorder", None)
        if rec is not None:
            rec.record(name, dt)


def bound_iter(gen: Iterator, recorder: Optional[PhaseRecorder]):
    """Drive ``gen`` with ``recorder`` bound only while it advances —
    the serial (pipeline-off) scan path's attribution wrapper."""
    while True:
        with bind(recorder):
            try:
                item = next(gen)
            except StopIteration:
                return
        yield item


def phase_totals() -> Dict[str, float]:
    """Process-wide cumulative seconds per phase (thread time: under
    overlap the sum can legitimately exceed wall time)."""
    with _totals_lock:
        out = dict(_totals)
    out.setdefault("parse", 0.0)
    out.setdefault("h2d", 0.0)
    return out


def reset_phase_totals() -> None:
    with _totals_lock:
        _totals.clear()
