"""Pipelined parallel ingest: overlap parse, H2D and compute.

The scan path used to be a fully serial pull loop — the device idled
while the CPU parsed, and the CPU idled while the device computed
(ROADMAP: post-compile-governor cold mass is parse+H2D+execute, ~14s
for q5's 8-table register+scan at SF1). The reference engine reads
partitions concurrently on tokio workers; this package is the
TPU-native equivalent, with three overlap axes:

- **cross-table** — :func:`prime_plan` starts every leaf scan's
  parse+H2D on a shared bounded thread pool
  (``BALLISTA_INGEST_THREADS``) before any consumer pulls, so
  independent tables (q5 joins eight) parse concurrently;
- **intra-query** — each scan streams through a bounded
  :class:`PrefetchHandle` queue (``BALLISTA_PREFETCH_BATCHES``,
  double-buffered by default): chunk N+1 parses on CPU while chunk N
  transfers/computes on device, with H2D issued from the producer
  thread (``ColumnBatch.from_numpy`` uploads as it builds);
- **cluster** — ``ShuffleReaderExec`` fetches a group's partition
  files concurrently and prefetches the next group behind the
  consumer (:func:`parallel_map` / the reader's in-flight futures).

Default ON; ``BALLISTA_INGEST_THREADS=1`` plus
``BALLISTA_PREFETCH_BATCHES=0`` restore the serial pull loop exactly.
Results are byte-identical either way — the pipeline reorders *timing*,
never rows (pinned by tests/test_ingest.py's determinism sweep).

Observability: the io layer brackets its work in :func:`phases.phase`
timers, which land on the owning scan's ``MetricsSet`` as
``elapsed_parse``/``elapsed_h2d`` (EXPLAIN ANALYZE renders them), emit
``ingest.parse``/``ingest.h2d`` spans under ``BALLISTA_TRACE=1`` (the
producer-thread tids make the overlap visible), and accumulate into
process totals ``phase_totals()`` that bench.py joins with wall time
for the parse/H2D/execute cold-path attribution.
"""

from .config import (  # noqa: F401
    ingest_threads,
    prefetch_batches,
    reconfigure,
)
from .phases import (  # noqa: F401
    PhaseRecorder,
    bound_iter,
    phase,
    phase_totals,
    reset_phase_totals,
)
from .pipeline import (  # noqa: F401
    KeyedLocks,
    PrefetchHandle,
    cancel_plan,
    ingest_pool,
    iter_partitions,
    parallel_map,
    pool_queue_depth,
    prime_plan,
)
