"""The pipeline engine: shared bounded pool + prefetch handles.

Deadlock-freedom invariant: nothing that runs ON a pool worker ever
*blocks* on a pool task that hasn't started. Both primitives here keep
it by construction —

- :class:`PrefetchHandle` consumers that are THEMSELVES pool workers
  try ``Future.cancel()`` immediately; other consumers poll the queue
  and retry the cancel whenever it stays empty — either way a
  producer the pool genuinely never started is taken inline instead
  of waited on (see ``__iter__`` for why both halves matter);
- :func:`parallel_map` runs the first item on the caller and, for each
  submitted future, cancels-and-runs-inline anything the pool hasn't
  started before waiting on it.

So an exhausted pool degrades to serial execution, never to a hang.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent import futures
from typing import Callable, Dict, Iterable, List, Optional

from ..observability import tracing
from ..observability.tracing import trace_event, trace_span
from . import phases
from .config import ingest_threads, prefetch_batches

_pool_lock = threading.Lock()
_pool: Optional[futures.ThreadPoolExecutor] = None


def ingest_pool() -> futures.ThreadPoolExecutor:
    """The process-wide bounded ingest pool (``BALLISTA_INGEST_THREADS``
    workers). Shared by scan priming, shuffle-group fetches and
    read-ahead, so total ingest concurrency has ONE bound."""
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = futures.ThreadPoolExecutor(
                max_workers=ingest_threads(),
                thread_name_prefix="ballista-ingest",
            )
        return _pool


def _reset_pool() -> None:
    global _pool
    with _pool_lock:
        p, _pool = _pool, None
    if p is not None:
        p.shutdown(wait=False)


def pool_queue_depth() -> int:
    """Work items queued on the ingest pool but not yet started — the
    backpressure gauge the executor heartbeat and health plane report.
    0 when the pool was never created (no ingest ran yet)."""
    with _pool_lock:
        p = _pool
    if p is None:
        return 0
    try:
        return p._work_queue.qsize()
    except Exception:  # noqa: BLE001 - executor internals drifted
        return 0


class KeyedLocks:
    """One lazily-created lock per key behind a single guard — the
    double-checked per-key materialization pattern shared by
    CacheSource keys, JoinExec build sides and ShuffleReaderExec
    groups: take ``get(key)``, re-check the cache inside it, compute
    once. Locks persist for the owner's lifetime (bounded by its key
    space), so invalidating a cache must NOT drop them — a builder
    mid-flight still holds one."""

    __slots__ = ("_guard", "_locks")

    def __init__(self):
        self._guard = threading.Lock()
        self._locks: Dict = {}

    def get(self, key) -> threading.Lock:
        with self._guard:
            return self._locks.setdefault(key, threading.Lock())


def _on_ingest_pool() -> bool:
    """True when the calling thread is an ingest pool worker (they are
    name-prefixed) — the only context where blocking on a not-yet-
    started pool task could deadlock."""
    return threading.current_thread().name.startswith("ballista-ingest")


# sentinels carried through the queue alongside batches
_DONE = object()
_ERROR = object()


class PrefetchHandle:
    """One scan's bounded producer/consumer pipe.

    A pool worker drives the batch generator — parse AND the H2D issue
    happen on the producer thread (``ColumnBatch.from_numpy`` uploads
    as it builds), so by the time the consumer takes a batch its
    transfer is already in flight — pushing into a queue of at most
    ``depth`` batches (the memory bound: at most ``depth`` parsed
    batches exist ahead of the consumer, double-buffered by default).

    Lifecycle: iterate to drain; ``cancel()`` stops the producer and
    empties the queue (safe at any point — consumers abandoning the
    stream early, e.g. under LimitExec, cancel from their ``finally``).
    Producer exceptions re-raise at the consumer, preserving serial
    error semantics."""

    __slots__ = ("_factory", "_depth", "_q", "_closed", "_future",
                 "_recorder", "_flow", "label", "max_occupancy")

    def __init__(self, factory: Callable[[], Iterable], depth: int,
                 label: str = "", recorder=None, pool=None):
        self._factory = factory
        self._depth = max(int(depth), 1)
        self._q: queue.Queue = queue.Queue(self._depth)
        self._closed = threading.Event()
        self._recorder = recorder
        # flow correlation: capture the creator thread's job/stage/task
        # attrs so producer spans on the pool worker stay attributable
        # to the query that primed them
        self._flow = tracing.current_flow()
        self.label = label
        # high-water mark of batches simultaneously queued (tests pin
        # it against the configured depth)
        self.max_occupancy = 0
        self._future = (pool or ingest_pool()).submit(self._produce)

    # -- producer (pool worker) ---------------------------------------------

    def _produce(self) -> None:
        with tracing.flow(**self._flow), \
                trace_span("ingest.prefetch", label=self.label):
            try:
                with phases.bind(self._recorder):
                    for batch in self._factory():
                        if not self._put((batch, None)):
                            return  # cancelled while blocked on a full queue
            except BaseException as e:  # noqa: BLE001 - re-raised at consumer
                self._put((_ERROR, e))
                return
        self._put((_DONE, None))

    def _put(self, item) -> bool:
        while not self._closed.is_set():
            try:
                self._q.put(item, timeout=0.05)
            except queue.Full:
                continue
            if item[0] is not _DONE and item[0] is not _ERROR:
                self.max_occupancy = max(self.max_occupancy,
                                         self._q.qsize())
            return True
        return False

    # -- consumer -----------------------------------------------------------

    def __iter__(self):
        # Pool-worker consumers cancel-or-inline IMMEDIATELY: blocking
        # there on a not-yet-started task can deadlock an exhausted
        # pool. Other consumers must NOT insta-cancel — one that
        # iterates right after priming would always win the race
        # against worker startup and degrade every scan to a serial
        # pull — but they can't block unboundedly either: primed
        # producers can outnumber workers, and a worker whose queue is
        # full holds its slot until ITS consumer arrives, which may be
        # behind THIS get. So: poll, and if the producer still hasn't
        # started, take the scan inline (cancel() succeeding proves
        # nothing was produced, so nothing can be duplicated).
        rec = self._recorder
        if _on_ingest_pool() and self._future.cancel():
            yield from phases.bound_iter(iter(self._factory()), rec)
            return
        waited = 0.0
        try:
            while True:
                t0 = time.perf_counter()
                try:
                    kind, err = self._q.get(timeout=0.05)
                except queue.Empty:
                    waited += time.perf_counter() - t0
                    if self._future.cancel():
                        yield from phases.bound_iter(
                            iter(self._factory()), rec)
                        return
                    if self._future.done() and self._q.empty():
                        # producer exited without a sentinel: only
                        # possible after an external cancel() — end the
                        # stream rather than poll forever
                        return
                    continue
                waited += time.perf_counter() - t0
                if kind is _DONE:
                    return
                if kind is _ERROR:
                    raise err
                if rec is not None:
                    rec.count_prefetched()
                yield kind
        finally:
            if rec is not None:
                rec.add_wait(waited)
            self.cancel()

    def cancel(self) -> None:
        """Stop the producer (idempotent) and drop queued batches."""
        self._closed.set()
        self._future.cancel()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass


def parallel_map(fn: Callable, items: Iterable) -> List:
    """``[fn(x) for x in items]`` fanned across the ingest pool,
    order-preserving and deadlock-free (see module docstring). Serial
    when the pool is width-1 or the pipeline is gated off."""
    items = list(items)
    if len(items) <= 1 or ingest_threads() <= 1 or prefetch_batches() <= 0:
        return [fn(x) for x in items]
    pool = ingest_pool()
    pending = [(x, pool.submit(fn, x)) for x in items[1:]]
    done = 0
    try:
        out = [fn(items[0])]
        for x, fut in pending:
            out.append(fn(x) if fut.cancel() else fut.result())
            done += 1
        return out
    finally:
        # an item that raised must not leave the rest running unobserved
        # on the shared pool (fetches burning network after the query
        # already failed); running futures finish, pending ones cancel
        for _, fut in pending[done:]:
            fut.cancel()


def iter_partitions(plan, partitions) -> "Iterable":
    """Yield ``plan.execute(p)``'s batches for each partition IN ORDER,
    with the partitions produced concurrently on the ingest pool — the
    pipelined replacement for the serial multi-partition pull loop
    (MergeExec, collect). Each partition subtree runs whole on its
    producer thread (scan, joins, partial aggregation — XLA releases
    the GIL during execution, so independent partitions genuinely
    overlap on a multi-core host), buffered behind the usual bounded
    queue. Yield order is partition order then batch order, identical
    to the serial loop — byte-identical results.

    Requires the consumed operators to tolerate concurrent partition
    execution; the engine already commits to that for cluster executors
    (see the benign-race notes in physical/base.py), and the two
    instance-level materializations shared ACROSS partitions —
    JoinExec's merged build, RepartitionExec's parts — take per-
    instance locks."""
    from ..lifecycle import check_cancel

    parts = list(partitions)
    if prefetch_batches() <= 0 or ingest_threads() <= 1 or len(parts) <= 1:
        for p in parts:
            for batch in plan.execute(p):
                # cooperative cancellation at the batch boundary (the
                # consumer thread carries the token; producers are
                # unparked by cancel_plan once this raises)
                check_cancel()
                yield batch
        return
    # STAGGERED: partition 0 runs inline first, so every governed
    # program in the subtree traces/lowers exactly once (concurrent
    # first-calls from N producers would each re-trace the same jits —
    # pure GIL-bound Python — turning the overlap into a slowdown on a
    # cold plan); the remaining partitions then overlap with the traces
    # warm, where their time is genuinely XLA execution (GIL released).
    for batch in plan.execute(parts[0]):
        check_cancel()
        yield batch
    handles = [
        PrefetchHandle(lambda p=p: plan.execute(p), prefetch_batches(),
                       label=f"partition[{p}]")
        for p in parts[1:]
    ]
    try:
        for h in handles:
            for batch in h:
                check_cancel()
                yield batch
    finally:
        for h in handles:
            h.cancel()


# -- plan-level priming -------------------------------------------------------


def _iter_scans(phys):
    from ..physical.operators import ScanExec

    stack = [phys]
    while stack:
        node = stack.pop()
        if isinstance(node, ScanExec):
            yield node
        stack.extend(node.children())


def prime_plan(phys, partitions: Optional[List[int]] = None) -> int:
    """Start background parse+H2D for every leaf scan of ``phys`` (all
    partitions, or just ``partitions``) — the cross-table overlap axis.
    Memory-resident sources are skipped (nothing to overlap). Handles
    ride on the ScanExec instances, which survive adaptive re-plans
    (``with_new_children`` keeps scan leaves), so a re-planned stage
    consumes the same prefetched stream; :func:`cancel_plan` cleanly
    drops whatever a rewrite or an early exit left unconsumed."""
    if prefetch_batches() <= 0:
        return 0
    from ..io.memory import MemTableSource
    from ..lifecycle import check_cancel

    n = 0
    for scan in _iter_scans(phys):
        if isinstance(scan.source, MemTableSource):
            continue
        nparts = scan.source.num_partitions()
        parts = range(nparts) if partitions is None else [
            p for p in partitions if 0 <= p < nparts
        ]
        for p in parts:
            # an already-cancelled query must not fan out N prefetches
            check_cancel()
            if scan.prime(p) is not None:
                n += 1
    if n:
        trace_event("ingest.prime", handles=n)
    return n


def cancel_plan(phys) -> None:
    """Cancel every unconsumed primed handle under ``phys`` (no-op for
    fully drained plans — consumed handles self-cancel)."""
    for scan in _iter_scans(phys):
        scan.cancel_primed()
