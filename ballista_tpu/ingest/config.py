"""Ingest pipeline knobs (env-resolved once, ``reconfigure()`` re-reads).

- ``BALLISTA_INGEST_THREADS``: workers on the shared ingest pool —
  the cross-table parallelism bound. Default ``min(cpu_count, 8)``
  (scan-side work is CPU parse; past the core count extra workers only
  thrash, and the native scanner already multi-threads within one file
  via ``BALLISTA_SCAN_THREADS``). ``1`` serializes tables against each
  other while still overlapping producer and consumer.
- ``BALLISTA_PREFETCH_BATCHES``: bounded prefetch queue depth per scan
  (and the shuffle reader's read-ahead gate). Default ``2`` (double
  buffering: one batch in flight to the consumer, one being parsed).
  ``0`` disables the pipeline entirely — scans run inline on the
  consuming thread, byte-for-byte the old serial behavior.
"""

from __future__ import annotations

import os
from typing import Optional

_DEFAULT_MAX_THREADS = 8

_threads: Optional[int] = None
_prefetch: Optional[int] = None


def _read_int(name: str, default: int, floor: int = 0) -> int:
    raw = os.environ.get(name, "")
    try:
        val = int(raw)
    except ValueError:
        return default
    return max(val, floor)


def ingest_threads() -> int:
    """Shared ingest pool width (>= 1)."""
    global _threads
    if _threads is None:
        _threads = _read_int(
            "BALLISTA_INGEST_THREADS",
            min(os.cpu_count() or 1, _DEFAULT_MAX_THREADS),
            floor=1,
        )
    return _threads


def prefetch_batches() -> int:
    """Per-scan prefetch queue depth; 0 = pipeline off (serial scans)."""
    global _prefetch
    if _prefetch is None:
        _prefetch = _read_int("BALLISTA_PREFETCH_BATCHES", 2, floor=0)
    return _prefetch


def reconfigure() -> None:
    """Re-read the env and rebuild the pool (tests flip knobs
    mid-process; a forked executor inherits env and resolves lazily)."""
    global _threads, _prefetch
    _threads = None
    _prefetch = None
    from .pipeline import _reset_pool

    _reset_pool()
