// Native shuffle data-plane server for ballista-tpu.
//
// The role the reference's Arrow Flight service plays for shuffle fetch
// (reference: rust/executor/src/flight_service.rs:193-228 FetchPartition).
// Speaks the exact protocol of ballista_tpu/distributed/dataplane.py:
//
//   request:  u32_be length | ballista_tpu.Action protobuf
//   response: u8 status (0 ok / 1 err) | u64_be length | payload
//
// The Action message is decoded with a minimal hand-rolled protobuf-wire
// reader (only the fetch_partition arm is needed), so the binary has zero
// dependencies beyond libc. Thread-per-connection; serves files from the
// executor work_dir (work_dir/{job}/{stage}/{partition}/data.arrow).
//
// Usage: shuffle_server <port> <work_dir>
// Also exposes a C API (start_shuffle_server) for embedding via ctypes.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <string>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// ---------------------------------------------------------------------------
// minimal protobuf wire decoding (varint + length-delimited)
// ---------------------------------------------------------------------------

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
      if (shift > 63) break;
    }
    ok = false;
    return 0;
  }

  bool bytes(uint64_t len, const uint8_t** out) {
    if (static_cast<uint64_t>(end - p) < len) {
      ok = false;
      return false;
    }
    *out = p;
    p += len;
    return true;
  }

  void skip(uint32_t wire_type) {
    switch (wire_type) {
      case 0: varint(); break;
      case 1: p += 8; break;
      case 2: {
        uint64_t n = varint();
        const uint8_t* dummy;
        bytes(n, &dummy);
        break;
      }
      case 5: p += 4; break;
      default: ok = false;
    }
    if (p > end) ok = false;
  }
};

struct FetchRequest {
  std::string job_id;
  uint32_t stage_id = 0;
  uint32_t partition_id = 0;
  bool is_shuffle = false;
  uint32_t output_partition = 0;
  bool valid = false;
};

// PartitionId { string job_id = 1; uint32 stage_id = 2; uint32 partition_id = 3; }
bool decode_partition_id(const uint8_t* buf, size_t len, FetchRequest* out) {
  Reader rr{buf, buf + len};
  while (rr.ok && rr.p < rr.end) {
    uint64_t t2 = rr.varint();
    uint32_t f2 = static_cast<uint32_t>(t2 >> 3);
    uint32_t w2 = static_cast<uint32_t>(t2 & 7);
    if (f2 == 1 && w2 == 2) {
      uint64_t sn = rr.varint();
      const uint8_t* sp;
      if (!rr.bytes(sn, &sp)) break;
      out->job_id.assign(reinterpret_cast<const char*>(sp), sn);
    } else if (f2 == 2 && w2 == 0) {
      out->stage_id = static_cast<uint32_t>(rr.varint());
    } else if (f2 == 3 && w2 == 0) {
      out->partition_id = static_cast<uint32_t>(rr.varint());
    } else {
      rr.skip(w2);
    }
  }
  return rr.ok && !out->job_id.empty();
}

// Action { oneof { ExecutePartition execute_partition = 1;
//                  PartitionId fetch_partition = 2; string sql = 3;
//                  FetchShufflePartition fetch_shuffle = 4; } }
// FetchShufflePartition { PartitionId producer = 1;
//                         uint32 output_partition = 2; }
FetchRequest decode_action(const uint8_t* buf, size_t len) {
  FetchRequest out;
  Reader r{buf, buf + len};
  while (r.ok && r.p < r.end) {
    uint64_t tag = r.varint();
    uint32_t field = static_cast<uint32_t>(tag >> 3);
    uint32_t wt = static_cast<uint32_t>(tag & 7);
    if (field == 2 && wt == 2) {  // fetch_partition submessage
      uint64_t n = r.varint();
      const uint8_t* sub;
      if (!r.bytes(n, &sub)) break;
      out.valid = decode_partition_id(sub, n, &out);
    } else if (field == 4 && wt == 2) {  // fetch_shuffle submessage
      uint64_t n = r.varint();
      const uint8_t* sub;
      if (!r.bytes(n, &sub)) break;
      Reader rr{sub, sub + n};
      out.is_shuffle = true;
      bool got_producer = false;
      while (rr.ok && rr.p < rr.end) {
        uint64_t t2 = rr.varint();
        uint32_t f2 = static_cast<uint32_t>(t2 >> 3);
        uint32_t w2 = static_cast<uint32_t>(t2 & 7);
        if (f2 == 1 && w2 == 2) {
          uint64_t sn = rr.varint();
          const uint8_t* sp;
          if (!rr.bytes(sn, &sp)) break;
          got_producer = decode_partition_id(sp, sn, &out);
        } else if (f2 == 2 && w2 == 0) {
          out.output_partition = static_cast<uint32_t>(rr.varint());
        } else {
          rr.skip(w2);
        }
      }
      out.valid = rr.ok && got_producer;
    } else {
      r.skip(wt);
    }
  }
  return out;
}

// ---------------------------------------------------------------------------
// socket plumbing
// ---------------------------------------------------------------------------

bool recv_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t k = recv(fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool send_all(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t k = send(fd, p, n, MSG_NOSIGNAL);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

void send_response(int fd, uint8_t status, const void* body, uint64_t len) {
  uint8_t hdr[9];
  hdr[0] = status;
  for (int i = 0; i < 8; ++i)
    hdr[1 + i] = static_cast<uint8_t>((len >> (8 * (7 - i))) & 0xff);
  if (send_all(fd, hdr, 9) && len > 0) send_all(fd, body, len);
}

void send_error(int fd, const std::string& msg) {
  send_response(fd, 1, msg.data(), msg.size());
}

struct ConnArgs {
  int fd;
  std::string work_dir;
};

bool path_component_ok(const std::string& s) {
  if (s.empty() || s.size() > 128) return false;
  for (char c : s)
    if (!isalnum(static_cast<unsigned char>(c)) && c != '-' && c != '_')
      return false;
  return true;
}

void* handle_conn(void* argp) {
  auto* args = static_cast<ConnArgs*>(argp);
  int fd = args->fd;
  uint8_t len4[4];
  if (recv_exact(fd, len4, 4)) {
    uint32_t len = (uint32_t(len4[0]) << 24) | (uint32_t(len4[1]) << 16) |
                   (uint32_t(len4[2]) << 8) | uint32_t(len4[3]);
    if (len > 0 && len < (1u << 20)) {
      std::string body(len, 0);
      if (recv_exact(fd, body.data(), len)) {
        FetchRequest req =
            decode_action(reinterpret_cast<const uint8_t*>(body.data()), len);
        if (!req.valid) {
          send_error(fd, "unsupported or malformed data-plane action");
        } else if (!path_component_ok(req.job_id)) {
          send_error(fd, "bad job id");
        } else {
          char path[512];
          if (req.is_shuffle) {
            snprintf(path, sizeof path, "%s/%s/%u/%u/shuffle-%u.arrow",
                     args->work_dir.c_str(), req.job_id.c_str(),
                     req.stage_id, req.partition_id, req.output_partition);
          } else {
            snprintf(path, sizeof path, "%s/%s/%u/%u/data.arrow",
                     args->work_dir.c_str(), req.job_id.c_str(), req.stage_id,
                     req.partition_id);
          }
          FILE* f = fopen(path, "rb");
          if (!f) {
            send_error(fd, std::string("no such partition: ") + path);
          } else {
            fseek(f, 0, SEEK_END);
            long size = ftell(f);
            fseek(f, 0, SEEK_SET);
            std::string data(static_cast<size_t>(size), 0);
            if (fread(data.data(), 1, data.size(), f) == data.size()) {
              send_response(fd, 0, data.data(), data.size());
            } else {
              send_error(fd, "partition read failed");
            }
            fclose(f);
          }
        }
      }
    }
  }
  close(fd);
  delete args;
  return nullptr;
}

struct ServerArgs {
  int listen_fd;
  std::string work_dir;
};

void* accept_loop(void* argp) {
  auto* s = static_cast<ServerArgs*>(argp);
  for (;;) {
    int fd = accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      break;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    auto* ca = new ConnArgs{fd, s->work_dir};
    pthread_t t;
    pthread_create(&t, nullptr, handle_conn, ca);
    pthread_detach(t);
  }
  delete s;
  return nullptr;
}

}  // namespace

extern "C" {

// Starts the server on a background thread bound to ``bind_host`` (numeric
// IPv4, "localhost", or ""/"0.0.0.0" for INADDR_ANY — matching the Python
// DataPlaneServer's bind semantics so loopback-only deployments stay
// loopback-only). Returns the bound port (>0) or a negative errno. port=0
// picks an ephemeral port.
int start_shuffle_server_bind(int port, const char* work_dir,
                              const char* bind_host) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  if (bind_host != nullptr && bind_host[0] != '\0' &&
      strcmp(bind_host, "0.0.0.0") != 0) {
    if (strcmp(bind_host, "localhost") == 0) {
      addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    } else if (inet_pton(AF_INET, bind_host, &addr.sin_addr) != 1) {
      close(fd);
      return -EINVAL;
    }
  }
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  if (listen(fd, 128) < 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  socklen_t alen = sizeof addr;
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  auto* sa = new ServerArgs{fd, work_dir};
  pthread_t t;
  pthread_create(&t, nullptr, accept_loop, sa);
  pthread_detach(t);
  return ntohs(addr.sin_port);
}

int start_shuffle_server(int port, const char* work_dir) {
  return start_shuffle_server_bind(port, work_dir, nullptr);
}

}  // extern "C"

#ifndef NO_MAIN
#include <sys/prctl.h>
#include <csignal>

int main(int argc, char** argv) {
  if (argc != 3 && argc != 4) {
    fprintf(stderr, "usage: %s <port> <work_dir> [bind_host]\n", argv[0]);
    return 2;
  }
  // die with the spawning executor: an abnormally-killed parent must not
  // orphan a daemon holding the configured port (opt out for standalone
  // runs with SHUFFLE_SERVER_PDEATHSIG=0)
  const bool tie_to_parent = [] {
    const char* pd = getenv("SHUFFLE_SERVER_PDEATHSIG");
    return pd == nullptr || strcmp(pd, "0") != 0;
  }();
  if (tie_to_parent) {
    prctl(PR_SET_PDEATHSIG, SIGTERM);
  }
  int port = start_shuffle_server_bind(atoi(argv[1]), argv[2],
                                       argc == 4 ? argv[3] : nullptr);
  if (port < 0) {
    fprintf(stderr, "bind failed: %s\n", strerror(-port));
    return 1;
  }
  printf("ballista-tpu shuffle server on port %d serving %s\n", port, argv[2]);
  fflush(stdout);
  if (tie_to_parent) {
    // PDEATHSIG can be inert under some sandboxes/kernels, so also poll.
    // The EXPECTED parent pid comes from the spawner
    // (SHUFFLE_SERVER_PARENT_PID): comparing against a pid captured
    // here would race a parent that died before we got scheduled —
    // we'd record the reaper and never notice. Reparenting (getppid
    // differs from the expected pid, or init) means the executor died.
    pid_t expected = getppid();
    const char* pp = getenv("SHUFFLE_SERVER_PARENT_PID");
    if (pp != nullptr && atoi(pp) > 0) expected = (pid_t)atoi(pp);
    for (;;) {
      pid_t now = getppid();
      if (now != expected || now == 1) return 0;
      sleep(2);
    }
  }
  pause();
  return 0;
}
#endif  // NO_MAIN
