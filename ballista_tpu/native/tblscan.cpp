// Native delimited-text scanner for ballista-tpu.
//
// The role DataFusion's Rust CSV reader plays for the reference engine's
// scans (reference: rust/client/src/context.rs:88-108 read_csv;
// rust/benchmarks/tpch/src/main.rs:128-155 .tbl registration): parse
// '|'/','-delimited files into typed columnar buffers at native speed.
//
// Exposed as a C API consumed from Python via ctypes (no pybind11 in the
// build environment). One pass over an mmap'd file; per-column typed
// vectors; string columns are dictionary-encoded with a SORTED dictionary
// so codes are ordinal (the engine's comparison kernels rely on this).
//
// Column kinds: 0=int64 1=int32 2=decimal(scale)->int64 3=date32(days)
//               4=utf8 dict codes (int32) 5=float32 6=boolean(int32)
//               -1 = skip column.
// NOTE: no quote handling — callers route quoted CSV through the Python
// reader and use this scanner for unquoted formats (TPC-H .tbl).

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

struct Column {
  int kind = -1;
  int scale = 0;
  std::vector<int64_t> i64;
  std::vector<int32_t> i32;
  std::vector<float> f32;
  // utf8: raw codes (pre-sort), dictionary arena
  std::unordered_map<std::string, int32_t> dict_map;
  std::vector<std::string> dict_values;
  // 1-byte values (status flags etc.) hit this O(1) table instead of a
  // per-row string construction + hash lookup; kept consistent with
  // dict_map so mixed-length columns stay correct
  int32_t char1[256];
  // SQL NULLs: empty non-string fields parse as NULL (CSV convention,
  // matching the reference's Arrow readers). valid is tracked per row;
  // has_null lets the wrapper skip materializing all-valid bitmaps.
  std::vector<uint8_t> valid;
  bool has_null = false;
  Column() { for (auto& v : char1) v = -1; }
};

struct Table {
  std::vector<Column> cols;
  int64_t num_rows = 0;
  std::string error;
};

inline int64_t days_from_civil(int y, int m, int d) {
  // Howard Hinnant's civil-days algorithm (public domain)
  y -= m <= 2;
  const int era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = static_cast<unsigned>(y - era * 400);
  const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097LL + static_cast<int>(doe) - 719468;
}

inline int64_t pow10_i(int n) {
  int64_t p = 1;
  while (n-- > 0) p *= 10;
  return p;
}

inline size_t col_size(const Column& c) {
  switch (c.kind) {
    case 0: case 2: return c.i64.size();
    case 1: case 3: case 4: case 6: return c.i32.size();
    case 5: return c.f32.size();
  }
  return 0;
}

// parse one field [s, e) into column c
inline bool parse_field(Column& c, const char* s, const char* e) {
  if (s == e && c.kind >= 0 && c.kind != 4) {
    // empty non-string field -> SQL NULL (utf8 keeps "" as a value,
    // the unquoted-format convention). Validity tracking starts lazily
    // at the first NULL: backfill earlier rows as valid, and the row
    // loop resizes with 1s after each subsequent parse.
    if (!c.has_null) {
      c.valid.assign(col_size(c), 1);
      c.has_null = true;
    }
    switch (c.kind) {
      case 0: case 2: c.i64.push_back(0); break;
      case 1: case 3: case 6: c.i32.push_back(0); break;
      case 5: c.f32.push_back(0.0f); break;
    }
    c.valid.push_back(0);
    return true;
  }
  switch (c.kind) {
    case 0: case 1: {  // int64 / int32
      bool neg = false;
      if (s < e && (*s == '-' || *s == '+')) neg = (*s == '-'), ++s;
      int64_t v = 0;
      for (; s < e; ++s) {
        if (*s < '0' || *s > '9') return false;
        v = v * 10 + (*s - '0');
      }
      if (neg) v = -v;
      if (c.kind == 0) c.i64.push_back(v);
      else c.i32.push_back(static_cast<int32_t>(v));
      return true;
    }
    case 2: {  // decimal -> scaled int64
      bool neg = false;
      if (s < e && (*s == '-' || *s == '+')) neg = (*s == '-'), ++s;
      int64_t ip = 0;
      for (; s < e && *s != '.'; ++s) {
        if (*s < '0' || *s > '9') return false;
        ip = ip * 10 + (*s - '0');
      }
      int64_t fp = 0;
      int fdigits = 0;
      if (s < e && *s == '.') {
        ++s;
        for (; s < e && fdigits < c.scale; ++s, ++fdigits) {
          if (*s < '0' || *s > '9') return false;
          fp = fp * 10 + (*s - '0');
        }
        // round on the first truncated digit
        if (s < e && *s >= '5' && *s <= '9') ++fp;
      }
      while (fdigits < c.scale) fp *= 10, ++fdigits;
      int64_t v = ip * pow10_i(c.scale) + fp;
      c.i64.push_back(neg ? -v : v);
      return true;
    }
    case 3: {  // date32: YYYY-MM-DD
      if (e - s < 10) return false;
      auto num = [&](const char* p, int n) {
        int v = 0;
        for (int i = 0; i < n; ++i) v = v * 10 + (p[i] - '0');
        return v;
      };
      int y = num(s, 4), m = num(s + 5, 2), d = num(s + 8, 2);
      c.i32.push_back(static_cast<int32_t>(days_from_civil(y, m, d)));
      return true;
    }
    case 4: {  // utf8 dict
      if (e - s == 1) {
        int32_t cached = c.char1[static_cast<unsigned char>(*s)];
        if (cached >= 0) {
          c.i32.push_back(cached);
          return true;
        }
      }
      std::string key(s, static_cast<size_t>(e - s));
      auto it = c.dict_map.find(key);
      int32_t code;
      if (it == c.dict_map.end()) {
        code = static_cast<int32_t>(c.dict_values.size());
        c.dict_map.emplace(key, code);
        c.dict_values.push_back(std::move(key));
      } else {
        code = it->second;
      }
      if (e - s == 1) c.char1[static_cast<unsigned char>(*s)] = code;
      c.i32.push_back(code);
      return true;
    }
    case 5: {  // float32
      char buf[64];
      size_t n = std::min<size_t>(static_cast<size_t>(e - s), 63);
      memcpy(buf, s, n);
      buf[n] = 0;
      c.f32.push_back(strtof(buf, nullptr));
      return true;
    }
    case 6: {  // boolean: true/false/t/f/1/0 (case-insensitive)
      char c0 = (s < e) ? static_cast<char>(tolower(*s)) : 0;
      if (c0 == 't' || c0 == '1') c.i32.push_back(1);
      else if (c0 == 'f' || c0 == '0') c.i32.push_back(0);
      else return false;
      return true;
    }
    default:
      return true;  // skipped column
  }
}

// Parse rows of [start-boundary after `from`, first row at/after `to`)
// into t's columns. Returns false (with t->error set) on a parse error.
// `data`/`end` bound the whole mapping; `from`==data means "begin at the
// top" (header handling is the caller's job).
bool parse_span(Table* t, const char* data, const char* end,
                const char* from, const char* to, char delim, int ncols) {
  const char* p = from;
  if (from != data) {
    // row ownership rule: a row belongs to the span containing its
    // first byte (probe for the newline ending the previous row)
    p = from - 1;
    const char* nl = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    p = (nl == nullptr) ? end : nl + 1;
  }
  int64_t row = 0;
  while (p < to) {  // a row that BEGINS before `to` parses to its EOL
    const char* nl = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (nl == nullptr) nl = end;
    if (p == nl) {  // empty line
      ++p;
      continue;
    }
    const char* row_start = p;
    for (int ci = 0; ci < ncols; ++ci) {
      const char* fe = static_cast<const char*>(
          memchr(p, delim, static_cast<size_t>(nl - p)));
      if (fe == nullptr) fe = nl;
      Column& c = t->cols[static_cast<size_t>(ci)];
      if (c.kind >= 0) {
        if (!parse_field(c, p, fe)) {
          // `row` counts from the span start, which is meaningless to a
          // reader of a ranged/multithreaded scan; the absolute byte
          // offsets of the failing row and of the span locate the error
          // in the file regardless of which sub-span hit it
          char msg[224];
          snprintf(msg, sizeof msg,
                   "parse error at row %lld of span (row byte offset "
                   "%lld, span starts at byte %lld) col %d (kind %d)",
                   static_cast<long long>(row),
                   static_cast<long long>(row_start - data),
                   static_cast<long long>(from - data), ci, c.kind);
          t->error = msg;
          return false;
        }
        if (c.has_null) c.valid.resize(col_size(c), 1);
      }
      p = fe < nl ? fe + 1 : nl;  // consume field delimiter
    }
    p = nl < end ? nl + 1 : end;
    ++row;
  }
  t->num_rows = row;
  return true;
}

// Append src's parsed rows onto dst (same column layout). utf8 codes are
// remapped into dst's dictionary space; validity lengths are normalized.
void append_table(Table& dst, Table& src, int ncols) {
  for (int ci = 0; ci < ncols; ++ci) {
    Column& d = dst.cols[static_cast<size_t>(ci)];
    Column& s = src.cols[static_cast<size_t>(ci)];
    if (d.kind < 0) continue;
    const size_t d_rows = col_size(d);
    const size_t s_rows = col_size(s);
    if (d.kind == 4) {
      std::vector<int32_t> remap(s.dict_values.size());
      for (size_t i = 0; i < s.dict_values.size(); ++i) {
        auto it = d.dict_map.find(s.dict_values[i]);
        if (it == d.dict_map.end()) {
          int32_t code = static_cast<int32_t>(d.dict_values.size());
          d.dict_map.emplace(s.dict_values[i], code);
          d.dict_values.push_back(s.dict_values[i]);
          remap[i] = code;
        } else {
          remap[i] = it->second;
        }
      }
      d.i32.reserve(d.i32.size() + s.i32.size());
      for (int32_t code : s.i32) d.i32.push_back(remap[code]);
      // the 1-byte fast cache maps to dst codes already; leave it
    } else {
      d.i64.insert(d.i64.end(), s.i64.begin(), s.i64.end());
      d.i32.insert(d.i32.end(), s.i32.begin(), s.i32.end());
      d.f32.insert(d.f32.end(), s.f32.begin(), s.f32.end());
    }
    if (s.has_null && !d.has_null) {
      d.valid.assign(d_rows, 1);
      d.has_null = true;
    }
    if (d.has_null) {
      if (s.has_null) {
        d.valid.insert(d.valid.end(), s.valid.begin(), s.valid.end());
      } else {
        d.valid.insert(d.valid.end(), s_rows, 1);
      }
    }
  }
  dst.num_rows += src.num_rows;
}

void sort_dictionary(Column& c) {
  // sort dict; remap codes so they stay ordinal
  const size_t n = c.dict_values.size();
  std::vector<int32_t> order(n);
  for (size_t i = 0; i < n; ++i) order[i] = static_cast<int32_t>(i);
  std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
    return c.dict_values[a] < c.dict_values[b];
  });
  std::vector<int32_t> remap(n);
  std::vector<std::string> sorted(n);
  for (size_t i = 0; i < n; ++i) {
    remap[order[i]] = static_cast<int32_t>(i);
    sorted[i] = std::move(c.dict_values[order[i]]);
  }
  c.dict_values = std::move(sorted);
  for (auto& code : c.i32) code = remap[code];
  c.dict_map.clear();
}

}  // namespace

extern "C" {

// Returns an opaque Table*; on fatal error returns a Table with error set
// (check tbl_error). wanted: indices of columns to materialize; others are
// parsed-past. delimiter: e.g. '|'; skip_header: 1 to drop first line.
//
// Byte-range scans (offset/max_bytes) enable bounded-RAM streaming over
// arbitrarily large files and parallel chunk workers: an offset > 0
// starts at the first line boundary AFTER offset, and parsing runs to
// the first line boundary at/after offset+max_bytes (max_bytes < 0 =
// EOF). Adjacent ranges therefore partition the file's rows exactly.
void* tbl_open_range_mt(const char* path, int ncols, const int32_t* kinds,
                        const int32_t* scales, const int32_t* wanted,
                        int nwanted, char delimiter, int skip_header,
                        int64_t offset, int64_t max_bytes, int nthreads) {
  auto init_table = [&](Table* t) {
    t->cols.resize(static_cast<size_t>(ncols));
    std::vector<char> want(static_cast<size_t>(ncols), 0);
    for (int i = 0; i < nwanted; ++i)
      want[static_cast<size_t>(wanted[i])] = 1;
    for (int i = 0; i < ncols; ++i) {
      t->cols[static_cast<size_t>(i)].kind =
          want[static_cast<size_t>(i)] ? kinds[i] : -1;
      t->cols[static_cast<size_t>(i)].scale = scales[i];
    }
  };
  auto* t = new Table();
  init_table(t);

  int fd = open(path, O_RDONLY);
  if (fd < 0) {
    t->error = std::string("open failed: ") + strerror(errno);
    return t;
  }
  struct stat st;
  fstat(fd, &st);
  size_t size = static_cast<size_t>(st.st_size);
  if (size == 0 || offset >= static_cast<int64_t>(size)) {
    close(fd);
    return t;
  }
  const char* data = static_cast<const char*>(
      mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0));
  close(fd);
  if (data == MAP_FAILED) {
    t->error = std::string("mmap failed: ") + strerror(errno);
    return t;
  }
  const char* end = data + size;
  const char* from = data + offset;  // span rule handles row alignment
  const char* stop = end;            // parse rows that BEGIN before stop
  if (max_bytes >= 0 && offset + max_bytes < static_cast<int64_t>(size)) {
    stop = data + offset + max_bytes;
  }
  if (skip_header && offset == 0) {
    const char* p = data;
    while (p < end && *p != '\n') ++p;
    from = (p < end) ? p + 1 : end;
    // the header consumed the span's data==from anchor; fake a non-top
    // start so parse_span's boundary probe lands on the header's newline
    if (from == end) stop = from;
  }

  const int64_t span_bytes = stop - from;
  int nt = nthreads;
  if (nt < 1) nt = 1;
  // a thread needs enough bytes to amortize merge cost (env override is
  // for tests exercising the merge on small inputs)
  int64_t min_per = 16 << 20;
  const char* mp = getenv("TBLSCAN_MIN_THREAD_BYTES");
  if (mp != nullptr && atoll(mp) > 0) min_per = atoll(mp);
  if (span_bytes / min_per < nt)
    nt = static_cast<int>(span_bytes / min_per);
  if (nt < 1) nt = 1;

  // offset==0 starts row-aligned (top of file, or just past the header),
  // so parse_span's boundary probe is skipped by passing data==from;
  // offset>0 must probe for the previous row's newline
  const bool aligned = (offset == 0);
  if (nt == 1) {
    if (!parse_span(t, aligned ? from : data, end, from, stop, delimiter,
                    ncols)) {
      munmap(const_cast<char*>(data), size);
      return t;
    }
  } else {
    std::vector<Table> parts(static_cast<size_t>(nt));
    std::vector<pthread_t> threads(static_cast<size_t>(nt));
    struct Job {
      Table* t;
      const char* data;
      const char* end;
      const char* from;
      const char* to;
      char delim;
      int ncols;
    };
    std::vector<Job> jobs(static_cast<size_t>(nt));
    const int64_t per = span_bytes / nt;
    for (int i = 0; i < nt; ++i) {
      auto& part = parts[static_cast<size_t>(i)];
      init_table(&part);
      const char* lo = from + per * i;
      const char* hi = (i == nt - 1) ? stop : from + per * (i + 1);
      // only an aligned first sub-span may skip the boundary probe
      jobs[static_cast<size_t>(i)] = {
          &part, (i == 0 && aligned) ? lo : data, end, lo, hi, delimiter,
          ncols};
    }
    auto run = [](void* arg) -> void* {
      auto* j = static_cast<Job*>(arg);
      parse_span(j->t, j->data, j->end, j->from, j->to, j->delim, j->ncols);
      return nullptr;
    };
    for (int i = 0; i < nt; ++i)
      pthread_create(&threads[static_cast<size_t>(i)], nullptr, run,
                     &jobs[static_cast<size_t>(i)]);
    for (int i = 0; i < nt; ++i)
      pthread_join(threads[static_cast<size_t>(i)], nullptr);
    for (int i = 0; i < nt; ++i) {
      if (!parts[static_cast<size_t>(i)].error.empty()) {
        t->error = parts[static_cast<size_t>(i)].error;
        munmap(const_cast<char*>(data), size);
        return t;
      }
    }
    for (int i = 0; i < nt; ++i)
      append_table(*t, parts[static_cast<size_t>(i)], ncols);
  }
  munmap(const_cast<char*>(data), size);
  for (auto& c : t->cols)
    if (c.kind == 4) sort_dictionary(c);
  return t;
}

void* tbl_open_range(const char* path, int ncols, const int32_t* kinds,
                     const int32_t* scales, const int32_t* wanted,
                     int nwanted, char delimiter, int skip_header,
                     int64_t offset, int64_t max_bytes) {
  return tbl_open_range_mt(path, ncols, kinds, scales, wanted, nwanted,
                           delimiter, skip_header, offset, max_bytes, 1);
}

void* tbl_open(const char* path, int ncols, const int32_t* kinds,
               const int32_t* scales, const int32_t* wanted, int nwanted,
               char delimiter, int skip_header) {
  return tbl_open_range(path, ncols, kinds, scales, wanted, nwanted,
                        delimiter, skip_header, 0, -1);
}

const char* tbl_error(void* h) {
  auto* t = static_cast<Table*>(h);
  return t->error.empty() ? nullptr : t->error.c_str();
}

int64_t tbl_num_rows(void* h) { return static_cast<Table*>(h)->num_rows; }

// fill int64 buffer (kind 0 and 2)
int tbl_fill_i64(void* h, int col, int64_t* out) {
  auto& c = static_cast<Table*>(h)->cols[static_cast<size_t>(col)];
  if (c.i64.empty() && static_cast<Table*>(h)->num_rows > 0) return -1;
  memcpy(out, c.i64.data(), c.i64.size() * sizeof(int64_t));
  return 0;
}

// fill int32 buffer (kinds 1, 3, 4)
int tbl_fill_i32(void* h, int col, int32_t* out) {
  auto& c = static_cast<Table*>(h)->cols[static_cast<size_t>(col)];
  if (c.i32.empty() && static_cast<Table*>(h)->num_rows > 0) return -1;
  memcpy(out, c.i32.data(), c.i32.size() * sizeof(int32_t));
  return 0;
}

int tbl_fill_f32(void* h, int col, float* out) {
  auto& c = static_cast<Table*>(h)->cols[static_cast<size_t>(col)];
  if (c.f32.empty() && static_cast<Table*>(h)->num_rows > 0) return -1;
  memcpy(out, c.f32.data(), c.f32.size() * sizeof(float));
  return 0;
}

int64_t tbl_dict_count(void* h, int col) {
  return static_cast<int64_t>(
      static_cast<Table*>(h)->cols[static_cast<size_t>(col)].dict_values.size());
}

int64_t tbl_dict_total_bytes(void* h, int col) {
  int64_t n = 0;
  for (auto& s :
       static_cast<Table*>(h)->cols[static_cast<size_t>(col)].dict_values)
    n += static_cast<int64_t>(s.size());
  return n;
}

// out: concatenated utf8 bytes; offsets: dict_count+1 entries
int tbl_fill_dict(void* h, int col, char* out, int64_t* offsets) {
  auto& c = static_cast<Table*>(h)->cols[static_cast<size_t>(col)];
  int64_t off = 0;
  size_t i = 0;
  for (auto& s : c.dict_values) {
    offsets[i++] = off;
    memcpy(out + off, s.data(), s.size());
    off += static_cast<int64_t>(s.size());
  }
  offsets[i] = off;
  return 0;
}

// 1 when the column saw at least one NULL (empty field); 0 = all valid
// (the wrapper can then skip materializing a bitmap entirely)
int tbl_has_null(void* h, int col) {
  return static_cast<Table*>(h)->cols[static_cast<size_t>(col)].has_null ? 1 : 0;
}

// fill per-row validity bytes (1 = valid, 0 = NULL); num_rows entries.
// Only meaningful when tbl_has_null returns 1.
int tbl_fill_valid(void* h, int col, uint8_t* out) {
  auto* t = static_cast<Table*>(h);
  auto& c = t->cols[static_cast<size_t>(col)];
  if (!c.has_null) return -1;
  if (static_cast<int64_t>(c.valid.size()) != t->num_rows) return -1;
  memcpy(out, c.valid.data(), c.valid.size());
  return 0;
}

void tbl_close(void* h) { delete static_cast<Table*>(h); }

}  // extern "C"
