"""Process-wide dictionary registry: device-resident string encodings.

The engine keeps utf8 columns dictionary-encoded end-to-end (codes on
device, values on host — columnar.py). Before this module, every place
where two differently-encoded columns met rebuilt a sorted union with
``np.unique`` over *object arrays* and re-derived remap tables with
per-call ``.astype(str)`` casts — the ``host.dictionary`` profiler lane:
GIL-bound numpy string work that re-ran on every shuffle read group,
every concat of mixed batches and every join probe chain, and that
ROADMAP item 1 flags as the lane that caps string-heavy queries at
scale.

This registry makes dictionary identity a managed resource, the way
``compile/`` made jit compilation one:

- **Interning**: producers (text/parquet scans) intern their sorted
  value sets per (table files, column) entry, so every scan of one
  table — across source instances, re-scans, executor tasks in one
  process — shares ONE ``Dictionary`` instance and codes are comparable
  by construction (unify degenerates to an identity check).
- **Versioned entries**: when an entry sees new values it appends a new
  *version* (sorted superset union) and records an int32 *step remap*
  (old code -> new code). Any two versions of one entry then remap
  through pure integer composition — no string comparison at all — and
  sites apply the table as a device-side ``jnp.take`` gather.
- **Content epochs**: every registered dictionary carries an *epoch* —
  a vectorized content fingerprint (``values_fingerprint``). Epochs are
  the cross-process currency: shuffle writers stamp them into Arrow IPC
  field metadata so readers resolve the SAME in-process instance (or
  adopt one, once, per epoch) instead of rebuilding values from the
  wire; ``compile/aot.py`` keys artifacts on epochs so the per-value
  Python fingerprint loop leaves the hot path and equal-content
  dictionaries (rebuilt per process, per artifact, per dataset copy)
  stop invalidating exported programs.
- **Cached remaps/unions**: cross-entry pairs (join keys from different
  tables) and multi-producer unions are built once per
  (fingerprint, fingerprint) pair — C-level searchsorted over the
  cached ``values_str()`` views, never per invocation, never over
  object arrays — and served from bounded process-wide caches.

``BALLISTA_DICT_REGISTRY=off`` restores the legacy behavior exactly:
no interning/stamping, and the unify/remap entry points below fall back
to the original object-array union code (kept here so
``dev/check_dict_sites.py`` can pin that no other module grows a host
unify path).

Invariants (also documented in docs/strings.md):

- dictionary values are ALWAYS sorted + duplicate-free — comparison
  kernels translate string ordering to code ordering and
  ``searchsorted`` boundaries (kernels/expr_eval.py) rely on it;
- versions of one entry form a superset chain (version k's value set
  contains version j's for k >= j), so step remaps are strictly
  increasing injections and inverses are well-defined;
- a ``Dictionary`` never mutates after registration; appends mint new
  instances.
"""

from __future__ import annotations

import hashlib
import os
import sys
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .columnar import Dictionary

# bounds: entries/epochs/remaps are tiny next to the dictionaries they
# index, but nothing here may grow without limit in a long-lived server
_MAX_VERSIONS = 64        # per entry; past it, interns return unstamped
_MAX_ENTRIES = 256        # table entries (LRU; evicted entries degrade
#                           their members to pairwise remaps, never wrong)
_MAX_EPOCHS = 512         # process-wide interned instances (LRU)
_MAX_REMAPS = 256         # cached pairwise remap tables (LRU)
_MAX_UNIONS = 64          # cached multi-producer unions (LRU)


def enabled() -> bool:
    return os.environ.get("BALLISTA_DICT_REGISTRY", "on").lower() not in (
        "off", "0", "false")


# ---------------------------------------------------------------------------
# vectorized content fingerprints (the "epoch" of a value set)
# ---------------------------------------------------------------------------


def _obj_lens(values) -> np.ndarray:
    """Per-value codepoint lengths of the ORIGINAL values (numpy's
    fixed-width unicode representation silently drops trailing U+0000,
    so ``np.char.str_len`` over a str view cannot see them)."""
    return np.fromiter((len(str(v)) for v in values), dtype=np.int64,
                       count=len(values))


def values_fingerprint(sv: np.ndarray,
                       lens: Optional[np.ndarray] = None) -> str:
    """sha1 of a sorted str array's content, vectorized (no per-value
    Python loop): the raw fixed-width UCS4 buffer (a memcpy, no utf-8
    re-encode) plus a per-value length plane. Collision-free over value
    sets: two sets sharing a buffer can only differ in trailing NULs,
    which the length plane separates (pass ``lens`` from the original
    objects when they might carry trailing NULs). Byte order rides in
    the digest so a fingerprint never crosses endianness silently."""
    h = hashlib.sha1()
    h.update(f"{len(sv)}:{sys.byteorder}:".encode())
    if len(sv):
        if lens is None:
            lens = np.char.str_len(sv)
        h.update(np.ascontiguousarray(lens.astype("<i8")).tobytes())
        h.update(np.ascontiguousarray(sv).tobytes())
    return h.hexdigest()


def fingerprint(d: Dictionary) -> str:
    """Content fingerprint of any dictionary, cached on the instance.
    Registry members carry it from registration; others compute it
    once, vectorized — this replaces the per-value Python loop of
    ``Dictionary.content_fingerprint`` everywhere hot (compile/aot.py
    keys on it). The object-length plane keeps a trailing-NUL value
    set (which the registry refuses to intern) from aliasing its
    stripped twin."""
    fp = d._reg_epoch
    if fp is None:
        fp = d._reg_epoch = values_fingerprint(d.values_str(),
                                               _obj_lens(d.values))
    return fp


def _nul_tails(values, sv: np.ndarray) -> bool:
    """True when any value is corrupted by the str view (trailing
    U+0000): such sets stay OUTSIDE the registry — legacy object-array
    semantics apply, exactness over speed."""
    return len(sv) > 0 and not np.array_equal(_obj_lens(values),
                                              np.char.str_len(sv))


def _str_view_exact(d: Dictionary) -> bool:
    """Whether ``d.values_str()`` represents the values losslessly
    (no trailing-NUL values). Cached per instance; registry members
    are exact by construction (intern/adopt refuse the rest)."""
    exact = d._str_exact
    if exact is None:
        exact = d._str_exact = not _nul_tails(d.values, d.values_str())
    return exact


def file_entry_key(kind: str, path: str, files: Sequence[str]) -> tuple:
    """Table-scoped entry-key base for file sources: same files (path +
    sizes + mtimes) -> same entry, so every source instance over this
    data shares interned dictionaries; regenerated data changes the
    signature and can never alias a stale entry. Column name is
    appended by the caller per dictionary."""
    try:
        sig = tuple((os.path.basename(f), os.path.getsize(f),
                     os.stat(f).st_mtime_ns) for f in files)
    except OSError:
        # unstatable source: a process-unique private entry (sharing
        # would risk aliasing data we cannot identify)
        with _key_seq_lock:
            _KEY_SEQ[0] += 1
            sig = (("unstatable", _KEY_SEQ[0]),)
    return (kind, os.path.abspath(path), sig)


_KEY_SEQ = [0]
_key_seq_lock = threading.Lock()


# ---------------------------------------------------------------------------
# registry entries
# ---------------------------------------------------------------------------


class RegistryEntry:
    """One table-scoped dictionary namespace: a chain of sorted-superset
    versions plus the int32 step remaps between them."""

    __slots__ = ("key", "entry_id", "lock", "versions", "steps",
                 "_composed")

    def __init__(self, key: tuple):
        self.key = key
        self.entry_id = hashlib.sha1(repr(key).encode()).hexdigest()[:16]
        self.lock = threading.Lock()
        self.versions: List[Dictionary] = []
        self.steps: List[np.ndarray] = []  # steps[i]: v_i codes -> v_{i+1}
        self._composed: Dict[Tuple[int, int], np.ndarray] = {}

    def compose(self, u: int, t: int) -> np.ndarray:
        """Composed remap: version-u codes -> version-t codes (u < t).
        Pure integer gathers over the recorded steps; cached."""
        r = self._composed.get((u, t))
        if r is None:
            r = self.steps[u]
            for i in range(u + 1, t):
                r = self.steps[i][r]
            self._composed[(u, t)] = r
        return r


class DictionaryRegistry:
    """Process-wide singleton (module-level ``REGISTRY``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, RegistryEntry]" = OrderedDict()
        self._by_id: Dict[str, RegistryEntry] = {}
        self._by_epoch: "OrderedDict[str, Dictionary]" = OrderedDict()
        self._remaps: "OrderedDict[Tuple[str, str], np.ndarray]" = \
            OrderedDict()
        self._unions: "OrderedDict[tuple, Dictionary]" = OrderedDict()

    # -- bookkeeping --------------------------------------------------------

    def _entry(self, key: tuple) -> RegistryEntry:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = self._entries[key] = RegistryEntry(key)
                self._by_id[e.entry_id] = e
                # bound the table set: a long-lived executor scanning
                # many datasets (file signatures mint fresh keys per
                # regeneration) must not pin stale version chains
                # forever. Evicted members degrade to pairwise remaps.
                while len(self._entries) > _MAX_ENTRIES:
                    _k, old = self._entries.popitem(last=False)
                    self._by_id.pop(old.entry_id, None)
            else:
                self._entries.move_to_end(key)
            return e

    def _intern_epoch(self, fp: str, d: Dictionary) -> Dictionary:
        """Exactly one live instance per content epoch (LRU-bounded);
        identity sharing is what turns downstream unify into a no-op."""
        with self._lock:
            cur = self._by_epoch.get(fp)
            if cur is not None:
                self._by_epoch.move_to_end(fp)
                return cur
            self._by_epoch[fp] = d
            while len(self._by_epoch) > _MAX_EPOCHS:
                self._by_epoch.popitem(last=False)
            return d

    def _stamp(self, d: Dictionary, entry: Optional[RegistryEntry],
               version: Optional[int], fp: str) -> Dictionary:
        if entry is not None:
            d._reg_entry_id = entry.entry_id
            d._reg_version = version
        d._reg_epoch = fp
        return self._intern_epoch(fp, d)

    # -- producer API -------------------------------------------------------

    def intern(self, key: tuple, values) -> Dictionary:
        """Sorted-unique ``values`` -> the shared Dictionary for this
        entry. Returns the current version when values are a subset of
        it (callers encode against the RETURNED dictionary's values);
        otherwise appends a superset union version and records the step
        remap. Registry off -> plain unstamped Dictionary."""
        sv = _as_str(values)
        if not enabled() or _nul_tails(values, sv):
            return Dictionary(values)
        entry = self._entry(key)
        with entry.lock:
            if not entry.versions:
                d = _with_str_cache(Dictionary(sv), sv)
                d = self._stamp(d, entry, 0, values_fingerprint(sv))
                entry.versions.append(d)
                return d
            cur = entry.versions[-1]
            cs = cur.values_str()
            if len(sv) == len(cs) and np.array_equal(sv, cs):
                return cur
            fp = values_fingerprint(sv)
            known = self._by_epoch.get(fp)
            if known is not None:  # an older version / adopted twin
                return known
            union = np.unique(np.concatenate([cs, sv])) if len(sv) else cs
            if len(union) == len(cs):  # subset: current covers it
                return cur
            if len(entry.versions) >= _MAX_VERSIONS:
                d = _with_str_cache(Dictionary(sv), sv)
                d._reg_epoch = fp
                return self._intern_epoch(fp, d)
            step = np.searchsorted(union, cs).astype(np.int32)
            nd = _with_str_cache(Dictionary(union), union)
            nd = self._stamp(nd, entry, len(entry.versions),
                             values_fingerprint(union))
            entry.steps.append(step)
            entry.versions.append(nd)
            return nd

    def lookup(self, key: tuple) -> Optional[Dictionary]:
        """Current version for an entry key, if any — lets a fresh
        source instance skip rebuilding values it already paid for."""
        if not enabled():
            return None
        with self._lock:
            e = self._entries.get(key)
        if e is None:
            return None
        with e.lock:
            return e.versions[-1] if e.versions else None

    # -- cross-process stamps (Arrow IPC metadata, AOT output protos) ------

    def stamp_of(self, d: Optional[Dictionary]) -> Optional[str]:
        if d is None or not enabled() or d._reg_epoch is None:
            return None
        if d._reg_entry_id is None:
            # entry-less registered dictionaries (unify unions, plain
            # adoptions) still ship their epoch: resolution is by
            # epoch, so readers get the same 3.4us fast path
            return f"-:-:{d._reg_epoch}"
        return f"{d._reg_entry_id}:{d._reg_version}:{d._reg_epoch}"

    def resolve(self, stamp: Optional[str]) -> Optional[Dictionary]:
        """Stamp -> the live in-process instance, or None (caller falls
        back to the shipped values). Resolution is BY CONTENT EPOCH, so
        a stale or foreign stamp can never alias a different value set."""
        if not stamp or not enabled():
            return None
        epoch = stamp.rsplit(":", 1)[-1]
        with self._lock:
            d = self._by_epoch.get(epoch)
            if d is not None:
                self._by_epoch.move_to_end(epoch)
            return d

    def adopt(self, stamp: Optional[str], values) -> Dictionary:
        """Values received from another process (shuffle read, loaded
        AOT artifact) -> ONE shared instance per content epoch. The
        stamp's epoch is verified against the actual values before any
        entry identity is trusted. Repeat adoptions of known content
        return the interned instance BEFORE building a Dictionary (the
        value-index construction dominates adoption cost)."""
        sv = _as_str(values)
        lens = _obj_lens(values)
        if not enabled() or (len(sv) and not np.array_equal(
                lens, np.char.str_len(sv))):
            return Dictionary(values)
        fp = values_fingerprint(sv, lens)
        with self._lock:
            cur = self._by_epoch.get(fp)
            if cur is not None:
                self._by_epoch.move_to_end(fp)
                return cur
        d = _with_str_cache(Dictionary(sv), sv)
        if stamp:
            parts = stamp.split(":")
            if len(parts) == 3 and parts[2] == fp:
                d._reg_entry_id = parts[0]
                try:
                    d._reg_version = int(parts[1])
                except ValueError:
                    d._reg_entry_id = None
        d._reg_epoch = fp
        return self._intern_epoch(fp, d)

    # -- remap / unify ------------------------------------------------------

    def _chain_remap(self, src: Dictionary, dst: Dictionary
                     ) -> Optional[np.ndarray]:
        """Same-entry fast path: pure integer composition (or inverse).
        None when not on one chain OR when src is dst-coded already."""
        eid = src._reg_entry_id
        if eid is None or eid != dst._reg_entry_id:
            return None
        u, t = src._reg_version, dst._reg_version
        if u is None or t is None or u == t:
            return None
        with self._lock:
            entry = self._by_id.get(eid)
        if entry is None:
            return None
        with entry.lock:
            if max(u, t) >= len(entry.versions) or \
                    entry.versions[u] is not src or \
                    entry.versions[t] is not dst:
                return None  # adopted twins without a local chain
            if u < t:
                return entry.compose(u, t)
            fwd = entry.compose(t, u)  # dst codes -> src codes
        inv = np.full(len(src), -1, np.int32)
        inv[fwd] = np.arange(len(dst), dtype=np.int32)
        return inv

    def remap_between(self, src: Dictionary, dst: Dictionary
                      ) -> Optional[np.ndarray]:
        """int32 table: src codes -> dst codes (-1 where the value is
        absent from dst). None means the codings are identical (no
        remap needed). Built once per (content, content) pair —
        integer composition within an entry, one C-level sorted search
        across entries — and cached process-wide."""
        if src is dst:
            return None
        if not enabled():
            return _searchsorted_remap(src.values_str(), dst.values_str())
        if not (_str_view_exact(src) and _str_view_exact(dst)):
            # trailing-NUL values: the str views are lossy. The legacy
            # join remap was str-view-based too, so this matches the
            # pre-registry semantics exactly — but such pairs must not
            # enter the content-keyed cache (their fingerprints carry
            # the object-length plane, their views do not)
            return _searchsorted_remap(src.values_str(), dst.values_str())
        sfp, dfp = fingerprint(src), fingerprint(dst)
        if sfp == dfp:
            return None
        key = (sfp, dfp)
        with self._lock:
            r = self._remaps.get(key)
            if r is not None:
                self._remaps.move_to_end(key)
                return r
        r = self._chain_remap(src, dst)
        if r is None:
            r = _searchsorted_remap(src.values_str(), dst.values_str())
        with self._lock:
            self._remaps[key] = r
            while len(self._remaps) > _MAX_REMAPS:
                self._remaps.popitem(last=False)
        return r

    def unify(self, dicts: Sequence[Optional[Dictionary]]
              ) -> Tuple[Optional[Dictionary], List[Optional[np.ndarray]]]:
        """Shared target dictionary for a set of batches' dictionaries +
        per-input int32 remap (None = codes already valid in the
        target). Empty/None inputs pass codes through unchanged, like
        the legacy union code did. Never returns -1s: the target always
        covers every input."""
        present = [d for d in dicts if d is not None and len(d)]
        if not present:
            return next((d for d in dicts if d is not None), None), \
                [None] * len(dicts)
        if not enabled() or not all(_str_view_exact(d) for d in present):
            # registry off, or a member carries trailing-NUL values the
            # str views cannot represent: the object-array union is the
            # only lossless path (and what the pre-registry sites did)
            return self._legacy_union(dicts)
        # one distinct content -> that instance, no remaps at all
        fps = [fingerprint(d) for d in present]
        first = present[0]
        if all(fp == fps[0] for fp in fps):
            return first, [None] * len(dicts)
        # one entry -> the max version present covers every member —
        # but only trust it when the remaps prove it: an adopted twin
        # stamped by a sibling process whose chain diverged from ours
        # can carry a higher version WITHOUT being a superset, and a
        # -1 in a unify remap would clip to code 0 downstream
        # (silently wrong values). Any miss falls through to the union.
        eids = {d._reg_entry_id for d in present}
        if len(eids) == 1 and None not in eids:
            target = max(present,
                         key=lambda d: d._reg_version
                         if d._reg_version is not None else -1)
            if target._reg_version is not None:
                remaps = self._remaps_to(dicts, target)
                if all(r is None or (r >= 0).all() for r in remaps):
                    return target, remaps
        # cross-entry / unstamped: cached union keyed by the member set
        ukey = tuple(sorted(set(fps)))
        with self._lock:
            target = self._unions.get(ukey)
            if target is not None:
                self._unions.move_to_end(ukey)
        if target is None:
            union = np.unique(np.concatenate(
                [d.values_str() for d in present]))
            target = _with_str_cache(Dictionary(union), union)
            target = self._stamp(target, None, None,
                                 values_fingerprint(union))
            with self._lock:
                self._unions[ukey] = target
                while len(self._unions) > _MAX_UNIONS:
                    self._unions.popitem(last=False)
        return target, self._remaps_to(dicts, target)

    def _remaps_to(self, dicts, target) -> List[Optional[np.ndarray]]:
        return [None if (d is None or len(d) == 0 or d is target)
                else self.remap_between(d, target) for d in dicts]

    def _legacy_union(self, dicts):
        """The pre-registry behavior, verbatim semantics: sorted union
        over OBJECT arrays + per-member searchsorted remaps (the
        ``BALLISTA_DICT_REGISTRY=off`` escape hatch and the
        determinism-sweep control)."""
        union = np.unique(np.concatenate(
            [np.asarray(d.values, dtype=object) for d in dicts
             if d is not None and len(d)]
        ))
        union_str = union.astype(str)
        out: List[Optional[np.ndarray]] = []
        for d in dicts:
            if d is None or len(d) == 0:
                out.append(None)
                continue
            out.append(np.searchsorted(
                union_str, d.values_str()).astype(np.int32))
        return Dictionary(union), out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "versions": sum(len(e.versions)
                                for e in self._entries.values()),
                "epochs": len(self._by_epoch),
                "remaps": len(self._remaps),
                "unions": len(self._unions),
            }


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _as_str(values) -> np.ndarray:
    arr = np.asarray(values)
    if arr.dtype.kind == "U":
        return arr
    return arr.astype(str)


def _with_str_cache(d: Dictionary, sv: np.ndarray) -> Dictionary:
    from .columnar import _STR_CACHE_CAP_BYTES

    if sv.nbytes <= _STR_CACHE_CAP_BYTES:  # same bound values_str() uses
        d._cache_str_view(sv)
    d._str_exact = True  # registry members passed the NUL-tail guard
    return d


def _searchsorted_remap(sv: np.ndarray, dv: np.ndarray) -> np.ndarray:
    """src values -> positions in dst values (-1 where absent); one
    C-level sorted search over the cached str views."""
    if len(dv) == 0:
        return np.full(max(len(sv), 1), -1, np.int32)
    idx = np.searchsorted(dv, sv)
    idx_c = np.minimum(idx, len(dv) - 1)
    ok = dv[idx_c] == sv
    return np.where(ok, idx_c, -1).astype(np.int32)


REGISTRY = DictionaryRegistry()


# convenience wrappers (call-site ergonomics; see the class docstrings)

def intern(key: tuple, values) -> Dictionary:
    return REGISTRY.intern(key, values)


def unify(dicts) -> Tuple[Optional[Dictionary], List[Optional[np.ndarray]]]:
    return REGISTRY.unify(dicts)


def remap_between(src: Dictionary, dst: Dictionary) -> Optional[np.ndarray]:
    return REGISTRY.remap_between(src, dst)


def unify_parts(
    parts: List[Tuple[np.ndarray, Union[Dictionary, np.ndarray]]]
) -> Tuple[Dictionary, List[np.ndarray]]:
    """Shuffle-read variant: [(codes, Dictionary-or-raw-values)] ->
    (target, remapped codes per part). Raw value arrays (legacy wire
    format) are adopted first so equal producers still collapse to one
    instance. Registry off restores the pre-registry code verbatim:
    ONE union Dictionary, raw arrays for the parts (no per-part
    value-index construction)."""
    if enabled():
        dicts: List[Optional[Dictionary]] = [
            dv if isinstance(dv, Dictionary) else REGISTRY.adopt(None, dv)
            for _codes, dv in parts]
        target, remaps = REGISTRY.unify(dicts)
        if target is None:
            target = Dictionary([])
        out_codes = []
        for (codes, _dv), remap in zip(parts, remaps):
            if remap is None:
                out_codes.append(codes)
            else:
                out_codes.append(remap[codes].astype(np.int32))
        return target, out_codes
    vals = [dv.values if isinstance(dv, Dictionary)
            else np.asarray(dv, dtype=object) for _codes, dv in parts]
    union = np.unique(np.concatenate(vals)) if vals \
        else np.asarray([], object)
    union_str = union.astype(str)
    out_codes = []
    for (codes, _dv), v in zip(parts, vals):
        if len(v) == 0:
            out_codes.append(codes)
            continue
        remap = np.searchsorted(union_str, v.astype(str))
        out_codes.append(remap[codes].astype(np.int32))
    return Dictionary(union), out_codes
