"""Columnar batch substrate: the unit of data flow between operators.

The reference engine flows Arrow ``RecordBatch`` values between DataFusion
operators and serializes them via Arrow IPC (reference:
rust/core/src/utils.rs:49-84, rust/core/src/memory_stream.rs:29-93). On TPU
the equivalent is a struct-of-arrays batch of *fixed capacity* device buffers
so every kernel sees static shapes:

- each column is a dense device array of length ``capacity`` (padded);
- a boolean ``selection`` mask says which physical rows are live — filters
  only AND into this mask, never compact on device;
- string columns are dictionary codes (int32) + a host-side interned
  ``Dictionary``;
- a batch is a registered JAX pytree, so whole operator pipelines jit/fuse
  into a single XLA program over its leaves.

Compaction (dropping dead rows) happens only at host boundaries (collect,
shuffle spill), where numpy boolean indexing is cheap.
"""

from __future__ import annotations

import itertools
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .compile import bucket_capacity, governed
from .datatypes import DataType, Field, Schema, Utf8
from .errors import ExecutionError, SchemaError

# Default physical batch capacity (rows). Power of two keeps XLA tilings happy.
DEFAULT_BATCH_CAPACITY = 1 << 20

# Dictionary.values_str() keeps its fixed-width str view only under this
# size — a comment-scale dictionary's view would pin hundreds of MB.
_STR_CACHE_CAP_BYTES = 256 << 20

# FNV-1a constants (stable_hashes)
_FNV_OFFSET = np.uint64(0xCBF29CE484222325)
_FNV_PRIME = np.uint64(0x100000001B3)


def round_capacity(n: int, minimum: int = 8) -> int:
    """Smallest power of two >= n (>= minimum).

    Power-of-two quantization balances shape reuse (every distinct
    capacity is a fresh XLA trace+compile) against padding waste (a
    coarser power-of-4 ladder was measured to DOUBLE warm execution time
    on TPC-H q18 at SF0.2 — padded rows still cost sort/scan bandwidth,
    and with the persistent compilation cache the compile side is already
    amortized)."""
    cap = minimum
    while cap < n:
        cap <<= 1
    return cap


_NARROW_LADDER = {
    np.dtype(np.int64): (np.int8, np.int16, np.int32),
    np.dtype(np.int32): (np.int8, np.int16),
}

_NARROW_WIRE: Optional[bool] = None


def _narrow_wire_enabled() -> bool:
    """Narrowing pays a host min/max pass per column; that's a win only
    when uploads cross a real device link (TPU), not on the CPU backend
    where jnp.asarray is a plain copy."""
    global _NARROW_WIRE
    if _NARROW_WIRE is None:
        env = os.environ.get("BALLISTA_NARROW_WIRE", "").lower()
        if env in ("on", "1", "true"):
            _NARROW_WIRE = True
        elif env in ("off", "0", "false"):
            _NARROW_WIRE = False
        else:
            _NARROW_WIRE = jax.default_backend() != "cpu"
    return _NARROW_WIRE


def _upload(arr: np.ndarray, want: np.dtype) -> jax.Array:
    """Host array -> device array of dtype ``want``, transferring the
    narrowest integer representation that holds the values and widening
    on device. Host->device bandwidth is the cold-query bottleneck
    (PCIe on a co-located host, far worse through a tunnel); TPC-H
    integer/decimal columns typically fit 1-2 bytes, so this cuts wire
    bytes ~3-4x for the cost of one fused device cast."""
    ladder = _NARROW_LADDER.get(arr.dtype)
    if ladder is None or arr.size == 0 or not _narrow_wire_enabled():
        return jnp.asarray(arr)
    lo = arr.min()
    hi = arr.max()
    for narrow in ladder:
        info = np.iinfo(narrow)
        if info.min <= lo and hi <= info.max:
            fn = governed(
                ("wire.widen", np.dtype(narrow).name, np.dtype(want).name),
                lambda _w=np.dtype(want): (lambda a: a.astype(_w)),
            )
            return fn(jnp.asarray(arr.astype(narrow)))
    return jnp.asarray(arr)


# ---------------------------------------------------------------------------
# Dictionary (host-side string table)
# ---------------------------------------------------------------------------


class Dictionary:
    """Interned host-side string table for a dictionary-encoded column.

    Identity-hashed: two scans of the same file share one instance, so it can
    ride in pytree aux-data without defeating jit caching.
    """

    __slots__ = ("values", "_index", "_tracked_bytes", "_aot_fp",
                 "_str_cache", "_hash_cache", "_str_exact",
                 "_reg_entry_id", "_reg_version", "_reg_epoch")

    def __init__(self, values: Sequence[str]):
        self.values: np.ndarray = np.asarray(list(values), dtype=object)
        self._index: Dict[str, int] = {v: i for i, v in enumerate(self.values)}
        # lazily-computed caches + dictionary-registry identity
        # (columnar_registry.py stamps entry/version/epoch on members)
        self._str_cache: Optional[np.ndarray] = None
        self._hash_cache: Optional[np.ndarray] = None
        self._str_exact: Optional[bool] = None
        self._reg_entry_id: Optional[str] = None
        self._reg_version: Optional[int] = None
        self._reg_epoch: Optional[str] = None
        # memory accounting (observability/memory.py): dictionaries are
        # the dominant host-resident string mass — ~pointer array +
        # index dict entry + string storage per value (estimate, not an
        # allocator truth; released in __del__)
        self._tracked_bytes = int(self.values.nbytes) + 120 * len(self.values)
        from .observability import memory as _obs_memory

        _obs_memory.record_host_bytes("dictionaries", self._tracked_bytes)

    def __del__(self):
        try:
            from .observability import memory as _obs_memory

            _obs_memory.release_host_bytes("dictionaries",
                                           self._tracked_bytes)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def __len__(self) -> int:
        return len(self.values)

    def code_of(self, s: str) -> int:
        """Code for string s, or -1 if absent (comparison can short-circuit)."""
        return self._index.get(s, -1)

    def lookup(self, codes: np.ndarray) -> np.ndarray:
        out = np.empty(len(codes), dtype=object)
        ok = (codes >= 0) & (codes < len(self.values))
        out[ok] = self.values[codes[ok]]
        out[~ok] = None
        return out

    @staticmethod
    def encode(strings: Sequence[str]) -> Tuple["Dictionary", np.ndarray]:
        uniq, codes = np.unique(np.asarray(strings, dtype=object), return_inverse=True)
        return Dictionary(uniq), codes.astype(np.int32)

    def content_fingerprint(self) -> str:
        """Hex digest of the dictionary CONTENT (not identity) — the
        fused-stage AOT cache keys compiled programs on it, because
        traced programs bake dictionary values as constants. Cached per
        instance (values are immutable by convention)."""
        fp = getattr(self, "_aot_fp", None)
        if fp is None:
            import hashlib

            h = hashlib.sha1()
            for v in self.values:
                b = str(v).encode("utf-8", "surrogatepass")
                # length-prefixed: a separator alone is ambiguous when
                # values can contain it (['a\x00','b'] vs ['a','\x00b'])
                h.update(str(len(b)).encode())
                h.update(b":")
                h.update(b)
            fp = self._aot_fp = h.hexdigest()
        return fp

    # -- cached views / search primitives ----------------------------------
    #
    # Every host-side string operation funnels through these so the
    # fixed-width str materialization and the per-value hash pass are
    # paid ONCE per immutable instance instead of once per call site
    # (join remap, concat/ipc unify and scan encode each used to
    # ``.astype(str)`` the same values on every invocation).

    def values_str(self) -> np.ndarray:
        """Fixed-width ``np.str_`` view of the (sorted) values, cached.
        Dictionaries past the cache cap recompute per call — the cached
        view for a multi-million-value comment dictionary would pin
        hundreds of MB of host RAM."""
        sv = self._str_cache
        if sv is None:
            sv = self.values.astype(str)
            if sv.nbytes <= _STR_CACHE_CAP_BYTES:
                self._cache_str_view(sv)
        return sv

    def _cache_str_view(self, sv: np.ndarray) -> None:
        """Pin a str view on the instance, keeping the 'dictionaries'
        host-memory plane honest (the view can be several times the
        object-string mass; __del__ releases the accumulated total)."""
        if self._str_cache is None:
            self._str_cache = sv
            self._track_extra(int(sv.nbytes))

    def _track_extra(self, nbytes: int) -> None:
        from .observability import memory as _obs_memory

        self._tracked_bytes += nbytes
        _obs_memory.record_host_bytes("dictionaries", nbytes)

    def positions_of(self, values) -> np.ndarray:
        """int32 code per value via one sorted search over the cached
        str view. Scan encode paths call this with values the
        dictionary was built FROM (presence guaranteed); absent values
        get the insertion position, exactly like the searchsorted
        calls this replaces."""
        vals = np.asarray(values)
        if vals.dtype.kind != "U":
            vals = vals.astype(str)
        return np.searchsorted(self.values_str(), vals).astype(np.int32)

    def code_range(self, s: str) -> Tuple[int, int]:
        """(left, right) insertion bounds of ``s`` in code space —
        string ordering predicates compile to code comparisons against
        these (kernels/expr_eval.py)."""
        sv = self.values_str()
        return (int(np.searchsorted(sv, s, side="left")),
                int(np.searchsorted(sv, s, side="right")))

    def stable_hashes(self) -> np.ndarray:
        """int64 FNV-1a hash per dictionary value — STABLE across processes
        and dictionary encodings, so hash partitioning of utf8 columns
        agrees between independent producers (codes are producer-local;
        string hashes are not).

        Vectorized: the hash recurrence runs per BYTE POSITION over all
        values at once (a max-width pass of numpy uint64 ops) instead
        of a per-value per-byte Python loop — this sits on the shuffle
        partitioning path. Cached per immutable instance. Values the
        fixed-width str view cannot represent (trailing U+0000) hash
        through the reference scalar loop so placement never moves."""
        cached = self._hash_cache
        if cached is not None:
            return cached
        n = len(self.values)
        if n == 0:
            out = np.empty(0, dtype=np.int64)
            self._hash_cache = out
            return out
        sv = self.values_str()
        enc = np.char.encode(sv, "utf-8")
        width = enc.dtype.itemsize
        h = np.full(n, _FNV_OFFSET, dtype=np.uint64)
        if width:
            mat = enc.view(np.uint8).reshape(n, width)
            nz = mat != 0
            lengths = np.where(nz.any(axis=1),
                               width - np.argmax(nz[:, ::-1], axis=1), 0)
            for j in range(width):
                active = j < lengths
                h = np.where(active, (h ^ mat[:, j]) * _FNV_PRIME, h)
        out = h.astype(np.int64)
        # rows whose true length the str view lost (trailing NULs)
        lens = np.fromiter((len(str(v)) for v in self.values),
                           dtype=np.int64, count=n)
        mangled = np.nonzero(lens != np.char.str_len(sv))[0]
        for i in mangled:
            hh = 0xCBF29CE484222325
            for b in str(self.values[i]).encode("utf-8"):
                hh = ((hh ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
            out[i] = np.int64(np.uint64(hh))
        self._hash_cache = out
        self._track_extra(int(out.nbytes))
        return out

    @staticmethod
    def canonicalize(values: Sequence[str]) -> Tuple["Dictionary", np.ndarray]:
        """Sorted-unique dictionary + old-code -> new-code remap table.

        Comparison kernels assume dictionaries are sorted and duplicate-free;
        any derived dictionary (upper/substr/...) must pass through here.
        """
        uniq, remap = np.unique(np.asarray(values, dtype=object), return_inverse=True)
        return Dictionary(uniq), remap.astype(np.int32)

    def __hash__(self) -> int:
        return id(self)

    def __eq__(self, other) -> bool:
        return self is other

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Dictionary({len(self)} values)"


# ---------------------------------------------------------------------------
# Column
# ---------------------------------------------------------------------------


@dataclass
class Column:
    """One physical column: device values + optional validity + dtype."""

    values: jax.Array  # [capacity] device (or numpy pre-transfer)
    dtype: DataType
    validity: Optional[jax.Array] = None  # bool [capacity]; None = all valid
    dictionary: Optional[Dictionary] = None  # only for Utf8

    @property
    def capacity(self) -> int:
        return int(self.values.shape[0])

    def valid_mask(self) -> jax.Array:
        if self.validity is None:
            return jnp.ones((self.capacity,), dtype=jnp.bool_)
        return self.validity

    # -- host conversion ----------------------------------------------------

    def to_numpy_logical(self, row_mask: Optional[np.ndarray] = None) -> np.ndarray:
        """Materialize logical values on host (decodes dicts/decimals).

        SQL NULLs (validity False) become None for strings and NaN for
        numerics — integer columns with NULLs widen to float64, matching
        pandas conventions.
        """
        from .observability.tracing import trace_span

        with trace_span("device.block", site="column.to_numpy"):
            vals = np.asarray(self.values)
            invalid = (None if self.validity is None
                       else ~np.asarray(self.validity))
        if row_mask is not None:
            vals = vals[row_mask]
            if invalid is not None:
                invalid = invalid[row_mask]
        if self.dtype.kind == "utf8" and self.dictionary is None:
            raise ExecutionError("utf8 column without dictionary")
        return decode_physical_array(
            vals, self.dtype.kind, self.dtype.scale,
            self.dictionary.values if self.dictionary is not None else None,
            invalid,
        )


# ---------------------------------------------------------------------------
# ColumnBatch
# ---------------------------------------------------------------------------


class ColumnBatch:
    """Fixed-capacity columnar batch; a JAX pytree.

    ``selection`` is the live-row mask (False for filtered-out rows AND for
    padding beyond the logical row count). ``num_rows`` is a traced i32 scalar
    with the count of live rows (kept consistent with ``selection`` by
    constructors; operators that filter must update both).
    """

    # _transient: donation eligibility (cache/donation.py) — True only
    # when the CREATOR guarantees single consumption; never flattened
    # into the pytree, consumed at most once by a donating call site
    __slots__ = ("schema", "columns", "selection", "num_rows",
                 "_transient")

    def __init__(
        self,
        schema: Schema,
        columns: Sequence[Column],
        selection: jax.Array,
        num_rows: jax.Array,
    ):
        self.schema = schema
        self.columns: Tuple[Column, ...] = tuple(columns)
        self.selection = selection
        self.num_rows = num_rows
        self._transient = False
        if len(self.columns) != len(schema):
            raise SchemaError(
                f"schema has {len(schema)} fields but {len(self.columns)} columns given"
            )

    # -- constructors -------------------------------------------------------

    @staticmethod
    def from_numpy(
        schema: Schema,
        arrays: Dict[str, np.ndarray],
        dictionaries: Optional[Dict[str, Dictionary]] = None,
        capacity: Optional[int] = None,
        validity: Optional[Dict[str, np.ndarray]] = None,
    ) -> "ColumnBatch":
        """Build a batch from host arrays of physical values, padding to
        capacity. ``validity`` maps column name -> bool array of length n
        (True = valid); columns absent from it are all-valid."""
        dictionaries = dictionaries or {}
        validity = validity or {}
        n = None
        for name, arr in arrays.items():
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise SchemaError(f"column {name} length {len(arr)} != {n}")
        n = n or 0
        # default capacities land on the canonical bucket ladder so
        # every batch-entry boundary produces ladder shapes (explicit
        # capacities — internal small result batches — stay exact)
        cap = capacity or bucket_capacity(n)
        if cap < n:
            raise ExecutionError(f"capacity {cap} < rows {n}")
        cols: List[Column] = []
        for f in schema.fields:
            if f.name not in arrays:
                raise SchemaError(f"missing column {f.name}")
            arr = np.asarray(arrays[f.name])
            want = f.dtype.device_dtype()
            if arr.dtype != want:
                arr = arr.astype(want)
            if n < cap:
                # trailing dims (fixed-size-list element axis) pad along
                # the row axis only
                pad = np.zeros((cap - n,) + arr.shape[1:], dtype=want)
                arr = np.concatenate([arr, pad])
            va = validity.get(f.name)
            if va is not None:
                va = np.asarray(va, dtype=np.bool_)
                if len(va) < cap:  # padding rows are not valid
                    va = np.concatenate(
                        [va, np.zeros(cap - len(va), dtype=np.bool_)]
                    )
                va = _upload(va, np.bool_)
            cols.append(
                Column(_upload(arr, want), f.dtype, va,
                       dictionaries.get(f.name))
            )
        sel = np.zeros(cap, dtype=np.bool_)
        sel[:n] = True
        return ColumnBatch(
            schema, cols, jnp.asarray(sel), jnp.asarray(np.int32(n))
        )

    @staticmethod
    def from_pydict(
        schema: Schema, data: Dict[str, Sequence], capacity: Optional[int] = None
    ) -> "ColumnBatch":
        """Build from logical Python values (strings, floats for decimals...)."""
        arrays: Dict[str, np.ndarray] = {}
        dicts: Dict[str, Dictionary] = {}
        for f in schema.fields:
            vals = data[f.name]
            if f.dtype.kind == "utf8":
                d, codes = Dictionary.encode([str(v) for v in vals])
                dicts[f.name] = d
                arrays[f.name] = codes
            elif f.dtype.kind == "decimal":
                arrays[f.name] = decimal_to_scaled(
                    [float(v) for v in vals], f.dtype.scale
                )
            else:
                arrays[f.name] = np.asarray(vals, dtype=f.dtype.device_dtype())
        return ColumnBatch.from_numpy(schema, arrays, dicts, capacity)

    # -- info ---------------------------------------------------------------

    @property
    def capacity(self) -> int:
        return int(self.selection.shape[0])

    def column(self, name: str) -> Column:
        return self.columns[self.schema.index_of(name)]

    def with_columns(self, schema: Schema, columns: Sequence[Column]) -> "ColumnBatch":
        return ColumnBatch(schema, columns, self.selection, self.num_rows)

    def with_selection(
        self, selection: jax.Array, num_rows: Optional[jax.Array] = None
    ) -> "ColumnBatch":
        if num_rows is None:
            num_rows = jnp.sum(selection).astype(jnp.int32)
        return ColumnBatch(self.schema, self.columns, selection, num_rows)

    # -- host materialization ----------------------------------------------

    def to_pydict(self) -> Dict[str, np.ndarray]:
        """Compact to host: logical values of live rows only.

        All device buffers are fetched in ONE ``jax.device_get`` (async
        copies issued together, then awaited) — per-column ``np.asarray``
        would serialize a device->host round-trip per array, which
        dominates query latency when the accelerator is remote."""
        from .observability.tracing import trace_span

        with trace_span("device.block", site="batch.to_pydict",
                        columns=len(self.columns)):
            sel, vals, valids = jax.device_get((
                self.selection,
                [c.values for c in self.columns],
                [c.validity for c in self.columns],
            ))
        mask = np.asarray(sel)
        out: Dict[str, np.ndarray] = {}
        for f, col, v, va in zip(self.schema.fields, self.columns, vals,
                                 valids):
            if f.dtype.kind == "utf8" and col.dictionary is None:
                raise ExecutionError("utf8 column without dictionary")
            invalid = None
            if va is not None:
                invalid = ~np.asarray(va)[mask]
            if f.dtype.kind == "list":
                out[f.name] = decode_list_rows(
                    np.asarray(v)[mask], f.dtype.element.kind,
                    f.dtype.element.scale, invalid,
                )
                continue
            out[f.name] = decode_physical_array(
                np.asarray(v)[mask], f.dtype.kind, f.dtype.scale,
                col.dictionary.values if col.dictionary is not None else None,
                invalid,
            )
        return out

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(self.to_pydict())

    def num_rows_host(self) -> int:
        return int(self.num_rows)

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return (
            f"ColumnBatch(cap={self.capacity}, fields={self.schema.names()})"
        )


# ---------------------------------------------------------------------------
# pytree registration: leaves = device arrays, aux = schema + dicts
# ---------------------------------------------------------------------------


def _flatten_batch(b: ColumnBatch):
    leaves = []
    col_meta = []
    for col in b.columns:
        leaves.append(col.values)
        has_validity = col.validity is not None
        if has_validity:
            leaves.append(col.validity)
        col_meta.append((col.dtype, has_validity, col.dictionary))
    leaves.append(b.selection)
    leaves.append(b.num_rows)
    return leaves, (b.schema, tuple(col_meta))


def _unflatten_batch(aux, leaves):
    schema, col_meta = aux
    leaves = list(leaves)
    it = iter(leaves)
    cols = []
    for dtype, has_validity, dictionary in col_meta:
        values = next(it)
        validity = next(it) if has_validity else None
        cols.append(Column(values, dtype, validity, dictionary))
    selection = next(it)
    num_rows = next(it)
    return ColumnBatch(schema, cols, selection, num_rows)


jax.tree_util.register_pytree_node(ColumnBatch, _flatten_batch, _unflatten_batch)


# ---------------------------------------------------------------------------
# Host-side helpers
# ---------------------------------------------------------------------------


def decimal_to_scaled(values, scale: int) -> np.ndarray:
    """float/str decimal values -> scaled int64 using HALF-UP (away from
    zero) rounding — the same rule as the native C++ parser, so results
    never depend on which scanner read the file."""
    v = np.asarray(values, dtype=np.float64) * (10 ** scale)
    return (np.sign(v) * np.floor(np.abs(v) + 0.5)).astype(np.int64)


def decode_physical_array(
    vals: np.ndarray,
    kind: str,
    scale: int = 0,
    dictionary_values: Optional[np.ndarray] = None,
    null_mask: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Physical array -> logical host values, applying SQL NULL conventions
    (None for strings, NaT for dates, NaN for numerics — integers with
    NULLs widen to float64). Shared by local collect and the distributed
    result-fetch path, so the decode rules cannot drift."""
    has_nulls = null_mask is not None and bool(np.asarray(null_mask).any())
    if kind == "utf8":
        if dictionary_values is None:
            raise ExecutionError("utf8 decode requires a dictionary")
        if isinstance(dictionary_values, Dictionary):
            # IPC readers hand back registry-resolved Dictionary
            # objects; decode sees their value array either way
            dictionary_values = dictionary_values.values
        dv = np.asarray(dictionary_values, dtype=object)
        codes = np.asarray(vals).astype(np.int64)
        ok = (codes >= 0) & (codes < len(dv))
        out = np.empty(len(codes), dtype=object)
        out[ok] = dv[codes[ok]]
        out[~ok] = None
        if has_nulls:
            out[null_mask] = None
        return out
    if kind == "date32":
        out = np.asarray(vals).astype("datetime64[D]")
        if has_nulls:
            out[null_mask] = np.datetime64("NaT")
        return out
    if kind == "timestamp_ns":
        out = np.asarray(vals).astype(np.int64).astype("datetime64[ns]")
        if has_nulls:
            out[null_mask] = np.datetime64("NaT")
        return out
    if kind == "decimal":
        out = np.asarray(vals).astype(np.float64) / (10.0 ** scale)
    elif kind in ("float32", "float64"):
        out = np.asarray(vals).astype(np.float64)
    elif has_nulls:
        out = np.asarray(vals).astype(np.float64)
    else:
        return np.asarray(vals)
    if has_nulls:
        out[null_mask] = np.nan
    return out


def decode_list_rows(
    vals2d: np.ndarray,
    element_kind: str,
    element_scale: int,
    null_mask: Optional[np.ndarray] = None,
) -> np.ndarray:
    """(rows, length) physical list values -> object array of per-row 1-D
    logical vectors (None for NULL rows). Shared by local collect and the
    distributed result-fetch path, like ``decode_physical_array``."""
    arr = np.asarray(vals2d)
    flat = decode_physical_array(arr.reshape(-1), element_kind,
                                 element_scale, None, None)
    rows = np.asarray(flat).reshape(arr.shape)
    cell = np.empty(arr.shape[0], dtype=object)
    for i in range(arr.shape[0]):
        cell[i] = (None if null_mask is not None and null_mask[i]
                   else rows[i])
    return cell


def empty_batch(schema) -> "ColumnBatch":
    """Zero-row batch with the given schema (utf8 columns get empty
    dictionaries so IPC encoding works)."""
    return ColumnBatch.from_numpy(
        schema,
        {f.name: np.zeros(0, f.dtype.device_dtype()) for f in schema.fields},
        {f.name: Dictionary([]) for f in schema.fields
         if f.dtype.kind == "utf8"},
        capacity=8,
    )


def concat_pydicts(parts: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    if not parts:
        return {}
    keys = parts[0].keys()
    return {k: np.concatenate([p[k] for p in parts]) for k in keys}
