"""Query lifecycle control plane: cooperative cancellation tokens.

The reference engine can *detect* failures but cannot *stop* work: a
client ``job.timeout`` only stops waiting while the job keeps burning
executor slots, and killing an executor abandons tasks mid-flight. This
module is the shared primitive both execution paths use to stop work
cleanly:

- :class:`CancelToken` — a one-shot flag with a reason. Set by the
  scheduler's ``CancelJob`` path (piggybacked on ``PollWorkResult``),
  a standalone ``ctx.cancel()``, the slow-query killer, a server-side
  deadline, or executor drain.
- :func:`bind_token` / :func:`check_cancel` — the token rides a
  thread-local so deep batch loops (scan pulls, shuffle reads, the
  executor task runner) can check it without plumbing a parameter
  through every operator. A check costs one thread-local read when no
  token is bound — the hot path stays clean (< 5% warm-q1 gate).

Cancellation is COOPERATIVE: work stops at batch/partition boundaries,
never mid-kernel. A fired token raises :class:`QueryCancelled`
(re-exported from :mod:`ballista_tpu.errors`), which the executor task
runner and the standalone collect treat as a terminal ``cancelled``
outcome, not a failure.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

from .errors import QueryCancelled


class CancelToken:
    """One-shot cooperative cancellation flag.

    ``cancel(reason)`` is idempotent (the FIRST reason wins — a drain
    cancelling an already job-cancelled task must not relabel it);
    ``check()`` raises :class:`QueryCancelled` once fired. ``wait()``
    lets watchdogs block on it."""

    __slots__ = ("_event", "reason", "job_id")

    def __init__(self, job_id: Optional[str] = None):
        self._event = threading.Event()
        self.reason: Optional[str] = None
        self.job_id = job_id

    def cancel(self, reason: str = "client") -> bool:
        """Fire the token; returns True when this call was the one that
        fired it."""
        if self._event.is_set():
            return False
        # benign race: two concurrent first-cancels may both write the
        # reason; either label is truthful and the event fires once
        self.reason = reason
        self._event.set()
        return True

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def check(self) -> None:
        if self._event.is_set():
            raise QueryCancelled(self.reason or "unknown",
                                 job_id=self.job_id)


_tls = threading.local()


def current_token() -> Optional[CancelToken]:
    """The token bound to the calling thread, or None."""
    return getattr(_tls, "token", None)


@contextmanager
def bind_token(token: Optional[CancelToken]):
    """Bind ``token`` as the calling thread's current cancel token for
    the duration of the block (None = explicitly unbound). Nested binds
    restore the outer token on exit."""
    prev = getattr(_tls, "token", None)
    _tls.token = token
    try:
        yield token
    finally:
        _tls.token = prev


def check_cancel() -> None:
    """Raise :class:`QueryCancelled` when the thread's bound token has
    fired; no-op (one thread-local read) otherwise. Sprinkled at batch
    and partition boundaries: scan pulls, shuffle-group reads, the
    executor task runner's root loop, and the standalone collect."""
    token = getattr(_tls, "token", None)
    if token is not None and token._event.is_set():
        raise QueryCancelled(token.reason or "unknown",
                             job_id=token.job_id)


@contextmanager
def slow_query_killer(token: CancelToken):
    """The KILL variant of ``watch_slow_query``: when
    ``BALLISTA_SLOW_QUERY_KILL_SECS`` is set, arm a watchdog that fires
    ``token`` (reason ``slow-query-kill``) once the wrapped block has
    run that long — the standalone face of the scheduler's reap-pass
    kill. The query then stops at its next batch boundary and surfaces
    as terminal ``cancelled`` in ``system.queries``. No-op (and no
    timer thread) when the knob is unset."""
    from .observability.health import slow_query_kill_secs

    kill = slow_query_kill_secs()
    if kill is None:
        yield
        return
    timer = threading.Timer(kill, token.cancel,
                            args=("slow-query-kill",))
    timer.daemon = True
    timer.start()
    try:
        yield
    finally:
        timer.cancel()
